"""Dense vectorized NFA — the TPU hot path.

Replaces the reference's per-event pattern processing
(StreamPreStateProcessor.processAndReturn:364 — O(pending × states) Java
object walks under a ReentrantLock per event) with a bit-parallel,
jit-compiled step over **micro-batches of events across partitions**:

- per-partition NFA state lives in HBM as dense arrays:
  ``active`` (uint32 bitmask, one bit per chain node), ``first_ts``
  (within-window anchors), ``counts`` (Kleene counters), ``regs``
  (captured attribute registers used by cross-state filters/selects);
- one step gathers the state rows for the batch's partitions, unrolls
  the node chain in reverse (so an event advances at most one node, the
  staged-update semantics of the host engine), evaluates all node
  filters vectorized, and scatters the state back;
- cost is O(batch × states × regs) independent of the partition count —
  1M+ partitions are just HBM rows;
- multi-chip: the partition axis is sharded over a ``jax.sharding.Mesh``
  (``shard()``); each shard owns its keys so the step needs no
  cross-device collectives, and emitted matches ride an all-gather only
  when the caller asks for global emission.

Dense-mode semantics (documented subset of the host engine,
ops/nfa.py — the planner falls back to the host engine otherwise):
 - linear chains (stream + count nodes; logical and/or as one node),
   <= 32 nodes; patterns and strict-continuity sequences (non-matching
   events kill pending sequence instances pre-advance, start node
   stays armed);
 - absent states (`not X for t`, `A and not B [for t]`) at positions
   >= 1 of PATTERN chains: entry arms a per-instance deadline
   register, a matching absent-stream event kills the instance, and a
   jitted timer step (make_time_step) advances/emits deadline-passed
   instances — the dense analog of the reference's scheduler-armed
   AbsentStreamPreStateProcessor.  Leading absent (deadline from app
   start), absent in sequences, and same-stream and-not stay on the
   host engine;
 - **instance axis**: up to ``n_instances`` simultaneous pending
   instances per (partition, node) — overlapping `every` arms advance
   independently, matching the reference's pendingStateEventList.
   When every slot of a successor node is occupied, the advancing
   instance is DROPPED (oldest-pending-wins) and the partition's
   ``overflow`` counter increments — the explicit-capacity analog of
   the reference's unbounded list (size the axis with
   ``@app:execution('tpu', instances='N')``).  Sequences force one
   instance (the reference keeps a single pending per state);
 - count ({m:n}) nodes: exact counts move at min==max; open-ended
   counts ({m:ANY} / min<max) stay dually pending, cloning per
   successor-matching event through the via-path with clone-time
   registers (exactly the reference's pre-capture _try_enter — [last]
   refs see the captures BEFORE the cloning event, on both engines);
   an open count's successor must be a plain stream node (fall back
   otherwise);
 - capture references limited to first (``ref.attr``/``ref[0]``) and
   last (``ref[last]``) events of a count state;
 - numeric attributes only (string keys are interned to partition ids
   host-side before the step).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core.exceptions import (
    SiddhiAppCreationError,
    SiddhiAppRuntimeError,
)
from siddhi_tpu.ops.nfa import ANY, NFABuilder, Node, PatternScope, Spec
from siddhi_tpu.planner.expr import (
    CompiledExpression,
    ExpressionCompiler,
    N_KEY,
    TS_KEY,
)
from siddhi_tpu.query_api import AttrType, StateInputStream, Variable
from siddhi_tpu.query_api.definition import StreamDefinition


@dataclass
class RegSlot:
    ref: str
    attr: str
    last: bool  # False: first captured event; True: last captured event
    index: int
    integer: bool = False  # True: hi/lo int32 pair in the iregs bank


# integer (INT/LONG) values ride hi/lo int32 pairs: hi = v >> 32 (signed),
# lo = (v & 0xffffffff) - 2^31 (bias-signed, so SIGNED int32 comparison of
# lo equals UNSIGNED comparison of the raw low word) — (hi, lo)
# lexicographic signed order == int64 signed order, bit-exact at any
# magnitude, no 64-bit device lanes needed (TPUs have none)
_INT_TYPES = (AttrType.INT, AttrType.LONG)


def _i64_split_const(v: int) -> Tuple[np.int32, np.int32]:
    v = int(v)
    return (np.int32(v >> 32), np.int32((v & 0xFFFFFFFF) - 2**31))


def _i64_join(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    return ((hi.astype(np.int64) << 32)
            | (lo.astype(np.int64) + 2**31).astype(np.uint32))


class DenseScope(PatternScope):
    """Filter/selector scope resolving captured refs to register slots."""

    def __init__(self, ref_defs, stream_to_ref, cand_def, alloc: "RegAllocator",
                 cand_ref=None):
        super().__init__(ref_defs, stream_to_ref, cand_def, cand_ref=cand_ref)
        self.alloc = alloc

    def resolve(self, var: Variable):
        key, t = super().resolve(var)
        if key.startswith("__cand."):
            return key, t
        # captured reference -> register slot key
        ref, idx, attr, _t = self.used_captures[key]
        integer = t in _INT_TYPES
        if idx in (None, 0):
            slot = self.alloc.slot(ref, attr, last=False, integer=integer)
        elif idx == -1:
            slot = self.alloc.slot(ref, attr, last=True, integer=integer)
        else:
            raise SiddhiAppCreationError(
                f"dense NFA supports only first/[0]/[last] capture refs, got index {idx}"
            )
        prefix = "__ireg" if integer else "__reg"
        return f"{prefix}.{slot.index}", t


class RegAllocator:
    """Two banks: float32 value slots (``regs``) and integer hi/lo pair
    slots (``iregs``) — indexed independently."""

    def __init__(self):
        self.slots: Dict[Tuple[str, str, bool], RegSlot] = {}
        self._n_float = 0
        self._n_int = 0

    def slot(self, ref: str, attr: str, last: bool,
             integer: bool = False) -> RegSlot:
        k = (ref, attr, last)
        if k not in self.slots:
            idx = self._n_int if integer else self._n_float
            self.slots[k] = RegSlot(ref, attr, last, idx, integer)
            if integer:
                self._n_int += 1
            else:
                self._n_float += 1
        return self.slots[k]

    @property
    def n(self) -> int:
        return self._n_float

    @property
    def n_int(self) -> int:
        return self._n_int


class DenseExprCompiler(ExpressionCompiler):
    """Dense-filter compiler: integer (INT/LONG) leaves ride hi/lo int32
    pairs (``<key>|hi`` / ``<key>|lo`` env lanes); comparisons between
    integer leaves compile to bit-exact paired compares at ANY
    magnitude.  Every other integer use (arithmetic, function args)
    raises, sending the query to the host engine — the reference is
    per-type exact and so are we, just along a narrower surface.

    ``PAIR_TYPES`` is the attribute-type set riding pair lanes; the
    device query engine subclasses with LONG-only (its INT attributes
    keep plain int32 lanes)."""

    PAIR_TYPES = _INT_TYPES

    def _i64_parts(self, e, var_only=False):
        """Integer leaf -> (hi_fn, lo_fn) env readers, else None.
        ``var_only`` skips constants (used to decide whether the pair
        path applies at all: an integer LITERAL against a float lane —
        ``[v > 100]`` — stays on the ordinary float compare)."""
        from siddhi_tpu.query_api import Constant

        if (not var_only and isinstance(e, Constant)
                and e.type in _INT_TYPES and e.value is not None):
            hi, lo = _i64_split_const(e.value)
            return (lambda env: hi), (lambda env: lo)
        if isinstance(e, Variable):
            key, t = self.scope.resolve(e)
            if t in self.PAIR_TYPES:
                return ((lambda env: env[key + "|hi"]),
                        (lambda env: env[key + "|lo"]))
        return None

    def _c_CompareOp(self, e):
        # pair compares engage only when an integer VARIABLE lane is
        # involved; integer constants alone coerce fine on float lanes
        if (self._i64_parts(e.left, var_only=True) is None
                and self._i64_parts(e.right, var_only=True) is None):
            return super()._c_CompareOp(e)
        lp, rp = self._i64_parts(e.left), self._i64_parts(e.right)
        if lp is None or rp is None:
            raise SiddhiAppCreationError(
                "dense NFA: comparison mixes a 64-bit integer lane with a "
                "non-integer operand — host engine used")
        lhi, llo = lp
        rhi, rlo = rp
        op = e.op

        def fn(env):
            a_hi, a_lo = lhi(env), llo(env)
            b_hi, b_lo = rhi(env), rlo(env)
            if op == "==":
                return (a_hi == b_hi) & (a_lo == b_lo)
            if op == "!=":
                return (a_hi != b_hi) | (a_lo != b_lo)
            if op == ">":
                return (a_hi > b_hi) | ((a_hi == b_hi) & (a_lo > b_lo))
            if op == ">=":
                return (a_hi > b_hi) | ((a_hi == b_hi) & (a_lo >= b_lo))
            if op == "<":
                return (a_hi < b_hi) | ((a_hi == b_hi) & (a_lo < b_lo))
            return (a_hi < b_hi) | ((a_hi == b_hi) & (a_lo <= b_lo))

        return CompiledExpression(fn, AttrType.BOOL)

    def _c_Variable(self, e):
        key, t = self.scope.resolve(e)
        if t in self.PAIR_TYPES:
            raise SiddhiAppCreationError(
                "dense NFA: integer attribute used outside a plain "
                "comparison (arithmetic/functions on 64-bit lanes need "
                "the host engine)")
        return super()._c_Variable(e)


def _rank_place(jnp, t, mask, anchor, src_regs, src_iregs, entry_dl,
                a, first, counts, regs, iregs, dl, ovf):
    """Rank-matched placement of advancing instances into free lanes of
    node ``t`` (shared by the event step and the timer step): the k-th
    advancing instance takes the k-th free lane; advancers beyond the
    free-lane count are dropped and counted in ``ovf`` — explicit
    capacity where the reference grows an unbounded pending list.

    ``entry_dl`` ([B, I] int32 or None) carries per-source deadline
    values for a target node with an absent 'for' spec; ``dl`` may be
    None when the engine has no deadline state at all.

    Returns updated ``(a, first, counts, regs, iregs, dl, ovf)``."""
    free = ~a[:, t, :] & (counts[:, t, :] == 0)  # [B, I]
    src_rank = jnp.cumsum(mask.astype(jnp.int32), axis=1) - 1
    free_rank = jnp.cumsum(free.astype(jnp.int32), axis=1) - 1
    n_free = jnp.sum(free.astype(jnp.int32), axis=1)  # [B]
    placed = mask & (src_rank < n_free[:, None])
    ovf = ovf + jnp.sum((mask & ~placed).astype(jnp.int32), axis=1)
    # [B, Isrc, Itgt] one-hot assignment
    assign = (placed[:, :, None] & free[:, None, :]
              & (src_rank[:, :, None] == free_rank[:, None, :]))
    got = jnp.any(assign, axis=1)  # [B, I] target lanes filled
    moved_regs = jnp.sum(
        jnp.where(assign[:, :, :, None], src_regs[:, :, None, :], 0.0),
        axis=1)  # [B, I, R]
    moved_anchor = jnp.sum(
        jnp.where(assign, anchor[:, :, None], 0), axis=1)  # [B, I]
    a = a.at[:, t, :].set(a[:, t, :] | got)
    regs = regs.at[:, t, :, :].set(
        jnp.where(got[:, :, None], moved_regs, regs[:, t, :, :]))
    if iregs.shape[-1]:
        moved_iregs = jnp.sum(
            jnp.where(assign[:, :, :, None], src_iregs[:, :, None, :], 0),
            axis=1)
        iregs = iregs.at[:, t, :, :].set(
            jnp.where(got[:, :, None], moved_iregs, iregs[:, t, :, :]))
    first = first.at[:, t, :].set(
        jnp.where(got, moved_anchor.astype(jnp.int32), first[:, t, :]))
    counts = counts.at[:, t, :].set(
        jnp.where(got, 0, counts[:, t, :]))
    if dl is not None:
        if entry_dl is not None:
            moved_dl = jnp.sum(
                jnp.where(assign, entry_dl[:, :, None], 0), axis=1)
            dl = dl.at[:, t, :].set(
                jnp.where(got, moved_dl.astype(jnp.int32), dl[:, t, :]))
        else:
            # target without a deadline spec: clear any stale value left
            # by a previous occupant of the lane
            dl = dl.at[:, t, :].set(jnp.where(got, 0, dl[:, t, :]))
    return a, first, counts, regs, iregs, dl, ovf


class DensePatternEngine:
    """Compiles a lowered node chain into a jitted per-stream step.

    Usage:
        eng = DensePatternEngine(nodes, ref_defs, stream_to_ref,
                                 within_ms, n_partitions, select_vars)
        state = eng.init_state()
        state, match_ev_idx, out = eng.process(state, stream_key,
                                               part_idx, cols, ts)
    """

    def __init__(
        self,
        nodes: List[Node],
        ref_defs: Dict[str, StreamDefinition],
        stream_to_ref: Dict[str, Optional[str]],
        within_ms: Optional[int],
        n_partitions: int,
        select_vars: List[Variable],
        select_names: Optional[List[str]] = None,
        every_start: bool = True,
        reset_on_emit: bool = True,
        mesh=None,
        partition_axis: str = "p",
        is_sequence: bool = False,
        n_instances: int = 4,
    ):
        import jax
        import jax.numpy as jnp

        self.jax, self.jnp = jax, jnp
        self.nodes = nodes
        self.ref_defs = ref_defs
        self.within_ms = within_ms
        self.n_partitions = n_partitions
        self.every_start = every_start
        self.reset_on_emit = reset_on_emit
        self.is_sequence = is_sequence
        self.mesh = mesh
        self.partition_axis = partition_axis
        self.S = len(nodes)
        # sequences keep one pending per state (reference
        # StreamPreStateProcessor.addState:217-223); non-every patterns
        # arm exactly one chain — the instance axis only matters for
        # overlapping `every` arms
        self.I = 1 if (is_sequence or not every_start) else max(int(n_instances), 1)
        if self.S > 32:
            raise SiddhiAppCreationError("dense NFA supports at most 32 chain nodes")
        # `every` models: a rearm at node 0's completion is the standing
        # virgin (`every e1 -> ...`); a WHOLE-CHAIN group-every
        # (`every (e1 -> e2)`, rearm on the last node back to 0) keeps
        # ONE arm at a time — the virgin arms only while the partition
        # has no active instance (completion consumes the arm, expiry
        # clears it; WithinPatternTestCase.testQuery4/6's cadence).
        # Partial-chain groups (`every (e1->e2) -> e3`) stay on the host
        # engine: the suffix instance keeps the partition occupied.
        self.group_every = False
        for n in nodes:
            if n.rearm_to is None:
                continue
            if n.pos == 0 and n.rearm_to == 0:
                continue  # standing virgin
            if (n.pos == self.S - 1 and n.rearm_to == 0
                    and not is_sequence
                    and nodes[0].kind == "stream"
                    and nodes[0].min_count == 1 and nodes[0].max_count == 1
                    and not any(sp.is_absent for nn in nodes
                                for sp in nn.specs)):
                # absent violations kill the host's single group arm
                # PERMANENTLY (no re-arm); the arm-when-empty virgin
                # would resurrect it — keep absent group-every on host
                self.group_every = True
                continue
            raise SiddhiAppCreationError(
                "dense NFA: this group-`every` shape (partial chain, or "
                "absent states whose violation must kill the arm "
                "permanently) needs the host engine")
        if self.group_every:
            # one arm at a time: a single instance lane suffices
            self.I = 1
        # absent states ride deadline-timer registers: a node with an
        # absent `for t` spec arms `deadline = entry_ts + t` on entry,
        # a matching absent-stream event kills the pending instance, and
        # the timer step (make_time_step) advances/emits instances whose
        # deadline passed — the dense analog of
        # AbsentStreamPreStateProcessor.java:35's scheduler arming
        self.deadline_w: List[Optional[int]] = []
        for n in nodes:
            w = None
            for sp in n.specs:
                if sp.is_absent and sp.waiting_ms is not None:
                    w = int(sp.waiting_ms)
            self.deadline_w.append(w)
        self.has_deadlines = any(w is not None for w in self.deadline_w)
        for ni, n in enumerate(nodes):
            if n.kind == "stream" and n.min_count == 0:
                raise SiddhiAppCreationError(
                    "dense NFA does not support optional (min 0) states yet; "
                    "use the host engine"
                )
            if n.kind != "absent" and not any(s.is_absent for s in n.specs):
                continue
            if is_sequence:
                raise SiddhiAppCreationError(
                    "dense NFA: absent states in sequences (strict "
                    "continuity over a waiting state) need the host engine")
            if n.kind == "absent" and self.deadline_w[ni] is None:
                raise SiddhiAppCreationError(
                    "dense NFA: standalone absent node without a 'for' "
                    "duration needs the host engine")
            if ni == 0 and self.deadline_w[ni] is not None:
                raise SiddhiAppCreationError(
                    "dense NFA: a leading absent 'for' deadline counts "
                    "from app start — host engine used")
            if self.deadline_w[ni] is not None and self.deadline_w[ni] > 2**23:
                raise SiddhiAppCreationError(
                    "dense NFA: absent 'for' durations above 2^23 ms would "
                    "overflow the int32 relative-time deadline — host "
                    "engine used")
            if n.kind == "logical":
                if n.logical_op == "or":
                    # the or-absent race (violation disables one branch,
                    # deadline completes with null present sides) stays
                    # on the host engine
                    raise SiddhiAppCreationError(
                        "dense NFA: 'or' with an absent side needs the "
                        "host engine")
                present_keys = {sp.stream_key for sp in n.specs
                                if not sp.is_absent}
                absent_keys = {sp.stream_key for sp in n.specs
                               if sp.is_absent}
                if present_keys & absent_keys:
                    raise SiddhiAppCreationError(
                        "dense NFA: logical and-not over the SAME stream "
                        "(one event can both match and violate) needs the "
                        "host engine")
                if ni == 0 and every_start:
                    # the host's start instance DIES on an absent-side
                    # violation and nothing re-arms it; the dense
                    # standing-virgin would immortally re-arm — diverging
                    # match sets, so this shape stays on the host engine
                    raise SiddhiAppCreationError(
                        "dense NFA: every-start logical and-not (violation "
                        "permanently kills the start state) needs the host "
                        "engine")

        self.alloc = RegAllocator()
        self._compile_filters(stream_to_ref)
        self._compile_outputs(select_vars, stream_to_ref, select_names)
        absent_refs = {sp.ref for n in nodes for sp in n.specs if sp.is_absent}
        for (ref, _attr, _last) in self.alloc.slots:
            if ref in absent_refs:
                raise SiddhiAppCreationError(
                    "dense NFA: filters/selects cannot reference an absent "
                    "event (it never arrives) — host engine used")
        # open-ended counts stay dually pending: they capture more events
        # after satisfaction and clone per successor-matching event (the
        # via-path in the step, carrying clone-time registers exactly
        # like the reference's _try_enter).  The via-path models one
        # capture+advance, so an open count's successor must be a plain
        # stream node.
        for ni, n in enumerate(nodes):
            is_count = not (n.min_count == 1 and n.max_count == 1)
            open_count = is_count and (n.max_count == ANY or n.max_count > n.min_count)
            if not open_count:
                continue
            if ni + 1 < len(nodes):
                nxt = nodes[ni + 1]
                if not (nxt.kind == "stream" and nxt.min_count == 1
                        and nxt.max_count == 1):
                    raise SiddhiAppCreationError(
                        "dense NFA: open-ended count followed by a "
                        "count/logical node needs the host engine")
        # capture slots each node writes — computed after BOTH filter and
        # output compilation so select-only slots get written too
        self.node_writes: List[List[RegSlot]] = []
        for node in self.nodes:
            writes = []
            for spec in node.specs:
                for (ref, _attr, _last), slot in self.alloc.slots.items():
                    if ref == spec.ref:
                        writes.append(slot)
            self.node_writes.append(writes)
        self._step_cache: Dict[str, Callable] = {}
        # @app:kernels: swap the jitted XLA step for the bit-packed
        # Pallas plane kernel (siddhi_tpu/kernels/dense_step.py).  Set
        # by planner/kernels.py after its eligibility gate; flipping it
        # requires clearing _step_cache.
        self.use_kernel = False

    # -- compilation --------------------------------------------------------

    def _compile_filters(self, stream_to_ref):
        """Per-node filters compiled against candidate columns + registers."""
        self.node_filters: List[List[Optional[CompiledExpression]]] = []
        for node in self.nodes:
            fs = []
            for spec in node.specs:
                if spec.filter_compiled is None:
                    fs.append(None)
                    continue
                # recompile the raw filter against the dense scope
                scope = DenseScope(self.ref_defs, stream_to_ref,
                                   spec.stream_def, self.alloc,
                                   cand_ref=spec.ref)
                compiler = DenseExprCompiler(scope)
                fs.append(compiler.compile(spec.raw_filter))
            self.node_filters.append(fs)

    def _compile_outputs(self, select_vars: List[Variable], stream_to_ref, select_names=None):
        """Selector variables -> (slot index | candidate attr) extractors.

        Output names use the query's `as` aliases when provided."""
        self.out_spec: List[Tuple[str, object]] = []  # (name, slot|('cand', attr))
        self.out_int: List[bool] = []  # integer (hi/lo pair) output lane?
        last_node = self.nodes[-1]
        last_refs = {s.ref for s in last_node.specs}
        for vi, var in enumerate(select_vars):
            ref = var.stream_id
            if ref not in self.ref_defs and ref in stream_to_ref:
                ref = stream_to_ref[ref]
            if ref is None or ref not in self.ref_defs:
                raise SiddhiAppCreationError(f"cannot resolve select ref '{var.stream_id}'")
            idx = var.stream_index
            name = (
                select_names[vi]
                if select_names and vi < len(select_names)
                else f"{ref}.{var.attribute}"
            )
            d = self.ref_defs[ref]
            if var.attribute not in d.attribute_names:
                raise SiddhiAppCreationError(
                    f"select ref '{ref}.{var.attribute}': no such attribute")
            integer = d.attribute_type(var.attribute) in _INT_TYPES
            if ref in last_refs and last_node.kind == "stream" and last_node.max_count == 1:
                # final event: values come from the candidate columns
                self.out_spec.append((name, ("cand", var.attribute)))
                self.out_int.append(integer)
                continue
            last = idx == -1
            if idx not in (None, 0, -1):
                raise SiddhiAppCreationError(
                    f"dense NFA supports only first/[0]/[last] select refs, got {idx}"
                )
            slot = self.alloc.slot(ref, var.attribute, last, integer=integer)
            self.out_spec.append((name, slot))
            self.out_int.append(integer)

    # -- state --------------------------------------------------------------

    def init_state_host(self) -> Dict[str, np.ndarray]:
        """Zero state as NUMPY arrays — no device allocation, so callers
        (e.g. the sharded wrapper) can lay out rows before any backend
        is selected."""
        # one scratch row (index P) absorbs padded/invalid batch rows so
        # their scatter-back cannot collide with a real partition
        P, S, I, R = (self.n_partitions + 1, self.S, self.I,
                      max(self.alloc.n, 1))
        active0 = np.zeros((P, S, I), dtype=bool)
        if not self.every_start:
            # non-every: node 0 armed once per partition (lane 0); after
            # a match reset_on_emit clears it and the automaton is done
            active0[:, 0, 0] = True
        state = {
            "active": active0,
            # relative ms since self.base_ts (int32: ~24 days of horizon),
            # 0 == unset
            "first_ts": np.zeros((P, S, I), dtype=np.int32),
            "counts": np.zeros((P, S, I), dtype=np.int32),
            "regs": np.zeros((P, S, I, R), dtype=np.float32),
            # per-partition dropped-instance count (successor slots full)
            "overflow": np.zeros(P, dtype=np.int32),
        }
        if self.alloc.n_int:
            # integer capture bank: hi/lo int32 pair per slot
            state["iregs"] = np.zeros((P, S, I, 2 * self.alloc.n_int),
                                      dtype=np.int32)
        if self.has_deadlines:
            # absent-node deadlines (relative ms; 0 == unset)
            state["deadline"] = np.zeros((P, S, I), dtype=np.int32)
        return state

    def state_pspecs(self):
        """Partition-axis sharding spec per state array (row-sharded;
        trailing node/instance/register dims replicated)."""
        from jax.sharding import PartitionSpec as Pspec

        a = self.partition_axis
        specs = {
            "active": Pspec(a, None, None),
            "first_ts": Pspec(a, None, None),
            "counts": Pspec(a, None, None),
            "regs": Pspec(a, None, None, None),
            "overflow": Pspec(a),
        }
        if self.alloc.n_int:
            specs["iregs"] = Pspec(a, None, None, None)
        if self.has_deadlines:
            specs["deadline"] = Pspec(a, None, None)
        return specs

    def init_state(self):
        jnp = self.jnp
        state = {k: jnp.asarray(v) for k, v in self.init_state_host().items()}
        if self.mesh is not None:
            from jax.sharding import NamedSharding

            specs = self.state_pspecs()
            state = {
                k: self.jax.device_put(v, NamedSharding(self.mesh, specs[k]))
                for k, v in state.items()
            }
        return state

    # -- step ---------------------------------------------------------------

    def make_step(self, stream_key: str, jit: bool = True) -> Callable:
        """Build the step for events of one source stream.

        step(state, part_idx[B] i32, cols {attr: [B] f32}, ts[B] i32
             relative-ms, valid[B] bool)
          -> (state, emit[B, 2*I] bool, out_vals[B, 2*I, n_out] f32,
              emit_anchor[B, 2*I] i32, n_emit i32 scalar)

        ``emit[b, i]``: a pending instance of event ``b``'s partition
        completed the chain on this event.  The emit arrays carry 2*I
        lanes: [0, I) for instances completing AT the last node, [I, 2I)
        for via-path clones (a dually-pending count's clone passing
        straight through the last node) — the two can fire on the same
        event at the same lane index, so they must not share a bank.
        ``emit_anchor`` carries each match's within-anchor (relative ms)
        so the host wrapper can order same-event matches by arming age,
        matching the reference's pendingStateEventList iteration order.

        ``jit=False`` returns the raw traceable function (for embedding in
        shard_map / outer jit).
        """
        cache_key = (stream_key, jit)
        if cache_key in self._step_cache:
            return self._step_cache[cache_key]
        if self.use_kernel:
            from siddhi_tpu.kernels.dense_step import build_packed_nfa

            fn = build_packed_nfa(self, stream_key, jit)
            self._step_cache[cache_key] = fn
            return fn
        jnp = self.jnp
        S = self.S
        I = self.I
        nodes = self.nodes
        node_filters = self.node_filters
        within = self.within_ms
        every_start = self.every_start
        group_every = self.group_every
        reset_on_emit = self.reset_on_emit
        is_sequence = self.is_sequence
        out_spec = self.out_spec
        O = max(len(out_spec), 1)

        def env_for(node_idx, cols, ts, regs_b, iregs_b, spec_idx=0,
                    regs_node=None):
            """Filter env over [B, I] lanes: candidate columns broadcast
            down the instance axis; registers are per-instance (float
            bank + hi/lo integer pair bank).  ``regs_node`` overrides
            which node's register lanes feed the env (the via-path
            evaluates node t's filter against the dually-pending source
            registers at t-1)."""
            env = {}
            spec = nodes[node_idx].specs[spec_idx]
            rn = node_idx if regs_node is None else regs_node
            for a in spec.stream_def.attributes:
                if a.type in _INT_TYPES:
                    hk, lk = f"{a.name}|hi", f"{a.name}|lo"
                    if hk in cols:
                        env[f"__cand.{a.name}|hi"] = cols[hk][:, None]
                        env[f"__cand.{a.name}|lo"] = cols[lk][:, None]
                elif a.name in cols:
                    env["__cand." + a.name] = cols[a.name][:, None]
            for slot in self.alloc.slots.values():
                if slot.integer:
                    env[f"__ireg.{slot.index}|hi"] = (
                        iregs_b[:, rn, :, 2 * slot.index])
                    env[f"__ireg.{slot.index}|lo"] = (
                        iregs_b[:, rn, :, 2 * slot.index + 1])
                else:
                    env[f"__reg.{slot.index}"] = regs_b[:, rn, :, slot.index]
            env[TS_KEY] = ts[:, None]
            env[N_KEY] = ts.shape[0]
            return env

        def eval_ok(s, si, cols, ts, regs, iregs, B):
            f = node_filters[s][si]
            if f is None:
                return jnp.ones((B, I), dtype=bool)
            return jnp.broadcast_to(
                jnp.asarray(f.fn(
                    env_for(s, cols, ts, regs, iregs, si))).astype(bool),
                (B, I))

        n_iout = sum(self.out_int)

        def step(state, part_idx, cols, ts, valid):
            B = part_idx.shape[0]
            a = state["active"][part_idx]        # [B, S, I] bool
            first = state["first_ts"][part_idx]  # [B, S, I]
            counts = state["counts"][part_idx]   # [B, S, I]
            regs = state["regs"][part_idx]       # [B, S, I, R]
            iregs = (state["iregs"][part_idx] if "iregs" in state
                     else jnp.zeros((B, S, I, 0), dtype=jnp.int32))
            ovf = state["overflow"][part_idx]    # [B]
            # deadline registers ride OUTSIDE the functional carry in a
            # one-cell holder: only placement and the absent kill/complete
            # branches touch them, and tracing is sequential python
            dlh = [state["deadline"][part_idx] if "deadline" in state
                   else None]
            emit = jnp.zeros((B, 2 * I), dtype=bool)
            out_vals = jnp.zeros((B, 2 * I, O), dtype=jnp.float32)
            out_ivals = jnp.zeros((B, 2 * I, 2 * n_iout), dtype=jnp.int32)
            emit_anchor = jnp.zeros((B, 2 * I), dtype=jnp.int32)

            # within-window expiry: clear expired instances (active bits,
            # in-progress counts and logical side masks alike)
            if within is not None:
                expired = (first > 0) & (ts[:, None, None] - first > within)
                a = a & ~expired
                counts = jnp.where(expired, 0, counts)
                first = jnp.where(expired, 0, first)
                if dlh[0] is not None:
                    dlh[0] = jnp.where(expired, 0, dlh[0])

            # group-every virgin gating: the fresh arm may only form
            # while the partition has NO active instance (post-expiry,
            # pre-event state — one arm at a time, matching the host's
            # arm-at-group-completion/expiry cadence)
            if group_every:
                grp_virgin_ok = ~jnp.any(
                    a.reshape(B, -1), axis=1)[:, None]  # [B, 1]

            # node filters evaluated once against entry-state registers
            # (the reversed loop reads them before any same-step regs
            # write could affect them); None = node not on this stream
            ok_pre = []
            for s in range(S):
                node = nodes[s]
                if node.kind == "logical":
                    oks = []
                    for si, sp in enumerate(node.specs):
                        if sp.stream_key != stream_key:
                            oks.append(None)
                        else:
                            oks.append(eval_ok(s, si, cols, ts, regs, iregs, B))
                    ok_pre.append(oks)
                elif node.specs[0].stream_key != stream_key:
                    ok_pre.append(None)
                else:
                    ok_pre.append(eval_ok(s, 0, cols, ts, regs, iregs, B))

            if is_sequence:
                # strict continuity (reference: SEQUENCE keeps one pending
                # per state, a non-matching event kills it; the start node
                # stays armed — StreamPreStateProcessor.addState:217-223):
                # any pending instance whose node cannot use this event
                # dies before the advance pass
                for s in range(1, S):
                    ok_s = ok_pre[s]
                    if isinstance(ok_s, list):
                        m = jnp.zeros((B, I), dtype=bool)
                        for o in ok_s:
                            if o is not None:
                                m = m | o
                    elif ok_s is None:
                        m = jnp.zeros((B, I), dtype=bool)
                    else:
                        m = ok_s
                    kill = a[:, s, :] & ~m & valid[:, None]
                    a = a.at[:, s, :].set(a[:, s, :] & ~kill)
                    counts = counts.at[:, s, :].set(
                        jnp.where(kill, 0, counts[:, s, :]))
                    first = first.at[:, s, :].set(
                        jnp.where(kill, 0, first[:, s, :]))

            # out-spec position -> index into the integer output pairs
            int_out_idx = {}
            for _oi, _isint in enumerate(self.out_int):
                if _isint:
                    int_out_idx[_oi] = len(int_out_idx)

            def _emit_rows(mask, anchor, src_regs, carry, bank=0,
                           src_iregs=None):
                """Instances in ``mask`` (with ``src_regs`` [B, I, R] and
                ``src_iregs`` [B, I, 2*RI]) complete the chain on this
                event.  ``bank`` selects the emit lane block (0:
                last-node completions, 1: via-path clones) so same-lane
                fires from both never collide."""
                a, first, counts, regs, iregs, emit, out_vals, out_ivals, emit_anchor, ovf = carry
                if src_iregs is None:
                    src_iregs = iregs[:, S - 1, :, :]
                lo = bank * I
                sl = slice(lo, lo + I)
                emit = emit.at[:, sl].set(emit[:, sl] | mask)
                emit_anchor = emit_anchor.at[:, sl].set(
                    jnp.where(mask, anchor, emit_anchor[:, sl]))
                for oi, (_name, src) in enumerate(out_spec):
                    ii = int_out_idx.get(oi)
                    if isinstance(src, tuple):  # ('cand', attr)
                        if ii is not None:
                            hk, lk = f"{src[1]}|hi", f"{src[1]}|lo"
                            if hk not in cols:
                                continue
                            out_ivals = out_ivals.at[:, sl, 2 * ii].set(
                                jnp.where(mask, cols[hk][:, None],
                                          out_ivals[:, sl, 2 * ii]))
                            out_ivals = out_ivals.at[:, sl, 2 * ii + 1].set(
                                jnp.where(mask, cols[lk][:, None],
                                          out_ivals[:, sl, 2 * ii + 1]))
                            continue
                        val = cols.get(src[1])
                        if val is None:
                            continue
                        out_vals = out_vals.at[:, sl, oi].set(
                            jnp.where(mask, val.astype(jnp.float32)[:, None],
                                      out_vals[:, sl, oi]))
                    elif ii is not None:
                        out_ivals = out_ivals.at[:, sl, 2 * ii].set(
                            jnp.where(mask, src_iregs[:, :, 2 * src.index],
                                      out_ivals[:, sl, 2 * ii]))
                        out_ivals = out_ivals.at[:, sl, 2 * ii + 1].set(
                            jnp.where(mask, src_iregs[:, :, 2 * src.index + 1],
                                      out_ivals[:, sl, 2 * ii + 1]))
                    else:
                        out_vals = out_vals.at[:, sl, oi].set(
                            jnp.where(mask, src_regs[:, :, src.index],
                                      out_vals[:, sl, oi]))
                return (a, first, counts, regs, iregs, emit, out_vals, out_ivals,
                        emit_anchor, ovf)

            def _place(mask, anchor, src_regs, t, carry, src_iregs=None):
                """Move instances in ``mask`` into free lanes of node
                ``t`` (rank-matched; see _rank_place).  A target node
                with an absent 'for' spec arms its deadline to this
                event's ts + waiting (the reference's _enter_node
                scheduler arming)."""
                a, first, counts, regs, iregs, emit, out_vals, out_ivals, emit_anchor, ovf = carry
                si = iregs[:, t - 1, :, :] if src_iregs is None else src_iregs
                w = self.deadline_w[t]
                entry_dl = (
                    jnp.broadcast_to(ts[:, None] + w, mask.shape)
                    if w is not None else None)
                a, first, counts, regs, iregs, dlh[0], ovf = _rank_place(
                    jnp, t, mask, anchor, src_regs, si, entry_dl,
                    a, first, counts, regs, iregs, dlh[0], ovf)
                return (a, first, counts, regs, iregs, emit, out_vals, out_ivals,
                        emit_anchor, ovf)

            def _advance(s, mask, carry):
                """Instances (lanes of node s) in ``mask`` complete node s:
                emit (last node) or move into free lanes of node s+1."""
                a, first, counts, regs, iregs = (
                    carry[0], carry[1], carry[2], carry[3], carry[4])
                anchor = jnp.where(first[:, s, :] > 0, first[:, s, :],
                                   ts[:, None])  # [B, I]
                if s == S - 1:
                    return _emit_rows(mask, anchor, regs[:, s, :, :], carry,
                                      src_iregs=iregs[:, s, :, :])
                return _place(mask, anchor, regs[:, s, :, :], s + 1, carry,
                              src_iregs=iregs[:, s, :, :])

            def write_slot(regs, iregs, s, slot, upd):
                """Capture the current event into one register slot of
                node ``s`` for lanes in ``upd`` (float bank or hi/lo
                integer pair bank by slot kind)."""
                if slot.integer:
                    hk, lk = f"{slot.attr}|hi", f"{slot.attr}|lo"
                    if hk in cols:
                        iregs = iregs.at[:, s, :, 2 * slot.index].set(
                            jnp.where(upd, cols[hk][:, None],
                                      iregs[:, s, :, 2 * slot.index]))
                        iregs = iregs.at[:, s, :, 2 * slot.index + 1].set(
                            jnp.where(upd, cols[lk][:, None],
                                      iregs[:, s, :, 2 * slot.index + 1]))
                elif slot.attr in cols:
                    regs = regs.at[:, s, :, slot.index].set(
                        jnp.where(upd,
                                  cols[slot.attr].astype(jnp.float32)[:, None],
                                  regs[:, s, :, slot.index]))
                return regs, iregs

            lane0 = jnp.zeros((B, I), dtype=bool).at[:, 0].set(True)
            carry = (a, first, counts, regs, iregs, emit, out_vals, out_ivals, emit_anchor, ovf)
            for s in reversed(range(S)):
                a, first, counts, regs, iregs, emit, out_vals, out_ivals, emit_anchor, ovf = carry
                node = nodes[s]
                spec = node.specs[0]
                if node.kind == "absent":
                    # a matching absent-stream event KILLS every pending
                    # instance waiting out the deadline (reference:
                    # absent violation, _process_event step 3); deadline
                    # completion itself runs in the timer step
                    if spec.stream_key != stream_key:
                        carry = (a, first, counts, regs, iregs, emit, out_vals,
                                 out_ivals, emit_anchor, ovf)
                        continue
                    viol = a[:, s, :] & ok_pre[s] & valid[:, None]
                    a = a.at[:, s, :].set(a[:, s, :] & ~viol)
                    counts = counts.at[:, s, :].set(
                        jnp.where(viol, 0, counts[:, s, :]))
                    first = first.at[:, s, :].set(
                        jnp.where(viol, 0, first[:, s, :]))
                    dlh[0] = dlh[0].at[:, s, :].set(
                        jnp.where(viol, 0, dlh[0][:, s, :]))
                    carry = (a, first, counts, regs, iregs, emit, out_vals,
                             out_ivals, emit_anchor, ovf)
                    continue
                if node.kind == "logical":
                    sides = [i for i, sp in enumerate(node.specs)
                             if sp.stream_key == stream_key
                             and not sp.is_absent]
                    kills = [i for i, sp in enumerate(node.specs)
                             if sp.stream_key == stream_key and sp.is_absent]
                    if not sides and not kills:
                        carry = (a, first, counts, regs, iregs, emit, out_vals,
                                 out_ivals, emit_anchor, ovf)
                        continue
                    pending = a[:, s, :]
                    if s == 0 and every_start:
                        # the standing virgin lives in lane 0
                        pending = pending | lane0
                    for si in kills:
                        # and-not violation: the absent side arriving
                        # while the node is pending kills the instance
                        # (virgins re-arm per event, so only real armed
                        # lanes die)
                        viol = a[:, s, :] & ok_pre[s][si] & valid[:, None]
                        a = a.at[:, s, :].set(a[:, s, :] & ~viol)
                        counts = counts.at[:, s, :].set(
                            jnp.where(viol, 0, counts[:, s, :]))
                        first = first.at[:, s, :].set(
                            jnp.where(viol, 0, first[:, s, :]))
                        if dlh[0] is not None:
                            dlh[0] = dlh[0].at[:, s, :].set(
                                jnp.where(viol, 0, dlh[0][:, s, :]))
                        pending = pending & ~viol
                        if s == 0 and every_start:
                            pending = pending | lane0
                    # event-time completion requires a present side to
                    # have matched THIS event (host completes only inside
                    # _try_capture's got branch); deferred completions —
                    # sides matched earlier, and-not-for deadline passing
                    # later — fire from the timer step alone
                    matched_now = jnp.zeros((B, I), dtype=bool)
                    for si in sides:
                        ok = ok_pre[s][si]
                        # an already-matched side ignores further events
                        # (the reference skips si in matched_sides —
                        # neither registers nor the anchor may refresh)
                        unmatched = (counts[:, s, :] & (1 << si)) == 0
                        fire = pending & ok & valid[:, None] & unmatched
                        if node.logical_op == "or":
                            # 'or' consumes only the FIRST matching side
                            # (host/reference leave the other side's
                            # capture null — LogicalPatternTestCase.
                            # testQuery3); 'and' lets one event fill both
                            fire = fire & ~matched_now
                        matched_now = matched_now | fire
                        counts = counts.at[:, s, :].set(
                            jnp.where(fire, counts[:, s, :] | (1 << si),
                                      counts[:, s, :]))
                        for slot in self.node_writes[s]:
                            if slot.ref == node.specs[si].ref:
                                regs, iregs = write_slot(regs, iregs, s, slot, fire)
                        first = first.at[:, s, :].set(
                            jnp.where(fire & (first[:, s, :] == 0), ts[:, None],
                                      first[:, s, :]))
                    # completion needs every PRESENT side (absent sides
                    # contribute by staying silent); `and not B for t`
                    # additionally requires the deadline to have passed
                    # (host _logical_complete: now >= deadline, with a
                    # timer-consumed deadline reading as satisfied)
                    pmask = sum(1 << i for i, sp in enumerate(node.specs)
                                if not sp.is_absent)
                    need = counts[:, s, :] & pmask
                    complete = (
                        (need == pmask)
                        if node.logical_op == "and"
                        else (need > 0)
                    ) & pending & valid[:, None] & matched_now
                    if self.deadline_w[s] is not None:
                        dls = dlh[0][:, s, :]
                        complete = complete & (
                            (dls == 0) | (ts[:, None] >= dls))
                    carry = _advance(s, complete,
                                     (a, first, counts, regs, iregs, emit, out_vals,
                                      out_ivals, emit_anchor, ovf))
                    a, first, counts, regs, iregs, emit, out_vals, out_ivals, emit_anchor, ovf = carry
                    # a completed logical node releases its lane (the host
                    # instance moves on); the lane-0 virgin re-arms fresh
                    a = a.at[:, s, :].set(a[:, s, :] & ~complete)
                    counts = counts.at[:, s, :].set(
                        jnp.where(complete, 0, counts[:, s, :]))
                    first = first.at[:, s, :].set(
                        jnp.where(complete, 0, first[:, s, :]))
                    if dlh[0] is not None and self.deadline_w[s] is not None:
                        dlh[0] = dlh[0].at[:, s, :].set(
                            jnp.where(complete, 0, dlh[0][:, s, :]))
                    carry = (a, first, counts, regs, iregs, emit, out_vals,
                             out_ivals, emit_anchor, ovf)
                    continue
                if spec.stream_key != stream_key:
                    carry = (a, first, counts, regs, iregs, emit, out_vals,
                             out_ivals, emit_anchor, ovf)
                    continue
                is_count = not (node.min_count == 1 and node.max_count == 1)
                pending = a[:, s, :]
                if s == 0 and every_start:
                    if is_count:
                        # a fresh virgin arms only while no unsatisfied
                        # counting instance exists (the host rearms at
                        # satisfaction — StreamPostStateProcessor
                        # addEveryState), taking the first free lane
                        unsat = (a[:, 0, :] & (counts[:, 0, :] > 0)
                                 & (counts[:, 0, :] < max(node.min_count, 1)))
                        has_unsat = jnp.any(unsat, axis=1)  # [B]
                        free0 = ~a[:, 0, :] & (counts[:, 0, :] == 0)
                        vrank = jnp.cumsum(free0.astype(jnp.int32), axis=1) - 1
                        virgin = free0 & (vrank == 0) & ~has_unsat[:, None]
                        pending = pending | virgin
                        # a virgin that SHOULD arm (no unsatisfied arm, the
                        # event passes the start filter) but finds no free
                        # lane is a dropped instance — count it (node-0
                        # filters read candidate columns only, so lane 0
                        # of ok is lane-uniform)
                        no_lane = (~has_unsat & ~jnp.any(free0, axis=1)
                                   & ok_pre[s][:, 0] & valid)
                        ovf = ovf + no_lane.astype(jnp.int32)
                    elif group_every:
                        pending = pending | (lane0 & grp_virgin_ok)
                    else:
                        # simple start never rests: the standing virgin
                        # fires straight through lane 0 on every event
                        pending = pending | lane0
                fire = pending & ok_pre[s] & valid[:, None]
                if is_count:
                    below_max = (node.max_count == ANY) | (counts[:, s, :] < node.max_count)
                    cap = fire & below_max
                    first_cap = cap & (counts[:, s, :] == 0)
                    counts = counts.at[:, s, :].set(
                        jnp.where(cap, counts[:, s, :] + 1, counts[:, s, :]))
                    # a counting lane is occupied from its first capture
                    a = a.at[:, s, :].set(a[:, s, :] | first_cap)
                    for slot in self.node_writes[s]:
                        if slot.ref != spec.ref:
                            continue
                        upd = cap if slot.last else first_cap
                        regs, iregs = write_slot(regs, iregs, s, slot, upd)
                    first = first.at[:, s, :].set(
                        jnp.where(first_cap & (first[:, s, :] == 0), ts[:, None],
                                  first[:, s, :]))
                    open_count = (node.max_count == ANY
                                  or node.max_count > node.min_count)
                    advance = cap & (counts[:, s, :] == max(node.min_count, 1))
                    if not open_count or s == S - 1:
                        # exact counts ({n}) move at min==max; a count
                        # LAST node emits once at satisfaction
                        # (emitted_at_node semantics — later captures
                        # don't re-emit because advance fires at == min)
                        carry = _advance(s, advance,
                                         (a, first, counts, regs, iregs, emit,
                                          out_vals, out_ivals, emit_anchor, ovf))
                        a, first, counts, regs, iregs, emit, out_vals, out_ivals, emit_anchor, ovf = carry
                    # lane lifecycle at max: exact counts are spent (their
                    # advance already placed the instance); open counts
                    # MOVE the still-pending instance to s+1 at max
                    # (reference _try_capture: count >= max ->
                    # _enter_node(pos+1)); its clones already advanced via
                    # the via-path at earlier successor events
                    if node.max_count != ANY:
                        at_max = cap & (counts[:, s, :] >= node.max_count)
                        if open_count and s < S - 1:
                            anchor_s = jnp.where(
                                first[:, s, :] > 0, first[:, s, :], ts[:, None])
                            carry = _place(at_max, anchor_s, regs[:, s, :, :],
                                           s + 1,
                                           (a, first, counts, regs, iregs,
                                            emit, out_vals, out_ivals,
                                            emit_anchor, ovf),
                                           src_iregs=iregs[:, s, :, :])
                            a, first, counts, regs, iregs, emit, out_vals, out_ivals, emit_anchor, ovf = carry
                        a = a.at[:, s, :].set(a[:, s, :] & ~at_max)
                        counts = counts.at[:, s, :].set(
                            jnp.where(at_max, 0, counts[:, s, :]))
                        first = first.at[:, s, :].set(
                            jnp.where(at_max, 0, first[:, s, :]))
                    carry = (a, first, counts, regs, iregs, emit, out_vals,
                             out_ivals, emit_anchor, ovf)
                else:
                    # capture the node's slots for real pending lanes
                    for slot in self.node_writes[s]:
                        if slot.ref != spec.ref:
                            continue
                        regs, iregs = write_slot(regs, iregs, s, slot, fire)
                    if s == 0 and every_start:
                        # fresh arming each event: the within anchor must
                        # be this event's ts, not a stale one
                        first = first.at[:, s, :].set(
                            jnp.where(fire, ts[:, None], first[:, s, :]))
                    else:
                        first = first.at[:, s, :].set(
                            jnp.where(fire & (first[:, s, :] == 0), ts[:, None],
                                      first[:, s, :]))
                    # only `every` keeps the start armed; a non-every
                    # sequence arms once and dies with its arm (reference:
                    # init() re-arms only for every —
                    # SequenceTestCase.testQuery31, mirrored in the host
                    # engine's _process_event re-arm gate)
                    keep_armed = s == 0 and every_start
                    if not keep_armed:
                        a = a.at[:, s, :].set(a[:, s, :] & ~fire)
                    carry = _advance(s, fire,
                                     (a, first, counts, regs, iregs, emit, out_vals,
                                      out_ivals, emit_anchor, ovf))
                    # via-path: a dually-pending open count at s-1 clones
                    # straight through this node on the same event
                    # (reference: _try_enter from a satisfied count
                    # instance; StreamPreStateProcessor dual pending)
                    if s >= 1:
                        prev = nodes[s - 1]
                        prev_open = (
                            prev.kind == "stream"
                            and not (prev.min_count == 1 and prev.max_count == 1)
                            and (prev.max_count == ANY
                                 or prev.max_count > prev.min_count)
                        )
                        if prev_open:
                            a, first, counts, regs, iregs, emit, out_vals, out_ivals, emit_anchor, ovf = carry
                            sat = (a[:, s - 1, :]
                                   & (counts[:, s - 1, :] >= max(prev.min_count, 1)))
                            if prev.max_count != ANY:
                                sat = sat & (counts[:, s - 1, :] < prev.max_count)
                            ok_via = (
                                jnp.broadcast_to(jnp.asarray(
                                    node_filters[s][0].fn(
                                        env_for(s, cols, ts, regs, iregs,
                                                regs_node=s - 1))).astype(bool),
                                    (B, I))
                                if node_filters[s][0] is not None
                                else jnp.ones((B, I), dtype=bool)
                            )
                            fire_via = sat & ok_via & valid[:, None]
                            via_regs = regs[:, s - 1, :, :]
                            via_iregs = iregs[:, s - 1, :, :]
                            for slot in self.node_writes[s]:
                                if slot.ref != spec.ref:
                                    continue
                                if slot.integer:
                                    hk, lk = (f"{slot.attr}|hi",
                                              f"{slot.attr}|lo")
                                    if hk not in cols:
                                        continue
                                    via_iregs = via_iregs.at[
                                        :, :, 2 * slot.index].set(jnp.where(
                                            fire_via, cols[hk][:, None],
                                            via_iregs[:, :, 2 * slot.index]))
                                    via_iregs = via_iregs.at[
                                        :, :, 2 * slot.index + 1].set(jnp.where(
                                            fire_via, cols[lk][:, None],
                                            via_iregs[:, :, 2 * slot.index + 1]))
                                elif slot.attr in cols:
                                    via_regs = via_regs.at[:, :, slot.index].set(
                                        jnp.where(
                                            fire_via,
                                            cols[slot.attr].astype(jnp.float32)[:, None],
                                            via_regs[:, :, slot.index]))
                            via_anchor = jnp.where(
                                first[:, s - 1, :] > 0, first[:, s - 1, :],
                                ts[:, None])
                            carry = (a, first, counts, regs, iregs, emit,
                                     out_vals, out_ivals, emit_anchor, ovf)
                            if s == S - 1:
                                carry = _emit_rows(fire_via, via_anchor,
                                                   via_regs, carry, bank=1,
                                                   src_iregs=via_iregs)
                            else:
                                carry = _place(fire_via, via_anchor, via_regs,
                                               s + 1, carry,
                                               src_iregs=via_iregs)
                            # PATTERN forward-once: the dually-pending arm
                            # is consumed at its successor match — it can
                            # emit at most once (reference
                            # removeIfNextStateProcessed; the host engine
                            # kills the source on via-advance likewise)
                            a, first, counts, regs, iregs, emit, out_vals, out_ivals, emit_anchor, ovf = carry
                            a = a.at[:, s - 1, :].set(
                                a[:, s - 1, :] & ~fire_via)
                            counts = counts.at[:, s - 1, :].set(
                                jnp.where(fire_via, 0, counts[:, s - 1, :]))
                            first = first.at[:, s - 1, :].set(
                                jnp.where(fire_via, 0, first[:, s - 1, :]))
                            carry = (a, first, counts, regs, iregs, emit,
                                     out_vals, out_ivals, emit_anchor, ovf)

            a, first, counts, regs, iregs, emit, out_vals, out_ivals, emit_anchor, ovf = carry

            # emission restart
            if reset_on_emit:
                any_emit = jnp.any(emit, axis=1)
                a = jnp.where(any_emit[:, None, None], False, a)
                counts = jnp.where(any_emit[:, None, None], 0, counts)
                first = jnp.where(any_emit[:, None, None], 0, first)
                if dlh[0] is not None:
                    dlh[0] = jnp.where(any_emit[:, None, None], 0, dlh[0])

            # scatter back (valid rows only)
            v1 = valid[:, None, None]
            new_state = {
                "active": state["active"].at[part_idx].set(
                    jnp.where(v1, a, state["active"][part_idx])
                ),
                "first_ts": state["first_ts"].at[part_idx].set(
                    jnp.where(v1, first, state["first_ts"][part_idx])
                ),
                "counts": state["counts"].at[part_idx].set(
                    jnp.where(v1, counts, state["counts"][part_idx])
                ),
                "regs": state["regs"].at[part_idx].set(
                    jnp.where(valid[:, None, None, None], regs,
                              state["regs"][part_idx])
                ),
                "overflow": state["overflow"].at[part_idx].set(
                    jnp.where(valid, ovf, state["overflow"][part_idx])
                ),
            }
            if "iregs" in state:
                new_state["iregs"] = state["iregs"].at[part_idx].set(
                    jnp.where(valid[:, None, None, None], iregs,
                              state["iregs"][part_idx]))
            if "deadline" in state:
                new_state["deadline"] = state["deadline"].at[part_idx].set(
                    jnp.where(v1, dlh[0], state["deadline"][part_idx]))
            # outs is a pytree: float lanes + integer hi/lo pair lanes;
            # n_emit is the count-gate scalar for the async emit
            # pipeline — the host fetches it alone and skips the column
            # transfer entirely on zero-match batches
            n_emit = jnp.sum((emit & valid[:, None]).astype(jnp.int32))
            return (new_state, emit, {"f": out_vals, "i": out_ivals},
                    emit_anchor, n_emit)

        fn = self.jax.jit(step, donate_argnums=(0,)) if jit else step
        self._step_cache[cache_key] = fn
        return fn

    # -- timer step (absent-node deadlines) ---------------------------------

    def make_time_step(self, jit: bool = True) -> Callable:
        """Build the deadline-timer step (engines with absent states).

        time_step(state, now_i32_rel)
          -> (state, emit[P, I] bool, outs {f, i}, fire[P, I] i32,
              n_emit i32)

        Runs over ALL partition rows (no event batch): pending instances
        whose absent deadline passed advance to the next node — or emit,
        when the absent node ends the chain — exactly like the host
        engine's scheduler tick (ops/nfa.py on_time; reference
        AbsentStreamPreStateProcessor timer path).  ``fire[p, i]`` is the
        deadline (relative ms) the instance fired at, which becomes the
        emitted match's timestamp.
        """
        cache_key = ("__time__", jit)
        if cache_key in self._step_cache:
            return self._step_cache[cache_key]
        jnp = self.jnp
        S, I = self.S, self.I
        nodes = self.nodes
        within = self.within_ms
        reset_on_emit = self.reset_on_emit
        out_spec = self.out_spec
        O = max(len(out_spec), 1)
        n_iout = sum(self.out_int)

        def time_step(state, now):
            a = state["active"]
            first = state["first_ts"]
            counts = state["counts"]
            regs = state["regs"]
            iregs = (state["iregs"] if "iregs" in state
                     else jnp.zeros(a.shape + (0,), dtype=jnp.int32))
            dl = state["deadline"]
            ovf = state["overflow"]
            Pr = a.shape[0]
            emit = jnp.zeros((Pr, I), dtype=bool)
            out_f = jnp.zeros((Pr, I, O), dtype=jnp.float32)
            out_i = jnp.zeros((Pr, I, 2 * n_iout), dtype=jnp.int32)
            fire = jnp.zeros((Pr, I), dtype=jnp.int32)

            # within expiry first (host on_time calls _expire(now) before
            # firing deadlines): an instance that ran out of its within
            # window never fires
            if within is not None:
                expired = (first > 0) & (now - first > within)
                a = a & ~expired
                counts = jnp.where(expired, 0, counts)
                first = jnp.where(expired, 0, first)
                dl = jnp.where(expired, 0, dl)

            # descending node order: a fire at node s placing into s+1
            # cannot re-fire this tick (host on_time is likewise a
            # single pass over instances)
            for s in reversed(range(S)):
                w = self.deadline_w[s]
                if w is None:
                    continue
                node = nodes[s]
                due = a[:, s, :] & (dl[:, s, :] > 0) & (now >= dl[:, s, :])
                ft = dl[:, s, :]  # fire timestamps (valid where due)
                if node.kind == "logical":
                    # complete only if every present side already
                    # matched; either way the deadline is CONSUMED (host
                    # clears inst.deadline at the tick — a later present
                    # match then completes immediately)
                    pmask = sum(1 << i for i, sp in enumerate(node.specs)
                                if not sp.is_absent)
                    fire_mask = due & ((counts[:, s, :] & pmask) == pmask)
                else:
                    fire_mask = due
                dl = dl.at[:, s, :].set(
                    jnp.where(due, 0, dl[:, s, :]))
                anchor = jnp.where(first[:, s, :] > 0, first[:, s, :], ft)
                if s == S - 1:
                    emit = emit | fire_mask
                    fire = jnp.where(fire_mask, ft, fire)
                    # outputs come from the node's register banks alone —
                    # select items never reference the absent event
                    # (validated at construction)
                    ii = 0
                    for oi, (_name, src) in enumerate(out_spec):
                        if self.out_int[oi]:
                            out_i = out_i.at[:, :, 2 * ii].set(jnp.where(
                                fire_mask, iregs[:, s, :, 2 * src.index],
                                out_i[:, :, 2 * ii]))
                            out_i = out_i.at[:, :, 2 * ii + 1].set(jnp.where(
                                fire_mask, iregs[:, s, :, 2 * src.index + 1],
                                out_i[:, :, 2 * ii + 1]))
                            ii += 1
                        else:
                            out_f = out_f.at[:, :, oi].set(jnp.where(
                                fire_mask, regs[:, s, :, src.index],
                                out_f[:, :, oi]))
                else:
                    w2 = self.deadline_w[s + 1]
                    entry_dl = (ft + w2) if w2 is not None else None
                    a, first, counts, regs, iregs, dl, ovf = _rank_place(
                        jnp, s + 1, fire_mask, anchor,
                        regs[:, s, :, :], iregs[:, s, :, :], entry_dl,
                        a, first, counts, regs, iregs, dl, ovf)
                a = a.at[:, s, :].set(a[:, s, :] & ~fire_mask)
                counts = counts.at[:, s, :].set(
                    jnp.where(fire_mask, 0, counts[:, s, :]))
                first = first.at[:, s, :].set(
                    jnp.where(fire_mask, 0, first[:, s, :]))

            if reset_on_emit:
                any_emit = jnp.any(emit, axis=1)
                a = jnp.where(any_emit[:, None, None], False, a)
                counts = jnp.where(any_emit[:, None, None], 0, counts)
                first = jnp.where(any_emit[:, None, None], 0, first)
                dl = jnp.where(any_emit[:, None, None], 0, dl)

            new_state = {
                "active": a,
                "first_ts": first,
                "counts": counts,
                "regs": regs,
                "overflow": ovf,
                "deadline": dl,
            }
            if "iregs" in state:
                new_state["iregs"] = iregs
            n_emit = jnp.sum(emit.astype(jnp.int32))
            return new_state, emit, {"f": out_f, "i": out_i}, fire, n_emit

        fn = self.jax.jit(time_step, donate_argnums=(0,)) if jit else time_step
        self._step_cache[cache_key] = fn
        return fn

    def next_wakeup_state(self, state) -> Optional[int]:
        """Earliest armed absent deadline (absolute ms), or None.  One
        device reduction + scalar transfer; engines without deadline
        nodes return None without touching the device."""
        if not self.has_deadlines or self.base_ts is None:
            return None
        if not hasattr(self, "_wakeup_fn"):
            jnp = self.jnp
            self._wakeup_fn = self.jax.jit(lambda a, dl: jnp.min(
                jnp.where(a & (dl > 0), dl, jnp.int32(2**31 - 1))))
        m = int(self._wakeup_fn(state["active"], state["deadline"]))
        if m >= 2**31 - 1:
            return None
        return self.base_ts + m

    def on_time_state(self, state, now: int):
        """Advance deadline timers to absolute time ``now``.

        Returns ``(state, fired)`` where ``fired`` is None (common) or
        ``(out[m, n_out], fire_ts[m] absolute-ms, part_rows[m])``
        ordered by (fire time, partition row, lane) — the host engine's
        deadline-ordered flush.  Works on sharded state too: the step is
        row-parallel, so XLA's sharding propagation runs it shard-local
        with no collectives."""
        if not self.has_deadlines or self.base_ts is None:
            return state, None
        rel = now - self.base_ts
        if rel <= 0:
            return state, None
        rel = min(rel, 2**31 - 1)
        tstep = self.make_time_step()
        state, emit, outs, fire, n_emit = tstep(state, np.int32(rel))
        # explicit count-gate fetch: int(device_scalar) is an IMPLICIT
        # transfer and would trip jax.transfer_guard('disallow')
        if int(self.jax.device_get(n_emit)) == 0:
            return state, None
        emit_np = np.asarray(emit)
        rows, lanes = np.nonzero(emit_np)
        out = self.assemble_out(np.asarray(outs["f"]), np.asarray(outs["i"]),
                                rows, lanes)
        fire_np = (np.asarray(fire)[rows, lanes].astype(np.int64)
                   + self.base_ts)
        order = np.lexsort((lanes, rows, fire_np))
        return state, (out[order], fire_np[order], rows[order])

    # -- host wrapper -------------------------------------------------------

    base_ts: Optional[int] = None
    # re-anchor before relative ms approach int32 range (~24.8 days of
    # stream time); headroom covers one batch + the within horizon
    _REL_LIMIT = 2**31 - 2**24

    def rel_ts64(self, ts: np.ndarray) -> np.ndarray:
        if self.base_ts is None:
            self.base_ts = int(ts[0]) - 1 if len(ts) else 0
        return ts - self.base_ts

    def maybe_re_anchor(self, state, rel64: np.ndarray, to_device=None):
        """Shift base_ts forward when relative timestamps approach the
        int32 range (they silently wrap after ~24.8 days otherwise and
        `within` checks corrupt).  ``first_ts`` anchors shift with it;
        instances whose anchor falls outside the `within` horizon are
        already expired and get their bits/counters cleared host-side
        (a once-per-24-days op, so the host round trip is fine).

        ``to_device(key, np_array)`` converts arrays back (defaults to
        jnp.asarray; the sharded wrapper passes a resharding put)."""
        if not len(rel64) or int(rel64.max()) < self._REL_LIMIT:
            return state, rel64
        horizon = self.within_ms or 0
        delta = int(rel64.min()) - 1 - horizon
        if delta <= 0 or int(rel64.max()) - delta >= 2**31:
            raise SiddhiAppRuntimeError(
                "dense NFA: timestamp span of one batch plus the within "
                "horizon exceeds the int32 relative-time range")
        self.base_ts += delta
        rel64 = rel64 - delta
        first = np.asarray(state["first_ts"]).astype(np.int64)  # [P, S, I]
        shifted = np.where(first > 0, first - delta, 0)
        if self.within_ms is not None:
            # anchors at/below the new zero were expired before the shift
            dead = (first > 0) & (shifted <= 0)
            active = np.asarray(state["active"]).copy()
            counts = np.asarray(state["counts"]).copy()
            if dead.any():
                active[dead] = False
                counts[dead] = 0
                shifted = np.where(dead, 0, shifted)
        else:
            # no within: anchors are semantically inert, clamp to stay
            # "set" (>0) without wrapping
            active = np.asarray(state["active"])
            counts = np.asarray(state["counts"])
            shifted = np.where(first > 0, np.maximum(shifted, 1), 0)
        if to_device is not None:
            conv = to_device
        elif self.mesh is not None:
            # keep the partition-axis sharding init_state applied — a
            # plain jnp.asarray would silently collapse state onto the
            # default device after a re-anchor
            from jax.sharding import NamedSharding

            specs = self.state_pspecs()
            conv = lambda k, v: self.jax.device_put(
                v, NamedSharding(self.mesh, specs[k]))
        else:
            conv = lambda _k, v: self.jnp.asarray(v)
        state = dict(state)
        state["first_ts"] = conv("first_ts", shifted.astype(np.int32))
        state["active"] = conv("active", active)
        state["counts"] = conv("counts", counts)
        if "deadline" in state:
            # armed deadlines shift with the base; one already at/below
            # the new zero clamps to 1 (long overdue — fires on the next
            # tick, which is where the un-shifted value pointed too)
            dlv = np.asarray(state["deadline"]).astype(np.int64)
            dshift = np.where(dlv > 0, np.maximum(dlv - delta, 1), 0)
            state["deadline"] = conv("deadline", dshift.astype(np.int32))
        return state, rel64

    def shift_row_ts(self, rows: Dict[str, np.ndarray],
                     delta: int) -> Dict[str, np.ndarray]:
        """Re-express HOST-side state rows against a base shifted by
        ``delta`` (new_base = old_base + delta), both directions.

        The multiplex group engine shares one ``base_ts`` across
        tenants: restoring a tenant snapshot taken under a different
        anchor, or admitting a tenant whose events predate the group
        anchor (a group-wide down-shift, delta < 0), rewrites the
        ``first_ts``/``deadline`` anchors with the same semantics as
        :meth:`maybe_re_anchor` — forward shifts expire instances that
        fall out of the ``within`` horizon (or clamp inert anchors to
        stay set), backward shifts only grow the values, bounded by the
        int32 range.  ``rows`` must already be HOST numpy arrays (both
        callers fetch before shifting) — no device materialization
        happens here."""
        out = dict(rows)
        first = rows["first_ts"].astype(np.int64)
        shifted = np.where(first > 0, first - delta, 0)
        if int(shifted.max(initial=0)) >= 2**31:
            raise SiddhiAppRuntimeError(
                "dense NFA: timestamp shift exceeds the int32 "
                "relative-time range")
        if delta > 0:
            if self.within_ms is not None:
                dead = (first > 0) & (shifted <= 0)
                if dead.any():
                    active = rows["active"].copy()
                    counts = rows["counts"].copy()
                    active[dead] = False
                    counts[dead] = 0
                    shifted = np.where(dead, 0, shifted)
                    out["active"] = active
                    out["counts"] = counts
            else:
                shifted = np.where(first > 0, np.maximum(shifted, 1), 0)
        out["first_ts"] = shifted.astype(np.int32)
        if "deadline" in rows:
            dlv = rows["deadline"].astype(np.int64)
            dshift = np.where(dlv > 0, dlv - delta, 0)
            if delta > 0:
                dshift = np.where(dlv > 0, np.maximum(dshift, 1), 0)
            elif int(dshift.max(initial=0)) >= 2**31:
                raise SiddhiAppRuntimeError(
                    "dense NFA: timestamp shift exceeds the int32 "
                    "relative-time range")
            out["deadline"] = dshift.astype(np.int32)
        return out

    def process(self, state, stream_key: str, part_idx: np.ndarray, cols: Dict[str, np.ndarray], ts: np.ndarray):
        """Process a batch, splitting rounds so each partition appears at
        most once per step (scatter collisions would race).  Rounds are
        padded to powers of two to bound jit recompilation.

        Returns ``(state, match_ev_idx, match_out)``: one row per match,
        ``match_ev_idx[m]`` the batch-row index of the completing event
        (ascending; same-event matches ordered by arming age, mirroring
        the reference's pendingStateEventList iteration order) and
        ``match_out[m, n_out]`` its output values."""
        state, pending = self.process_deferred(state, stream_key, part_idx,
                                               cols, ts)
        if pending is not None and pending.resolve() == 0:
            pending = None
        if pending is None:
            return state, *flatten_match_parts(
                [], [], [], max(len(self.out_spec), 1))
        from siddhi_tpu.core.emit_queue import fetch_coalesced

        ev, out = pending.materialize(fetch_coalesced(
            pending.device_arrays()))
        return state, ev, out

    def process_deferred(self, state, stream_key: str, part_idx: np.ndarray,
                         cols: Dict[str, np.ndarray], ts: np.ndarray):
        """Async-emit variant of :meth:`process`: every round's match
        outputs stay resident on device inside the returned
        :class:`DeferredDenseEmit` (None only for empty input).  NOTHING
        crosses device->host here — even the per-round ``n_emit`` count
        gate stays a device scalar until ``resolve()`` fetches it, which
        the ingest stage (core/ingest_stage.py) defers past the next
        batch's dispatch so the H2D transfer overlaps this batch's
        step."""
        faults = getattr(self, "faults", None)
        if faults is not None:
            faults.check("step.dense")
        from siddhi_tpu.core.ingest_stage import staged_put

        step = self.make_step(stream_key)
        rel64 = self.rel_ts64(np.asarray(ts, dtype=np.int64))
        state, rel64 = self.maybe_re_anchor(state, rel64)
        rel = rel64.astype(np.int32)
        prepared = self.prepare_cols(stream_key, cols)
        pending = DeferredDenseEmit(self)
        for ridx in _collision_rounds(part_idx):
            b = len(ridx)
            bp = max(1 << (b - 1).bit_length(), 16)  # pad to pow2, min 16
            pi = np.full(bp, self.n_partitions, dtype=np.int32)  # scratch row
            pi[:b] = part_idx[ridx]
            tb = np.zeros(bp, dtype=np.int32)
            tb[:b] = rel[ridx]
            valid = np.zeros(bp, dtype=bool)
            valid[:b] = True
            cb = {}
            for k, v in prepared.items():
                col = np.zeros(bp, dtype=v.dtype)
                col[:b] = v[ridx]
                cb[k] = col
            # one pytree H2D put per round behind the ingest.put fault
            # site (core/ingest_stage.py — the sanctioned ingest path)
            pi, cb, tb, valid = staged_put(
                (pi, cb, tb, valid), faults=faults,
                stats=getattr(self, "ingest_stats", None))
            state, emit, outs, emit_anchor, n_emit = step(
                state, pi, cb, tb, valid
            )
            # count gate deferred: n_emit stays a device scalar until
            # DeferredDenseEmit.resolve() (driven by the ingest stage)
            pending.chunks.append({
                "emit": emit, "f": outs["f"], "i": outs["i"],
                "anchor": emit_anchor, "sel": slice(0, b), "ridx": ridx,
                "count": n_emit,
            })
        return state, (pending if pending.chunks else None)

    def assemble_out(self, out_f: np.ndarray, out_i: np.ndarray,
                     rows: np.ndarray, lanes: np.ndarray) -> np.ndarray:
        """Match output rows from the device banks: float lanes stay
        float32; integer lanes re-join their hi/lo pair into exact
        int64.  All-float engines return a float32 [m, O] matrix (the
        historical shape); engines with integer outputs return an
        object-dtype matrix carrying exact per-column values."""
        if not any(self.out_int):
            return out_f[rows, lanes]
        m = len(rows)
        res = np.empty((m, len(self.out_spec)), dtype=object)
        ii = 0
        for oi, is_int in enumerate(self.out_int):
            if is_int:
                hi = out_i[rows, lanes, 2 * ii]
                lo = out_i[rows, lanes, 2 * ii + 1]
                res[:, oi] = _i64_join(hi, lo)
                ii += 1
            else:
                res[:, oi] = out_f[rows, lanes, oi].astype(np.float64)
        return res

    @property
    def output_names(self) -> List[str]:
        return [name for name, _ in self.out_spec]

    @property
    def default_stream(self) -> str:
        """Junction key of the pattern's first source stream (includes
        the '#'/'!' prefix for inner/fault streams — make_step matches
        on spec.stream_key, not the bare definition id)."""
        for node in self.nodes:
            for spec in node.specs:
                return spec.stream_key
        raise SiddhiAppCreationError("pattern has no source streams")

    @property
    def stream_keys(self) -> List[str]:
        keys = []
        for node in self.nodes:
            for spec in node.specs:
                if spec.stream_key not in keys:
                    keys.append(spec.stream_key)
        return keys

    def stream_attrs(self, stream_key: str) -> List[str]:
        """Column keys the step expects for events of one stream."""
        for node in self.nodes:
            for spec in node.specs:
                if spec.stream_key == stream_key:
                    return list(spec.stream_def.attribute_names)
        raise SiddhiAppCreationError(f"stream '{stream_key}' not in pattern")

    def numeric_stream_attrs(self, stream_key: str) -> List[str]:
        """Numeric attribute names of one stream (strings stay host-side
        as interned partition keys)."""
        return [a.name for a in self._stream_def(stream_key).attributes
                if a.type.is_numeric]

    def _stream_def(self, stream_key: str):
        for node in self.nodes:
            for spec in node.specs:
                if spec.stream_key == stream_key:
                    return spec.stream_def
        raise SiddhiAppCreationError(f"stream '{stream_key}' not in pattern")

    def device_col_keys(self, stream_key: str) -> List[str]:
        """Exact device col-dict keys the step expects: float attrs ride
        one float32 lane, integer attrs ride an ``|hi``/``|lo`` int32
        pair — the fixed pytree structure of shard_map in_specs."""
        keys: List[str] = []
        for a in self._stream_def(stream_key).attributes:
            if not a.type.is_numeric:
                continue
            if a.type in _INT_TYPES:
                keys.extend((f"{a.name}|hi", f"{a.name}|lo"))
            else:
                keys.append(a.name)
        return keys

    def prepare_cols(self, stream_key: str,
                     cols: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Host numpy columns (native dtypes) -> device lane columns:
        float attrs cast to float32, integer attrs split into the
        bias-signed hi/lo int32 pair (bit-exact at any magnitude)."""
        out: Dict[str, np.ndarray] = {}
        for a in self._stream_def(stream_key).attributes:
            v = cols.get(a.name)
            if v is None:
                continue
            v = np.asarray(v)
            if a.type in _INT_TYPES:
                v64 = v.astype(np.int64)
                out[f"{a.name}|hi"] = (v64 >> 32).astype(np.int32)
                out[f"{a.name}|lo"] = (
                    (v64 & 0xFFFFFFFF) - 2**31).astype(np.int32)
            elif a.type.is_numeric:
                out[a.name] = v.astype(np.float32)
        return out


class DeferredDenseEmit:
    """Device-resident match outputs of one dense batch, pending drain.

    Each chunk is one collision round whose count gate fired: the
    ``emit``/``f``/``i``/``anchor`` arrays are still jit outputs on
    device; ``sel`` maps padded device rows back to the round's events
    (a ``slice`` on the unsharded engine, a routed-slot index array on
    the sharded one) and ``ridx`` maps round rows to batch rows.
    ``device_arrays()`` + ``materialize()`` is the pending-emit queue
    contract (core/emit_queue.py): materialize receives the fetched host
    arrays in ``device_arrays()`` order and reproduces exactly what the
    synchronous path returns.
    """

    __slots__ = ("engine", "chunks", "_total")

    def __init__(self, engine):
        self.engine = engine
        self.chunks: List[dict] = []
        self._total: Optional[int] = None

    def probe(self):
        """Device scalar marking step completion (ingest-stage overlap
        evidence); None when no round dispatched."""
        return self.chunks[0]["count"] if self.chunks else None

    def resolve(self) -> int:
        """Fetch the deferred per-round count gates (scalars only) and
        prune rounds that matched nothing, so their column banks are
        never transferred.  Idempotent; returns total match count."""
        if self._total is not None:
            return self._total
        if self.chunks:
            import jax

            counts = jax.device_get([ch["count"] for ch in self.chunks])
        else:
            counts = []
        self.chunks = [ch for ch, c in zip(self.chunks, counts) if int(c)]
        self._total = int(sum(int(c) for c in counts))
        return self._total

    def device_arrays(self) -> List:
        arrs: List = []
        for ch in self.chunks:
            arrs.extend((ch["emit"], ch["f"], ch["i"], ch["anchor"]))
        return arrs

    def materialize(self, host_arrays) -> Tuple[np.ndarray, np.ndarray]:
        eng = self.engine
        ev_parts: List[np.ndarray] = []
        out_parts: List[np.ndarray] = []
        key_parts: List[np.ndarray] = []  # (ev, anchor, lane) sort keys
        for ci, ch in enumerate(self.chunks):
            emit_h, f_h, i_h, anchor_h = host_arrays[4 * ci:4 * ci + 4]
            sel = ch["sel"]
            emit_np = np.asarray(emit_h)[sel]  # [b, 2I]
            if not emit_np.any():
                continue  # count gate can overcount padded lanes: skip
            out_f = np.asarray(f_h)[sel]
            out_i = np.asarray(i_h)[sel]
            anchor_np = np.asarray(anchor_h)[sel]
            rows, lanes = np.nonzero(emit_np)
            ridx = ch["ridx"]
            ev_parts.append(ridx[rows])
            out_parts.append(eng.assemble_out(out_f, out_i, rows, lanes))
            key_parts.append(np.stack(
                [ridx[rows], anchor_np[rows, lanes], lanes], axis=1))
        return flatten_match_parts(
            ev_parts, out_parts, key_parts, max(len(eng.out_spec), 1))


def flatten_match_parts(ev_parts, out_parts, key_parts, n_out: int
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """Concatenate per-round match fragments and order them by
    (event index, arming anchor, lane) — the single definition of the
    match-ordering contract, shared by the unsharded and sharded
    wrappers."""
    if not ev_parts:
        return (np.empty(0, dtype=np.int64),
                np.empty((0, n_out), dtype=np.float32))
    ev = np.concatenate(ev_parts)
    out = np.concatenate(out_parts)
    keys = np.concatenate(key_parts)
    order = np.lexsort((keys[:, 2], keys[:, 1], keys[:, 0]))
    return ev[order].astype(np.int64), out[order]


def _collision_rounds(part_idx: np.ndarray) -> List[np.ndarray]:
    """Split indices into rounds where each partition appears at most once,
    preserving per-partition order."""
    order = np.argsort(part_idx, kind="stable")
    sorted_parts = part_idx[order]
    # occurrence number of each element within its partition group
    is_new = np.ones(len(part_idx), dtype=bool)
    is_new[1:] = sorted_parts[1:] != sorted_parts[:-1]
    group_start = np.maximum.accumulate(np.where(is_new, np.arange(len(part_idx)), 0))
    occ = np.arange(len(part_idx)) - group_start
    occ_orig = np.empty(len(part_idx), dtype=np.int64)
    occ_orig[order] = occ
    n_rounds = int(occ.max()) + 1 if len(occ) else 0
    return [np.flatnonzero(occ_orig == r) for r in range(n_rounds)]


# ---------------------------------------------------------------------------
# High-level compile API
# ---------------------------------------------------------------------------


def compile_pattern(
    app_str: str,
    query_name: Optional[str] = None,
    n_partitions: int = 1024,
    mesh=None,
    every_start: Optional[bool] = None,
    n_instances: int = 4,
):
    """Compile a SiddhiQL pattern query into a DensePatternEngine.

    The partition axis is the implicit per-key replication of the query
    (the reference's `partition with (key of Stream)` over pattern
    queries); callers route events to partition ids (interned keys).
    """
    from siddhi_tpu.compiler import SiddhiCompiler
    from siddhi_tpu.query_api.annotation import find_annotation

    app = SiddhiCompiler.parse(app_str)
    query = None
    for i, q in enumerate(app.queries):
        info = find_annotation(q.annotations, "info")
        nm = (info.element("name") if info else None) or f"query_{i}"
        if query_name is None or nm == query_name:
            query = q
            break
    if query is None:
        raise SiddhiAppCreationError(f"query '{query_name}' not found")
    st = query.input_stream
    if not isinstance(st, StateInputStream):
        raise SiddhiAppCreationError("compile_pattern needs a pattern query")
    is_sequence = st.type == StateInputStream.SEQUENCE

    def resolve(s):
        d = app.stream_definitions.get(s.stream_id)
        if d is None:
            raise SiddhiAppCreationError(f"stream '{s.stream_id}' is not defined")
        return d

    builder = NFABuilder(st, resolve)
    nodes = builder.build()
    if every_start is None:
        # group-scoped `every` is rejected by DensePatternEngine.__init__
        every_start = any(n.rearm_to is not None for n in nodes)

    select_vars = []
    select_names = []
    if query.selector.selection:
        for oa in query.selector.selection:
            if not isinstance(oa.expression, Variable) or oa.expression.stream_id is None:
                raise SiddhiAppCreationError(
                    "dense NFA select items must be event references (e1.attr)"
                )
            select_vars.append(oa.expression)
            select_names.append(oa.name)

    return DensePatternEngine(
        nodes=nodes,
        ref_defs=builder.ref_defs,
        stream_to_ref=builder.stream_to_ref,
        within_ms=st.within_ms,
        n_partitions=n_partitions,
        select_vars=select_vars,
        select_names=select_names,
        every_start=every_start,
        mesh=mesh,
        is_sequence=is_sequence,
        n_instances=n_instances,
    )
