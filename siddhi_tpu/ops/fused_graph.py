"""Device-resident stream-graph fusion: one jitted program per chain.

The product path routes every inter-query hop host-side through
`StreamJunction` — the producer materializes an EventBatch, the junction
dispatches it, the consumer re-pads and re-uploads it.  For a chain of
device-mode queries (filter → window → pattern) that is three H2D/D2H
round trips and three EventBatch builds per batch cycle, which is why
the product path trails the kernel-path bench by orders of magnitude
(ROADMAP "Whole-app fusion").

`FusedGraphEngine` composes the EXISTING per-stage step kernels
(ops/device_query.py `make_step`, ops/dense_nfa.py `make_step`) into one
jit-compiled multi-stage program: each stage's "expr" output lanes feed
the next stage's input lanes directly in HBM, passthrough outputs
forward the producer's own input lane, and a per-stage valid mask
(`v & ov`) replaces the junction's row compaction — filtered-out rows
simply stop participating, they are never compacted, transferred, or
re-padded.  The host is touched only at the chain head (one
`staged_put` per chunk), at the count-gated emit drain, and at the
re-anchor horizon (~24.8 days), exactly like a single device query.

Stage subset (the planner falls back to the junction path, with a
counted reason, for anything else — planner/fusion.py):

- interior + head stages: single-input device queries of kind
  filter / running / sliding, no group-by, CURRENT output;
- intermediate lanes: INT (int32, bit-exact), FLOAT (float32), BOOL,
  and DOUBLE expression outputs (both paths compute those in float32,
  so forwarding the f32 lane is bit-identical to the junction's
  f64 column + f32 re-pad);
- tail: a device query (as above; order-by/limit/offset ride the
  planner's host-side passthrough selector, as on the junction path)
  OR an unpartitioned dense pattern over the last intermediate stream
  (no absent-deadline timers).

The dense tail runs under `lax.scan` over the batch rows inside the
SAME jit: the junction path processes an unpartitioned pattern in B
singleton collision rounds (one dispatch each); the scan is that exact
round sequence fused into one program, with invalid rows routed to the
engine's scratch partition row — bit-identical match sets and ordering
(`flatten_match_parts` lexsort keys are preserved).

Emission follows the async-emit contract (core/emit_queue.py): one
count scalar gates the chunk, matched chunks stay device-resident in
the bounded pending-emit queue until a coalesced drain, and
`FusedDeferredEmit.materialize` reproduces exactly what the junction
path's tail query would have emitted (one EventBatch per junction
batch).

This module is scanned by the `host-sync-hazard` analysis rule: it
contains NO host materializer call sites at all — counts resolve
through `fetch_coalesced`, column fetches happen only inside the
pending-emit drain, and host-side prep uses zero-fill + `.astype`.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.ops.device_query import (
    MAX_DEVICE_BATCH,
    _pow2,
    _split_i64,
)

TAIL_DEVICE = "device"
TAIL_DENSE = "dense"


class FusedGraphEngine:
    """One fused chain: device-query stages wired output→input on
    device, with an optional dense-pattern tail.

    ``stages``: the chain's DeviceQueryEngines in flow order (each
    stage's input stream is the previous stage's `insert into` target).
    ``dense_tail``/``dense_stream_key``: terminal DensePatternEngine
    reading the last intermediate stream (or None for a device tail).
    """

    def __init__(self, stages: List, dense_tail=None,
                 dense_stream_key: Optional[str] = None):
        if not stages:
            raise SiddhiAppCreationError("fused chain needs device stages")
        if len(stages) + (1 if dense_tail is not None else 0) < 2:
            raise SiddhiAppCreationError("fused chain needs >= 2 stages")
        self.stages = list(stages)
        self.dense = dense_tail
        self.dense_stream_key = dense_stream_key
        head = stages[0]
        self.jax, self.jnp = head.jax, head.jnp
        for eng in stages:
            if eng.kind not in ("filter", "running", "sliding"):
                raise SiddhiAppCreationError(
                    f"fused chain: stage kind '{eng.kind}' not fusable")
            if eng.group_exprs or eng.partition_mode:
                raise SiddhiAppCreationError(
                    "fused chain: group-by/partition stages not fusable")
        for eng in stages[1:]:
            if eng.long_attrs:
                raise SiddhiAppCreationError(
                    "fused chain: LONG intermediate attributes have no "
                    "device-resident lane")
        # stage-to-stage wire plans: consumer attr -> producer lane
        self._wires: List[Optional[List[Tuple[str, str, str]]]] = [None]
        for si in range(1, len(stages)):
            self._wires.append(
                self._wire_for(stages[si - 1], stages[si].attrs))
        if dense_tail is not None:
            if dense_stream_key is None:
                raise SiddhiAppCreationError(
                    "fused chain: dense tail needs its stream key")
            if getattr(dense_tail, "has_deadlines", False):
                raise SiddhiAppCreationError(
                    "fused chain: absent-deadline patterns need the "
                    "scheduler-driven junction path")
            dkeys = set(dense_tail.device_col_keys(dense_stream_key))
            self._dense_wire: List[Tuple[str, str, str, bool]] = []
            spec = {name: (kind, v)
                    for kind, v, name in stages[-1].out_spec}
            for a in dense_tail.numeric_stream_attrs(dense_stream_key):
                kind, v = self._resolve_spec(spec, a)
                self._dense_wire.append(
                    (a, kind, v, (a + "|hi") in dkeys))
            self.tail_kind = TAIL_DENSE
            self.output_names = list(dense_tail.output_names)
            from siddhi_tpu.core.dense_pattern import output_attr_types

            self.out_dtypes = [
                t.np_dtype for t in output_attr_types(dense_tail)]
        else:
            tail = stages[-1]
            self.tail_kind = TAIL_DEVICE
            self.output_names = list(tail.output_names)
            self.out_dtypes = [t.np_dtype for t in tail.out_types]
            # tail passthroughs gather the tail's INPUT lane host-side;
            # those lanes are f32/i32/bool on the fused path, so only
            # types whose lane is exact may ride them (planner-enforced;
            # re-checked here for direct-API callers)
            self.fwd_names = sorted({
                v for kind, v, _n in tail.out_spec if kind == "passthrough"
            })
        # wired by the runtime (staged_put device-put accounting)
        self.ingest_stats = None
        # @app:faults injector (planner-wired; one chain = one step site)
        self.faults = None
        self._fused_step: Optional[Callable] = None

    @staticmethod
    def _resolve_spec(spec, attr):
        if attr not in spec:
            raise SiddhiAppCreationError(
                f"fused chain: consumer attribute '{attr}' is not an "
                "output of the producer stage")
        kind, v = spec[attr]
        if kind == "expr":
            return "out", attr
        if kind == "passthrough":
            return "in", v
        raise SiddhiAppCreationError(
            f"fused chain: producer select item '{attr}' ({kind}) "
            "cannot stay device-resident")

    def _wire_for(self, producer, attrs):
        spec = {name: (kind, v) for kind, v, name in producer.out_spec}
        return [(a, *self._resolve_spec(spec, a)) for a in attrs]

    # -- state ---------------------------------------------------------------

    def init_state(self) -> Tuple:
        states = [eng.init_state() for eng in self.stages]
        if self.dense is not None:
            states.append(self.dense.init_state())
        return tuple(states)

    # -- the fused program ---------------------------------------------------

    def make_step(self) -> Callable:
        """One jit over the whole chain:

        fused(states, cols {head lane: [B]}, rels (per-stage [B] i32),
              grp [B] i32, valid [B] bool)
          -> device tail: (states, emitmask[B], out {name: [B]},
                           fwd {attr: [B]}, count)
          -> dense tail:  (states, emitmask[B, 2I], f, i, anchor, count)

        ``count`` is exact (already masked by the chain's valid lane),
        so the async-emit count gate never overcounts padding.
        """
        if self._fused_step is not None:
            return self._fused_step
        self._fused_step = self.jax.jit(self._build_fused())
        return self._fused_step

    def _build_fused(self) -> Callable:
        """The raw (un-jitted) fused chain function — the subclassable
        seam: ShardedFusedGraphEngine (parallel/fused_shard.py) wraps
        it in shard_map before jitting."""
        jax, jnp = self.jax, self.jnp
        dev_steps = [eng.make_step(jit=False) for eng in self.stages]
        wires = self._wires
        dense = self.dense
        if dense is not None:
            dstep = dense.make_step(self.dense_stream_key, jit=False)
            dkeys = list(dense.device_col_keys(self.dense_stream_key))
            dwire = self._dense_wire
            P = dense.n_partitions

        def fused(states, cols, rels, grp, valid):
            new_states = []
            v = valid
            cur = cols
            ov = valid
            out: Dict = {}
            for si, step in enumerate(dev_steps):
                if si > 0:
                    # the hop: wire producer lanes straight into the
                    # consumer's input env — no compaction, no transfer;
                    # rows the producer dropped just lose their valid bit
                    v = v & ov.astype(bool)
                    cur = {
                        a: (out[key] if src == "out" else cur[key])
                        for a, src, key in wires[si]
                    }
                st, ov, out, _n = step(states[si], cur, rels[si],
                                       grp, grp, v)
                new_states.append(st)
            if dense is None:
                emitmask = ov.astype(bool) & v
                count = jnp.sum(emitmask.astype(jnp.int32))
                fwd = {k: cur[k] for k in self.fwd_names}
                return tuple(new_states), emitmask, out, fwd, count
            # dense tail: the junction path feeds an unpartitioned
            # pattern one singleton collision round per row; lax.scan is
            # that exact sequence inside the same program.  Invalid rows
            # route to the scratch partition row (what the junction
            # path's padding lanes do) so state stays bit-identical.
            v = v & ov.astype(bool)
            dcols = {}
            for a, src, key, is_int in dwire:
                lane = out[key] if src == "out" else cur[key]
                if is_int:
                    # int32 lane -> the engine's bit-exact hi/lo pair
                    # (prepare_cols semantics, computed in-jit)
                    lane = lane.astype(jnp.int32)
                    dcols[a + "|hi"] = jnp.where(
                        lane < 0, jnp.int32(-1), jnp.int32(0))
                    dcols[a + "|lo"] = jnp.bitwise_xor(
                        lane, jnp.int32(-(2 ** 31)))
                else:
                    dcols[a] = lane.astype(jnp.float32)
            xs = {"__t": rels[-1], "__v": v}
            for k in dkeys:
                xs[k] = dcols[k]

            def body(dstate, x):
                vb = x["__v"][None]
                pi = jnp.where(x["__v"], jnp.int32(0),
                               jnp.int32(P)).astype(jnp.int32)[None]
                cb = {k: x[k][None] for k in dkeys}
                dstate, emit, outs, anchor, _ne = dstep(
                    dstate, pi, cb, x["__t"][None], vb)
                return dstate, (emit[0], outs["f"][0], outs["i"][0],
                                anchor[0])

            dstate, ys = jax.lax.scan(body, states[-1], xs)
            new_states.append(dstate)
            emit, out_f, out_i, anchor = ys
            emitmask = emit & v[:, None]
            count = jnp.sum(emitmask.astype(jnp.int32))
            return (tuple(new_states), emitmask, out_f, out_i, anchor,
                    count)

        return fused

    # -- host entry points ---------------------------------------------------

    def process_batch_deferred(self, states: Tuple,
                               cols: Dict[str, np.ndarray],
                               ts: np.ndarray):
        """Run the fused program over one junction batch (chunked at
        MAX_DEVICE_BATCH) and keep every output device-resident behind
        a FusedDeferredEmit — the async-emit contract of the per-query
        engines, for the whole chain at once."""
        n = len(ts)
        if n == 0:
            return states, None
        chunks: List[dict] = []
        if n > MAX_DEVICE_BATCH:
            for i in range(0, n, MAX_DEVICE_BATCH):
                sl = slice(i, i + MAX_DEVICE_BATCH)
                states = self._chunk(
                    states, {k: v[sl] for k, v in cols.items()}, ts[sl],
                    i, chunks)
        else:
            states = self._chunk(states, cols, ts, 0, chunks)
        return states, FusedDeferredEmit(self, chunks, ts)

    def _pad_batch(self, n: int) -> int:
        """Padded chunk width.  The sharded subclass rounds up further
        so the batch axis splits evenly over the mesh."""
        return _pow2(n)

    def _chunk(self, states: Tuple, cols: Dict[str, np.ndarray],
               ts: np.ndarray, offset: int, chunks: List[dict]) -> Tuple:
        n = len(ts)
        B = self._pad_batch(n)
        states = list(states)
        # per-stage relative timestamps: each stage keeps its own epoch
        # (base_ts), re-anchored host-side at the int32 horizon exactly
        # like its standalone runtime would
        rels: List[np.ndarray] = []
        for si, eng in enumerate(self.stages):
            if eng.base_ts is None:
                eng.base_ts = int(ts[0]) - 1
            rel64 = ts - eng.base_ts
            if int(rel64.max()) >= eng._REL_LIMIT:
                states[si], rel64 = eng._re_anchor(states[si], rel64)
            r = np.zeros(B, dtype=np.int32)
            r[:n] = rel64.astype(np.int32)
            rels.append(r)
        if self.dense is not None:
            rel64 = self.dense.rel_ts64(ts)
            states[-1], rel64 = self.dense.maybe_re_anchor(
                states[-1], rel64)
            r = np.zeros(B, dtype=np.int32)
            r[:n] = rel64.astype(np.int32)
            rels.append(r)
        # head lanes: zero-padded to B, one staged_put for the whole
        # chain's chunk (the single sanctioned ingest device_put)
        head = self.stages[0]
        c: Dict[str, np.ndarray] = {}
        for a, lane in head._lane_dtype.items():
            col = np.zeros(B, dtype=lane)
            if a in cols:
                col[:n] = cols[a].astype(lane)
            c[a] = col
        for a in head.long_attrs:
            hi = np.zeros(B, dtype=np.int32)
            lo = np.zeros(B, dtype=np.int32)
            if a in cols:
                hi[:n], lo[:n] = _split_i64(cols[a])
            c[a + "|hi"] = hi
            c[a + "|lo"] = lo
        grp = np.zeros(B, dtype=np.int32)
        valid = np.zeros(B, dtype=bool)
        valid[:n] = True
        from siddhi_tpu.core.ingest_stage import staged_put

        c, rels_t, grp, valid = staged_put(
            (c, tuple(rels), grp, valid), faults=self.faults,
            stats=self.ingest_stats)
        if self.faults is not None:
            self.faults.check("step.device")
            if self.dense is not None:
                self.faults.check("step.dense")
        step = self.make_step()
        res = step(tuple(states), c, rels_t, grp, valid)
        if self.tail_kind == TAIL_DEVICE:
            new_states, emitmask, out, fwd, count = res
            chunks.append({
                "kind": TAIL_DEVICE, "emitmask": emitmask,
                "out": dict(out), "names": list(out),
                "fwd": dict(fwd), "fwd_names": list(fwd),
                "count": count, "n": n, "ts": ts,
            })
        else:
            new_states, emitmask, out_f, out_i, anchor, count = res
            chunks.append({
                "kind": TAIL_DENSE, "emitmask": emitmask, "f": out_f,
                "i": out_i, "anchor": anchor, "count": count, "n": n,
                "offset": offset,
            })
        return tuple(new_states)


class FusedDeferredEmit:
    """Device-resident outputs of one fused junction batch, pending
    drain — the pending-emit queue contract (core/emit_queue.py):
    ``probe``/``resolve`` fetch only count scalars, ``device_arrays`` +
    ``materialize`` reproduce exactly what the junction path's tail
    query would have emitted for this batch (ONE EventBatch worth of
    columns, already cast to the declared output dtypes)."""

    __slots__ = ("graph", "chunks", "ts64", "_total")

    def __init__(self, graph: FusedGraphEngine, chunks: List[dict],
                 ts64: np.ndarray):
        self.graph = graph
        self.chunks = chunks
        self.ts64 = ts64
        self._total: Optional[int] = None

    def probe(self):
        return self.chunks[0]["count"] if self.chunks else None

    def resolve(self) -> int:
        if self._total is not None:
            return self._total
        if self.chunks:
            from siddhi_tpu.core.emit_queue import fetch_coalesced

            counts = fetch_coalesced([ch["count"] for ch in self.chunks])
        else:
            counts = []
        self.chunks = [ch for ch, c in zip(self.chunks, counts) if int(c)]
        self._total = int(sum(int(c) for c in counts))
        return self._total

    def device_arrays(self) -> List:
        arrs: List = []
        for ch in self.chunks:
            arrs.append(ch["emitmask"])
            if ch["kind"] == TAIL_DEVICE:
                arrs.extend(ch["out"][nm] for nm in ch["names"])
                arrs.extend(ch["fwd"][k] for k in ch["fwd_names"])
            else:
                arrs.extend((ch["f"], ch["i"], ch["anchor"]))
        return arrs

    def materialize(self, host_arrays
                    ) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
        g = self.graph
        if g.tail_kind == TAIL_DEVICE:
            return self._materialize_device(host_arrays)
        return self._materialize_dense(host_arrays)

    def _materialize_device(self, host):
        tail = self.graph.stages[-1]
        pos = 0
        col_parts: List[Dict[str, np.ndarray]] = []
        ts_parts: List[np.ndarray] = []
        for ch in self.chunks:
            n = ch["n"]
            em = host[pos][:n]
            pos += 1
            out_np = {}
            for nm in ch["names"]:
                out_np[nm] = host[pos][:n]
                pos += 1
            fwd_cols = {}
            for k in ch["fwd_names"]:
                fwd_cols[k] = host[pos][:n]
                pos += 1
            idx = np.flatnonzero(em)
            if len(idx) == 0:
                continue
            col_parts.append(
                tail._out_columns(out_np, idx, None, fwd_cols, idx))
            ts_parts.append(ch["ts"][idx])
        if not ts_parts:
            return tail._empty_cols(), np.empty(0, dtype=np.int64)
        out_cols = {
            nm: np.concatenate([p[nm] for p in col_parts])
            for nm in tail.output_names
        }
        return out_cols, np.concatenate(ts_parts)

    def _materialize_dense(self, host):
        from siddhi_tpu.ops.dense_nfa import flatten_match_parts

        g = self.graph
        eng = g.dense
        pos = 0
        ev_parts: List[np.ndarray] = []
        out_parts: List[np.ndarray] = []
        key_parts: List[np.ndarray] = []
        for ch in self.chunks:
            n = ch["n"]
            em = host[pos][:n]
            f_h = host[pos + 1][:n]
            i_h = host[pos + 2][:n]
            anchor = host[pos + 3][:n]
            pos += 4
            if not em.any():
                continue
            rows, lanes = np.nonzero(em)
            ev_parts.append(ch["offset"] + rows)
            out_parts.append(eng.assemble_out(f_h, i_h, rows, lanes))
            key_parts.append(np.stack(
                [ch["offset"] + rows, anchor[rows, lanes], lanes],
                axis=1))
        ev, out = flatten_match_parts(
            ev_parts, out_parts, key_parts, max(len(eng.out_spec), 1))
        out_cols = {
            nm: out[:, oi].astype(g.out_dtypes[oi])
            for oi, nm in enumerate(g.output_names)
        }
        return out_cols, self.ts64[ev]
