"""Window processors.

Re-design of the reference's 30 window implementations
(query/processor/stream/window/*WindowProcessor.java) as columnar
operators: each window keeps buffered rows as arrays and, per input
batch, returns a combined batch of CURRENT (arrivals) and EXPIRED
(evictions) events plus optional RESET markers for batch windows.
Downstream aggregators add CURRENT rows and subtract EXPIRED rows, which
reproduces the reference's windowed-aggregation semantics.

Time-driven windows receive ``on_time(now)`` ticks from the scheduler
(watermark-driven in playback mode).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core import event as ev
from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.extension.registry import extension
from siddhi_tpu.extension.validator import REPEAT, Param
from siddhi_tpu.planner.expr import CompiledExpression
from siddhi_tpu.query_api.attribute import AttrType

# common @Parameter type sets for the builtin window declarations
_INTS = (AttrType.INT, AttrType.LONG)
_FLOATS = (AttrType.FLOAT, AttrType.DOUBLE)


class WindowProcessor:
    """Base window operator.

    ``process(batch, now)`` -> output batch (CURRENT + EXPIRED [+ RESET]).
    ``on_time(now)`` -> output batch for scheduler ticks (time windows).
    ``next_wakeup()`` -> absolute ms when a tick is needed, or None.
    """

    needs_scheduler = False

    def __init__(self, args: List[CompiledExpression], attribute_names: List[str]):
        self.args = args
        self.attribute_names = attribute_names

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        raise NotImplementedError

    def on_time(self, now: int) -> Optional[EventBatch]:
        return None

    def next_wakeup(self) -> Optional[int]:
        return None

    # findable-processor surface for joins / on-demand queries
    def buffered(self) -> Optional[EventBatch]:
        return None

    def snapshot(self) -> Dict:
        return {}

    def restore(self, state: Dict):
        pass

    @staticmethod
    def _const_int(c: CompiledExpression, what: str) -> int:
        try:
            return int(c.fn({}))
        except Exception as e:
            raise SiddhiAppCreationError(f"{what} must be a constant") from e


def _empty_like(b: EventBatch) -> EventBatch:
    return EventBatch(
        b.stream_id,
        b.attribute_names,
        {k: v[:0] for k, v in b.columns.items()},
        b.timestamps[:0],
        b.types[:0],
    )


def reset_marker(template: EventBatch, now: int) -> EventBatch:
    """One-row RESET event (default-valued data) telling downstream
    aggregators to clear state — the ComplexEvent.Type.RESET analog."""
    cols = {}
    for k, v in template.columns.items():
        if v.dtype == object:
            col = np.empty(1, dtype=object)
            col[0] = None
        else:
            col = np.zeros(1, dtype=v.dtype)
        cols[k] = col
    return EventBatch(
        template.stream_id,
        template.attribute_names,
        cols,
        np.asarray([now], dtype=np.int64),
        np.asarray([ev.RESET], dtype=np.int8),
    )


@extension("window", "length")
class LengthWindow(WindowProcessor):
    """Sliding length window (reference: LengthWindowProcessor).

    Keeps the last N events; each arrival beyond capacity expires the
    oldest buffered event.
    """

    PARAMETERS = (Param('window.length', _INTS),)
    OVERLOADS = (('window.length',),)

    def __init__(self, args, attribute_names):
        super().__init__(args, attribute_names)
        self.length = self._const_int(args[0], "length window size")
        self._buf: Optional[EventBatch] = None

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        cur = batch.only(ev.CURRENT)
        if self._buf is None:
            self._buf = _empty_like(cur)
        prev_len = len(self._buf)
        combined = EventBatch.concat([self._buf, cur])
        n_total = len(combined)
        n_over = max(0, n_total - self.length)
        self._buf = combined.take(np.arange(n_over, n_total))
        if n_over == 0:
            return cur
        # interleave so each arrival's eviction directly precedes it
        # (reference inserts the evicted clone before the current event,
        # LengthWindowProcessor), keeping aggregate subtract-then-add order
        order: List[int] = []
        types: List[int] = []
        for i in range(len(cur)):
            evict_idx = prev_len + i - self.length
            if evict_idx >= 0:
                order.append(evict_idx)
                types.append(ev.EXPIRED)
            order.append(prev_len + i)
            types.append(ev.CURRENT)
        out = combined.take(np.asarray(order))
        out.types = np.asarray(types, dtype=np.int8)
        out.timestamps = np.where(
            out.types == ev.EXPIRED, now, out.timestamps
        ).astype(np.int64)
        return out

    def buffered(self) -> Optional[EventBatch]:
        return self._buf

    def snapshot(self):
        return {"buf": self._buf}

    def restore(self, state):
        self._buf = state["buf"]


@extension("window", "lengthBatch")
class LengthBatchWindow(WindowProcessor):
    """Tumbling length window (reference: LengthBatchWindowProcessor).

    Collects N events, then flushes them as CURRENT while expiring the
    previous batch; emits a RESET marker before each flush so downstream
    aggregators restart per batch.
    """

    PARAMETERS = (Param('window.length', _INTS),)
    OVERLOADS = (('window.length',),)

    is_batch = True  # selector emits last-row-per-group (ProcessingMode.BATCH)

    def __init__(self, args, attribute_names):
        super().__init__(args, attribute_names)
        self.length = self._const_int(args[0], "lengthBatch window size")
        self._pending: Optional[EventBatch] = None
        self._last_flushed: Optional[EventBatch] = None

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        cur = batch.only(ev.CURRENT)
        if self._pending is None:
            self._pending = _empty_like(cur)
        self._pending = EventBatch.concat([self._pending, cur])
        outs: List[EventBatch] = []
        while len(self._pending) >= self.length:
            flush = self._pending.take(np.arange(self.length))
            self._pending = self._pending.take(
                np.arange(self.length, len(self._pending))
            )
            if self._last_flushed is not None and len(self._last_flushed):
                exp = self._last_flushed.with_types(ev.EXPIRED)
                exp.timestamps = np.full(len(exp), now, dtype=np.int64)
                outs.append(exp)
            # RESET clears batch aggregators between tumbles
            outs.append(reset_marker(cur, now))
            outs.append(flush)
            self._last_flushed = flush
        if not outs:
            return _empty_like(cur)
        return EventBatch.concat(outs)

    def buffered(self) -> Optional[EventBatch]:
        return self._pending

    def snapshot(self):
        return {"pending": self._pending, "last": self._last_flushed}

    def restore(self, state):
        self._pending = state["pending"]
        self._last_flushed = state["last"]


@extension("window", "time")
class TimeWindow(WindowProcessor):
    """Sliding time window (reference: TimeWindowProcessor): each event
    expires ``t`` ms after arrival; evictions fire on scheduler ticks."""

    PARAMETERS = (Param('window.time', _INTS),)
    OVERLOADS = (('window.time',),)

    needs_scheduler = True

    def __init__(self, args, attribute_names):
        super().__init__(args, attribute_names)
        self.time_ms = self._const_int(args[0], "time window duration")
        self._buf: Optional[EventBatch] = None

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        cur = batch.only(ev.CURRENT)
        if self._buf is None:
            self._buf = _empty_like(cur)
        expired = self._expire(now)
        if len(cur):
            self._buf = EventBatch.concat([self._buf, cur])
        parts = [b for b in (expired, cur) if b is not None and len(b)]
        return EventBatch.concat(parts) if parts else _empty_like(cur)

    def _expire(self, now: int) -> Optional[EventBatch]:
        if self._buf is None or len(self._buf) == 0:
            return None
        dead = self._buf.timestamps + self.time_ms <= now
        if not dead.any():
            return None
        expired = self._buf.mask(dead).with_types(ev.EXPIRED)
        expired.timestamps = np.full(len(expired), now, dtype=np.int64)
        self._buf = self._buf.mask(~dead)
        return expired

    def on_time(self, now: int) -> Optional[EventBatch]:
        return self._expire(now)

    def next_wakeup(self) -> Optional[int]:
        if self._buf is None or len(self._buf) == 0:
            return None
        return int(self._buf.timestamps.min()) + self.time_ms

    def buffered(self) -> Optional[EventBatch]:
        return self._buf

    def snapshot(self):
        return {"buf": self._buf}

    def restore(self, state):
        self._buf = state["buf"]


@extension("window", "timeBatch")
class TimeBatchWindow(WindowProcessor):
    """Tumbling time window (reference: TimeBatchWindowProcessor): collects
    events per period, flushes CURRENT at each boundary and expires the
    previous flush."""

    PARAMETERS = (Param('window.time', _INTS),)
    OVERLOADS = (('window.time',),)

    needs_scheduler = True
    is_batch = True

    def __init__(self, args, attribute_names):
        super().__init__(args, attribute_names)
        self.time_ms = self._const_int(args[0], "timeBatch window duration")
        self._pending: Optional[EventBatch] = None
        self._last_flushed: Optional[EventBatch] = None
        self._window_end: Optional[int] = None

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        cur = batch.only(ev.CURRENT)
        if self._pending is None:
            self._pending = _empty_like(cur)
        if self._window_end is None and len(cur):
            self._window_end = int(cur.timestamps[0]) + self.time_ms
        out = self._maybe_flush(now)
        if len(cur):
            self._pending = EventBatch.concat([self._pending, cur])
            if self._window_end is None:
                # flush above went idle; this arrival starts a new period
                self._window_end = int(cur.timestamps[0]) + self.time_ms
        return out if out is not None else _empty_like(cur)

    def _maybe_flush(self, now: int) -> Optional[EventBatch]:
        if self._window_end is None or now < self._window_end:
            return None
        outs: List[EventBatch] = []
        while self._window_end is not None and now >= self._window_end:
            flush = self._pending
            self._pending = _empty_like(flush)
            if self._last_flushed is not None and len(self._last_flushed):
                exp = self._last_flushed.with_types(ev.EXPIRED)
                exp.timestamps = np.full(len(exp), self._window_end, dtype=np.int64)
                outs.append(exp)
            if len(flush) or (self._last_flushed is not None and len(self._last_flushed)):
                outs.append(reset_marker(flush, self._window_end))
            if len(flush):
                outs.append(flush)
            self._last_flushed = flush
            if len(self._pending) == 0 and len(flush) == 0:
                self._window_end = None  # go idle until next event
            else:
                self._window_end += self.time_ms
        return EventBatch.concat(outs) if outs else None

    def on_time(self, now: int) -> Optional[EventBatch]:
        return self._maybe_flush(now)

    def next_wakeup(self) -> Optional[int]:
        return self._window_end

    def buffered(self) -> Optional[EventBatch]:
        return self._pending

    def snapshot(self):
        return {"pending": self._pending, "last": self._last_flushed, "end": self._window_end}

    def restore(self, state):
        self._pending, self._last_flushed, self._window_end = (
            state["pending"], state["last"], state["end"]
        )


@extension("window", "externalTime")
class ExternalTimeWindow(WindowProcessor):
    """Sliding window over an event-time attribute (reference:
    ExternalTimeWindowProcessor) — expiry driven purely by arriving
    events' timestamps, no scheduler."""

    PARAMETERS = (Param('timestamp', (AttrType.LONG,)),
                  Param('window.time', _INTS))
    OVERLOADS = (('timestamp', 'window.time'),)

    def __init__(self, args, attribute_names):
        super().__init__(args, attribute_names)
        # args: (timestamp variable, duration)
        self.ts_expr = args[0]
        self.time_ms = self._const_int(args[1], "externalTime duration")
        # buffer of (1-row EventBatch, external ts), insertion-ordered;
        # external timestamps are monotone in practice, so expiry pops the
        # front — O(evictions) per batch, no full-buffer copies
        from collections import deque

        self._buf = deque()

    def _event_ts(self, batch: EventBatch) -> np.ndarray:
        from siddhi_tpu.core.query import build_env

        return np.broadcast_to(
            np.asarray(self.ts_expr.fn(build_env(batch))), (len(batch),)
        ).astype(np.int64)

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        cur = batch.only(ev.CURRENT)
        outs: List[EventBatch] = []
        ets = self._event_ts(cur) if len(cur) else np.empty(0, dtype=np.int64)
        for i in range(len(cur)):
            t_i = int(ets[i])
            cutoff = t_i - self.time_ms
            while self._buf and self._buf[0][1] <= cutoff:
                row, _ = self._buf.popleft()
                exp = row.with_types(ev.EXPIRED)
                exp.timestamps = np.full(len(exp), t_i, dtype=np.int64)
                outs.append(exp)
            row = cur.take(np.asarray([i]))
            outs.append(row)
            self._buf.append((row, t_i))
        return EventBatch.concat(outs) if outs else _empty_like(cur)

    def buffered(self) -> Optional[EventBatch]:
        if not self._buf:
            return None
        return EventBatch.concat([r for r, _ in self._buf])

    def snapshot(self):
        return {"buf": self._buf}

    def restore(self, state):
        self._buf = state["buf"]


@extension("window", "externalTimeBatch")
class ExternalTimeBatchWindow(WindowProcessor):
    """Tumbling window over an event-time attribute (reference:
    ExternalTimeBatchWindowProcessor)."""

    PARAMETERS = (Param('timestamp', (AttrType.LONG,)),
                  Param('window.time', _INTS),
                  Param('start.time', _INTS))
    OVERLOADS = (('timestamp', 'window.time'),
                 ('timestamp', 'window.time', 'start.time'))

    is_batch = True

    def __init__(self, args, attribute_names):
        super().__init__(args, attribute_names)
        self.ts_expr = args[0]
        self.time_ms = self._const_int(args[1], "externalTimeBatch duration")
        self.start_ts = self._const_int(args[2], "start time") if len(args) > 2 else None
        self._pending: Optional[EventBatch] = None
        self._last_flushed: Optional[EventBatch] = None
        self._window_end: Optional[int] = None

    def _event_ts(self, batch: EventBatch) -> np.ndarray:
        from siddhi_tpu.core.query import build_env

        return np.broadcast_to(
            np.asarray(self.ts_expr.fn(build_env(batch))), (len(batch),)
        ).astype(np.int64)

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        cur = batch.only(ev.CURRENT)
        if self._pending is None:
            self._pending = _empty_like(cur)
        outs: List[EventBatch] = []
        ets = self._event_ts(cur) if len(cur) else np.empty(0, dtype=np.int64)
        for i in range(len(cur)):
            t_i = int(ets[i])
            if self._window_end is None:
                base = self.start_ts if self.start_ts is not None else t_i
                self._window_end = base + self.time_ms
            while t_i >= self._window_end:
                flush = self._pending
                self._pending = _empty_like(flush)
                if self._last_flushed is not None and len(self._last_flushed):
                    exp = self._last_flushed.with_types(ev.EXPIRED)
                    exp.timestamps = np.full(len(exp), self._window_end, dtype=np.int64)
                    outs.append(exp)
                if len(flush):
                    outs.append(reset_marker(flush, self._window_end))
                    outs.append(flush)
                # empty windows also replace the last flush, so an old batch
                # cannot be re-expired on every empty period
                self._last_flushed = flush
                self._window_end += self.time_ms
            row = cur.take(np.asarray([i]))
            self._pending = EventBatch.concat([self._pending, row])
        return EventBatch.concat(outs) if outs else _empty_like(cur)

    def buffered(self) -> Optional[EventBatch]:
        return self._pending

    def snapshot(self):
        return {"pending": self._pending, "last": self._last_flushed, "end": self._window_end}

    def restore(self, state):
        self._pending, self._last_flushed, self._window_end = (
            state["pending"], state["last"], state["end"]
        )


@extension("window", "timeLength")
class TimeLengthWindow(WindowProcessor):
    """Sliding window bounded by both time and count (reference:
    TimeLengthWindowProcessor)."""

    PARAMETERS = (Param('window.time', _INTS),
                  Param('window.length', _INTS))
    OVERLOADS = (('window.time', 'window.length'),)

    needs_scheduler = True

    def __init__(self, args, attribute_names):
        super().__init__(args, attribute_names)
        self.time_ms = self._const_int(args[0], "timeLength duration")
        self.length = self._const_int(args[1], "timeLength size")
        self._buf: Optional[EventBatch] = None

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        cur = batch.only(ev.CURRENT)
        if self._buf is None:
            self._buf = _empty_like(cur)
        outs: List[EventBatch] = []
        exp = self._expire_time(now)
        if exp is not None and len(exp):
            outs.append(exp)
        for i in range(len(cur)):
            if len(self._buf) >= self.length:
                evict = self._buf.take(np.asarray([0])).with_types(ev.EXPIRED)
                evict.timestamps = np.full(1, now, dtype=np.int64)
                outs.append(evict)
                self._buf = self._buf.take(np.arange(1, len(self._buf)))
            row = cur.take(np.asarray([i]))
            outs.append(row)
            self._buf = EventBatch.concat([self._buf, row])
        return EventBatch.concat(outs) if outs else _empty_like(cur)

    def _expire_time(self, now: int) -> Optional[EventBatch]:
        if self._buf is None or len(self._buf) == 0:
            return None
        dead = self._buf.timestamps + self.time_ms <= now
        if not dead.any():
            return None
        expired = self._buf.mask(dead).with_types(ev.EXPIRED)
        expired.timestamps = np.full(len(expired), now, dtype=np.int64)
        self._buf = self._buf.mask(~dead)
        return expired

    def on_time(self, now: int) -> Optional[EventBatch]:
        return self._expire_time(now)

    def next_wakeup(self) -> Optional[int]:
        if self._buf is None or len(self._buf) == 0:
            return None
        return int(self._buf.timestamps.min()) + self.time_ms

    def buffered(self) -> Optional[EventBatch]:
        return self._buf

    def snapshot(self):
        return {"buf": self._buf}

    def restore(self, state):
        self._buf = state["buf"]


@extension("window", "delay")
class DelayWindow(WindowProcessor):
    """Holds events for ``t`` ms, then releases them as CURRENT
    (reference: DelayWindowProcessor)."""

    PARAMETERS = (Param('window.delay', _INTS),)
    OVERLOADS = (('window.delay',),)

    needs_scheduler = True

    def __init__(self, args, attribute_names):
        super().__init__(args, attribute_names)
        self.time_ms = self._const_int(args[0], "delay duration")
        self._buf: Optional[EventBatch] = None

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        cur = batch.only(ev.CURRENT)
        if self._buf is None:
            self._buf = _empty_like(cur)
        out = self._release(now)
        if len(cur):
            self._buf = EventBatch.concat([self._buf, cur])
        return out if out is not None else _empty_like(cur)

    def _release(self, now: int) -> Optional[EventBatch]:
        if self._buf is None or len(self._buf) == 0:
            return None
        due = self._buf.timestamps + self.time_ms <= now
        if not due.any():
            return None
        released = self._buf.mask(due)  # stays CURRENT
        self._buf = self._buf.mask(~due)
        return released

    def on_time(self, now: int) -> Optional[EventBatch]:
        return self._release(now)

    def next_wakeup(self) -> Optional[int]:
        if self._buf is None or len(self._buf) == 0:
            return None
        return int(self._buf.timestamps.min()) + self.time_ms

    def snapshot(self):
        return {"buf": self._buf}

    def restore(self, state):
        self._buf = state["buf"]


@extension("window", "sort")
class SortWindow(WindowProcessor):
    """Keeps the N smallest/largest events by sort keys (reference:
    SortWindowProcessor): when over capacity, evicts the greatest (asc)
    or smallest (desc) as EXPIRED."""

    PARAMETERS = (Param('window.length', _INTS),
                  Param('attribute'))
    OVERLOADS = (('window.length',),
                 ('window.length', 'attribute', REPEAT))

    def __init__(self, args, attribute_names):
        super().__init__(args, attribute_names)
        self.length = self._const_int(args[0], "sort window size")
        # remaining args: key expressions with optional 'asc'/'desc' consts
        self.keys: List[Tuple[object, bool]] = []
        i = 1
        while i < len(args):
            expr = args[i]
            asc = True
            if i + 1 < len(args):
                try:
                    nxt = args[i + 1].fn({})
                    if isinstance(nxt, str) and nxt.lower() in ("asc", "desc"):
                        asc = nxt.lower() == "asc"
                        i += 1
                except Exception as e:
                    # next arg is a key expression, not an asc/desc
                    # const — expected for non-constant args; traced so
                    # no construction fault vanishes silently
                    import logging

                    logging.getLogger("siddhi_tpu").debug(
                        "sort window: arg %d is not an order const "
                        "(%s); treating it as a key expression", i + 1, e)
            self.keys.append((expr, asc))
            i += 1
        self._buf: Optional[EventBatch] = None

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        from siddhi_tpu.core.query import build_env

        cur = batch.only(ev.CURRENT)
        if self._buf is None:
            self._buf = _empty_like(cur)
        outs: List[EventBatch] = []
        for i in range(len(cur)):
            row = cur.take(np.asarray([i]))
            outs.append(row)
            self._buf = EventBatch.concat([self._buf, row])
            if len(self._buf) > self.length:
                order = self._sorted_order()
                evict_pos = order[-1]
                evict = self._buf.take(np.asarray([evict_pos])).with_types(ev.EXPIRED)
                evict.timestamps = np.full(1, now, dtype=np.int64)
                outs.append(evict)
                keep = np.ones(len(self._buf), dtype=bool)
                keep[evict_pos] = False
                self._buf = self._buf.mask(keep)
        return EventBatch.concat(outs) if outs else _empty_like(cur)

    def _sorted_order(self) -> np.ndarray:
        from siddhi_tpu.core.query import build_env

        env = build_env(self._buf)
        idx = np.arange(len(self._buf))
        for expr, asc in reversed(self.keys):
            col = np.broadcast_to(np.asarray(expr.fn(env)), (len(self._buf),))
            _, dense = np.unique(col[idx], return_inverse=True)
            order = np.argsort(dense if asc else -dense, kind="stable")
            idx = idx[order]
        return idx

    def buffered(self) -> Optional[EventBatch]:
        return self._buf

    def snapshot(self):
        return {"buf": self._buf}

    def restore(self, state):
        self._buf = state["buf"]


@extension("window", "frequent")
class FrequentWindow(WindowProcessor):
    """Misra-Gries frequent-event window (reference:
    FrequentWindowProcessor): keeps events whose key is among the N
    highest-frequency keys; evicted keys' events expire."""

    PARAMETERS = (Param('event.count', _INTS),
                  Param('attribute'))
    OVERLOADS = (('event.count',),
                 ('event.count', 'attribute', REPEAT))

    def __init__(self, args, attribute_names):
        super().__init__(args, attribute_names)
        self.n = self._const_int(args[0], "frequent count")
        self.key_exprs = list(args[1:])  # empty: whole-row key
        self.attribute_names = attribute_names
        self._counts: Dict = {}
        self._rows: Dict = {}  # key -> latest row (1-row EventBatch)

    def _key_of(self, row: EventBatch):
        from siddhi_tpu.core.query import build_env

        def unbox(v):
            return v.item() if isinstance(v, np.generic) else v

        if self.key_exprs:
            env = build_env(row)
            return tuple(
                unbox(np.asarray(e.fn(env)).reshape(-1)[0]) for e in self.key_exprs
            )
        return tuple(unbox(row.columns[a][0]) for a in row.attribute_names)

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        cur = batch.only(ev.CURRENT)
        outs: List[EventBatch] = []
        for i in range(len(cur)):
            row = cur.take(np.asarray([i]))
            key = self._key_of(row)
            if key in self._counts:
                self._counts[key] += 1
                self._rows[key] = row
                outs.append(row)
            elif len(self._counts) < self.n:
                self._counts[key] = 1
                self._rows[key] = row
                outs.append(row)
            else:
                # decrement all; evict zeros (Misra-Gries)
                for k in list(self._counts):
                    self._counts[k] -= 1
                    if self._counts[k] == 0:
                        del self._counts[k]
                        evict = self._rows.pop(k).with_types(ev.EXPIRED)
                        evict.timestamps = np.full(1, now, dtype=np.int64)
                        outs.append(evict)
        return EventBatch.concat(outs) if outs else _empty_like(cur)

    def snapshot(self):
        return {"counts": self._counts, "rows": self._rows}

    def restore(self, state):
        self._counts, self._rows = state["counts"], state["rows"]


@extension("window", "lossyFrequent")
class LossyFrequentWindow(WindowProcessor):
    """Lossy-counting frequent window (reference:
    LossyFrequentWindowProcessor(support, [error], keys...))."""

    PARAMETERS = (Param('support.threshold', _FLOATS),
                  Param('error.bound', _FLOATS),
                  Param('attribute'))
    OVERLOADS = (('support.threshold',),
                 ('support.threshold', 'error.bound'),
                 ('support.threshold', 'error.bound', 'attribute', REPEAT))

    def __init__(self, args, attribute_names):
        super().__init__(args, attribute_names)
        self.support = float(args[0].fn({}))
        i = 1
        self.error = self.support / 10.0
        if len(args) > 1:
            try:
                v = args[1].fn({})
                if isinstance(v, (float, np.floating)):
                    self.error = float(v)
                    i = 2
            except Exception as e:
                # arg 2 is an attribute expression, not an error-bound
                # const — expected overload ambiguity; traced so no
                # construction fault vanishes silently
                import logging

                logging.getLogger("siddhi_tpu").debug(
                    "lossyFrequent window: arg 2 is not an error-bound "
                    "const (%s); defaulting error to support/10", e)
        self.key_exprs = list(args[i:])
        self.attribute_names = attribute_names
        self._counts: Dict = {}
        self._deltas: Dict = {}
        self._rows: Dict = {}
        self._total = 0

    _key_of = FrequentWindow._key_of

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        cur = batch.only(ev.CURRENT)
        outs: List[EventBatch] = []
        for i in range(len(cur)):
            self._total += 1
            bucket = int(np.ceil(self._total * self.error))
            row = cur.take(np.asarray([i]))
            key = self._key_of(row)
            if key in self._counts:
                self._counts[key] += 1
            else:
                self._counts[key] = 1
                self._deltas[key] = bucket - 1
            self._rows[key] = row
            # emit current if above support threshold
            if self._counts[key] >= (self.support - self.error) * self._total:
                outs.append(row)
            # periodic pruning
            for k in list(self._counts):
                if self._counts[k] + self._deltas[k] <= bucket:
                    del self._counts[k]
                    self._deltas.pop(k, None)
                    evict = self._rows.pop(k).with_types(ev.EXPIRED)
                    evict.timestamps = np.full(1, now, dtype=np.int64)
                    outs.append(evict)
        return EventBatch.concat(outs) if outs else _empty_like(cur)

    def snapshot(self):
        return {
            "counts": self._counts, "deltas": self._deltas,
            "rows": self._rows, "total": self._total,
        }

    def restore(self, state):
        self._counts = state["counts"]
        self._deltas = state["deltas"]
        self._rows = state["rows"]
        self._total = state["total"]


@extension("window", "hopping")
class HoppingWindow(WindowProcessor):
    """Hopping window ``#window.hopping(windowTime, hopTime)``: every
    ``hopTime`` emits the pane of events whose timestamps fall within the
    trailing ``windowTime``; with overlap (hop < window) an event appears
    in multiple panes, and ``hop == window`` degenerates to the tumbling
    ``timeBatch``.  Each boundary expires the previous pane wholesale and
    precedes the new pane with a RESET marker, mirroring
    TimeBatchWindowProcessor's previous-flush expiry.

    Reference: query/processor/stream/window/HopingWindowProcessor.java —
    an abstract HOP-mode SPI base with no concrete subclass in-core; this
    is the concrete realization (pane boundary = the reference's
    ``_hopingTimestamp`` grouping key, carried here as the EXPIRED/RESET
    timestamps)."""

    PARAMETERS = (Param('window.time', _INTS),
                  Param('hop.time', _INTS))
    OVERLOADS = (('window.time', 'hop.time'),)

    needs_scheduler = True
    is_batch = True

    def __init__(self, args, attribute_names):
        super().__init__(args, attribute_names)
        if len(args) != 2:
            raise SiddhiAppCreationError(
                "hopping window needs (windowTime, hopTime), "
                f"got {len(args)} args")
        self.window_ms = self._const_int(args[0], "hopping window duration")
        self.hop_ms = self._const_int(args[1], "hopping window hop")
        if self.window_ms <= 0 or self.hop_ms <= 0:
            raise SiddhiAppCreationError(
                "hopping window duration and hop must be positive")
        self._buffer: Optional[EventBatch] = None
        self._last_pane: Optional[EventBatch] = None
        self._boundary: Optional[int] = None  # next pane-emission time

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        cur = batch.only(ev.CURRENT)
        if self._buffer is None:
            self._buffer = _empty_like(cur)
        if self._boundary is None and len(cur):
            self._boundary = int(cur.timestamps[0]) + self.window_ms
        out = self._maybe_flush(now)
        if len(cur):
            self._buffer = EventBatch.concat([self._buffer, cur])
            if self._boundary is None:
                # flush above went idle; this arrival starts a new window
                self._boundary = int(cur.timestamps[0]) + self.window_ms
        return out if out is not None else _empty_like(cur)

    def _maybe_flush(self, now: int) -> Optional[EventBatch]:
        if self._boundary is None or now < self._boundary:
            return None
        outs: List[EventBatch] = []
        while self._boundary is not None and now >= self._boundary:
            b = self._boundary
            ts = self._buffer.timestamps
            # pane covers [b - window, b): a boundary-timestamped event
            # belongs to the NEXT pane, exactly like timeBatch's flush
            pane = self._buffer.mask((ts >= b - self.window_ms) & (ts < b))
            # evict rows that can never appear in a later pane
            self._buffer = self._buffer.mask(
                ts >= b + self.hop_ms - self.window_ms)
            if self._last_pane is not None and len(self._last_pane):
                exp = self._last_pane.with_types(ev.EXPIRED)
                exp.timestamps = np.full(len(exp), b, dtype=np.int64)
                outs.append(exp)
            if len(pane) or (self._last_pane is not None and len(self._last_pane)):
                outs.append(reset_marker(pane, b))
            if len(pane):
                outs.append(pane)
            self._last_pane = pane
            if len(self._buffer) == 0 and len(pane) == 0:
                self._boundary = None  # go idle until next event
            else:
                self._boundary += self.hop_ms
        return EventBatch.concat(outs) if outs else None

    def on_time(self, now: int) -> Optional[EventBatch]:
        return self._maybe_flush(now)

    def next_wakeup(self) -> Optional[int]:
        return self._boundary

    def buffered(self) -> Optional[EventBatch]:
        return self._buffer

    def snapshot(self):
        return {"buffer": self._buffer, "last": self._last_pane,
                "boundary": self._boundary}

    def restore(self, state):
        self._buffer, self._last_pane, self._boundary = (
            state["buffer"], state["last"], state["boundary"]
        )


@extension("window", "batch")
class BatchWindow(WindowProcessor):
    """Chunk-per-arrival window (reference: BatchWindowProcessor): each
    arriving chunk expires the previous chunk."""

    PARAMETERS = ()
    OVERLOADS = ((),)

    is_batch = True

    def __init__(self, args, attribute_names):
        super().__init__(args, attribute_names)
        self._last: Optional[EventBatch] = None

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        cur = batch.only(ev.CURRENT)
        if len(cur) == 0:
            return cur
        outs: List[EventBatch] = []
        if self._last is not None and len(self._last):
            exp = self._last.with_types(ev.EXPIRED)
            exp.timestamps = np.full(len(exp), now, dtype=np.int64)
            outs.append(exp)
        outs.append(reset_marker(cur, now))
        outs.append(cur)
        self._last = cur
        return EventBatch.concat(outs)

    def buffered(self) -> Optional[EventBatch]:
        return self._last

    def snapshot(self):
        return {"last": self._last}

    def restore(self, state):
        self._last = state["last"]


@extension("window", "session")
class SessionWindow(WindowProcessor):
    """Session window with gap timeout (reference:
    SessionWindowProcessor(gap, [key])): events buffer per session key;
    a session closes when no event arrives for ``gap`` ms, expiring its
    events."""

    PARAMETERS = (Param('window.session', _INTS),
                  Param('window.key'))
    OVERLOADS = (('window.session',),
                 ('window.session', 'window.key'))

    needs_scheduler = True
    is_batch = True

    def __init__(self, args, attribute_names):
        super().__init__(args, attribute_names)
        self.gap_ms = self._const_int(args[0], "session gap")
        self.key_expr = args[1] if len(args) > 1 else None
        self._sessions: Dict = {}  # key -> (EventBatch, last_ts)

    def _keys(self, batch: EventBatch) -> List:
        from siddhi_tpu.core.query import build_env

        if self.key_expr is None:
            return [None] * len(batch)
        col = np.broadcast_to(
            np.asarray(self.key_expr.fn(build_env(batch))), (len(batch),)
        )
        return [v.item() if isinstance(v, np.generic) else v for v in col]

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        cur = batch.only(ev.CURRENT)
        outs: List[EventBatch] = []
        exp = self._close_due(now)
        if exp is not None:
            outs.append(exp)
        keys = self._keys(cur)
        for i in range(len(cur)):
            row = cur.take(np.asarray([i]))
            k = keys[i]
            buf, _ = self._sessions.get(k, (None, 0))
            buf = row if buf is None else EventBatch.concat([buf, row])
            self._sessions[k] = (buf, int(row.timestamps[0]))
            outs.append(row)
        return EventBatch.concat(outs) if outs else _empty_like(cur)

    def _close_due(self, now: int) -> Optional[EventBatch]:
        closed: List[EventBatch] = []
        for k, (buf, last_ts) in list(self._sessions.items()):
            if last_ts + self.gap_ms <= now:
                exp = buf.with_types(ev.EXPIRED)
                exp.timestamps = np.full(len(exp), now, dtype=np.int64)
                closed.append(exp)
                del self._sessions[k]
        return EventBatch.concat(closed) if closed else None

    def on_time(self, now: int) -> Optional[EventBatch]:
        return self._close_due(now)

    def next_wakeup(self) -> Optional[int]:
        if not self._sessions:
            return None
        return min(last + self.gap_ms for _, last in self._sessions.values())

    def snapshot(self):
        return {"sessions": self._sessions}

    def restore(self, state):
        self._sessions = state["sessions"]


@extension("window", "cron")
class CronWindow(WindowProcessor):
    """Cron-scheduled tumbling batch window (reference:
    CronWindowProcessor.java:187-225 dispatchEvents): events are held
    until the cron expression fires; at each fire the previous batch is
    expired (timestamped at fire time) and the held batch is emitted as
    CURRENT, becoming the next expired set."""

    PARAMETERS = (Param('cron.expression', (AttrType.STRING,)),)
    OVERLOADS = (('cron.expression',),)

    needs_scheduler = True
    is_batch = True

    def __init__(self, args, attribute_names):
        super().__init__(args, attribute_names)
        from siddhi_tpu.core.trigger import CronSchedule

        expr = args[0].fn({})
        if not isinstance(expr, str):
            raise SiddhiAppCreationError("cron window expects a cron-expression string")
        self._cron = CronSchedule(expr)
        self._pending: Optional[EventBatch] = None
        self._last_flushed: Optional[EventBatch] = None
        self._next_fire: Optional[int] = None

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        cur = batch.only(ev.CURRENT)
        if self._pending is None:
            self._pending = _empty_like(cur)
        if self._next_fire is None:
            self._next_fire = self._cron.next_fire(now)
        if len(cur):
            self._pending = EventBatch.concat([self._pending, cur])
        return _empty_like(cur)

    def on_time(self, now: int) -> Optional[EventBatch]:
        if self._next_fire is None or now < self._next_fire:
            return None
        fire = self._next_fire
        self._next_fire = self._cron.next_fire(now)
        if len(self._pending or ()) == 0 and len(self._last_flushed or ()) == 0:
            return None
        outs: List[EventBatch] = []
        if self._last_flushed is not None and len(self._last_flushed):
            exp = self._last_flushed.with_types(ev.EXPIRED)
            exp.timestamps = np.full(len(exp), fire, dtype=np.int64)
            outs.append(exp)
            outs.append(reset_marker(self._last_flushed, fire))
        flush = self._pending
        if len(flush):
            outs.append(flush)
        self._last_flushed = flush
        self._pending = _empty_like(flush)
        return EventBatch.concat(outs) if outs else None

    def next_wakeup(self) -> Optional[int]:
        return self._next_fire

    def buffered(self) -> Optional[EventBatch]:
        return self._pending

    def snapshot(self):
        return {"pending": self._pending, "last": self._last_flushed, "next": self._next_fire}

    def restore(self, state):
        self._pending, self._last_flushed, self._next_fire = (
            state["pending"], state["last"], state["next"]
        )


class _WindowExprEval:
    """Evaluator for expression/expressionBatch window retention
    expressions (reference: ExpressionWindowProcessor.java:68-103).

    The expression string is parsed with the SiddhiQL expression grammar
    and evaluated against the current buffer: bare attributes and
    ``last.attr`` read the newest event, ``first.attr`` the oldest;
    ``count()``, ``sum/min/max/avg(attr)`` aggregate over the buffer;
    ``eventTimestamp(first|last)`` reads buffer timestamps."""

    _AGGS = {"sum": np.sum, "min": np.min, "max": np.max, "avg": np.mean}

    def __init__(self, expr_string: str, attribute_names: List[str]):
        from siddhi_tpu.compiler.parser import Parser
        from siddhi_tpu.compiler.tokenizer import tokenize
        from siddhi_tpu.query_api import expression as X

        self.X = X
        self.attribute_names = set(attribute_names)
        toks = tokenize(expr_string)
        self.ast = Parser(toks).parse_expression()
        self._validate(self.ast)

    def _validate(self, e):
        """Reject unknown attributes at app-creation time, not on the
        first event."""
        X = self.X
        if isinstance(e, X.Variable):
            # first/last refs and bare names must be stream attributes;
            # bare 'first'/'last' only appear as eventTimestamp() args,
            # which are handled before recursion below
            if e.stream_id in (None, "first", "last") and e.attribute not in self.attribute_names:
                raise SiddhiAppCreationError(
                    f"expression window: unknown attribute '{e.attribute}'")
            return
        if isinstance(e, X.FunctionCall):
            if e.name == "eventTimestamp":
                return  # args are first/last selectors, not attributes
            for a in e.args:
                self._validate(a)
            return
        for attr in ("left", "right", "expr"):
            child = getattr(e, attr, None)
            if isinstance(child, X.Expression):
                self._validate(child)

    def __call__(self, buf: EventBatch, start: int = 0) -> bool:
        """Evaluate over ``buf[start:]`` without materializing a copy —
        numpy slices below are views, so eviction scans stay O(n)."""
        if len(buf) - start <= 0:
            return True
        return bool(self._ev(self.ast, buf, start))

    def _col(self, buf: EventBatch, attr: str, pos: int, start: int):
        if attr not in buf.columns:
            raise SiddhiAppCreationError(f"expression window: unknown attribute '{attr}'")
        return buf.columns[attr][start if pos == 0 else -1]

    def _ev(self, e, buf: EventBatch, start: int):
        X = self.X
        if isinstance(e, X.Constant):
            return e.value
        if isinstance(e, X.TimeConstant):
            return e.value
        if isinstance(e, X.Variable):
            if e.stream_id in ("first", "last"):
                return self._col(buf, e.attribute, 0 if e.stream_id == "first" else -1, start)
            if e.stream_id is None:
                return self._col(buf, e.attribute, -1, start)
            raise SiddhiAppCreationError(
                f"expression window: unsupported reference '{e.stream_id}.{e.attribute}'")
        if isinstance(e, X.FunctionCall):
            name = e.name
            if name == "count":
                return len(buf) - start
            if name == "eventTimestamp":
                if e.args and isinstance(e.args[0], X.Variable):
                    which = e.args[0].attribute
                    return int(buf.timestamps[start if which == "first" else -1])
                return int(buf.timestamps[-1])
            if name in self._AGGS:
                arg = e.args[0]
                if not isinstance(arg, X.Variable) or arg.stream_id is not None:
                    raise SiddhiAppCreationError(
                        "expression window aggregates take a plain attribute")
                if arg.attribute not in buf.columns:
                    raise SiddhiAppCreationError(
                        f"expression window: unknown attribute '{arg.attribute}'")
                col = buf.columns[arg.attribute][start:]
                return self._AGGS[name](col) if len(col) else 0
            raise SiddhiAppCreationError(
                f"expression window: unsupported function '{name}()'")
        if isinstance(e, X.ArithmeticOp):
            a, b = self._ev(e.left, buf, start), self._ev(e.right, buf, start)
            if e.op == "+":
                return a + b
            if e.op == "-":
                return a - b
            if e.op == "*":
                return a * b
            if e.op == "/":
                return a / b
            return a % b
        if isinstance(e, X.CompareOp):
            a, b = self._ev(e.left, buf, start), self._ev(e.right, buf, start)
            op = e.op
            if op == "==":
                return a == b
            if op == "!=":
                return a != b
            if op == "<":
                return a < b
            if op == "<=":
                return a <= b
            if op == ">":
                return a > b
            return a >= b
        if isinstance(e, X.AndOp):
            return bool(self._ev(e.left, buf, start)) and bool(self._ev(e.right, buf, start))
        if isinstance(e, X.OrOp):
            return bool(self._ev(e.left, buf, start)) or bool(self._ev(e.right, buf, start))
        if isinstance(e, X.NotOp):
            return not bool(self._ev(e.expr, buf, start))
        if isinstance(e, X.IsNull):
            return self._ev(e.expr, buf, start) is None
        raise SiddhiAppCreationError(
            f"expression window: unsupported expression node {type(e).__name__}")


@extension("window", "expression")
class ExpressionWindow(WindowProcessor):
    """Sliding window retained by an expression (reference:
    ExpressionWindowProcessor.java:68-103): each arrival is appended,
    then events are expired from the oldest until the expression holds
    over the remaining buffer.

    Inherently sequential host-side operator (retention depends on each
    prior decision): O(buffer) per arrival; eviction scans use offset
    views, not copies."""

    PARAMETERS = (Param('expression', (AttrType.STRING,)),)
    OVERLOADS = (('expression',),)

    def __init__(self, args, attribute_names):
        super().__init__(args, attribute_names)
        expr = args[0].fn({})
        if not isinstance(expr, str):
            raise SiddhiAppCreationError("expression window expects a string expression")
        self._eval = _WindowExprEval(expr, attribute_names)
        self._buf: Optional[EventBatch] = None

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        cur = batch.only(ev.CURRENT)
        if self._buf is None:
            self._buf = _empty_like(cur)
        outs: List[EventBatch] = []
        for i in range(len(cur)):
            row = cur.take(np.asarray([i]))
            self._buf = EventBatch.concat([self._buf, row])
            n_evict = 0
            while len(self._buf) - n_evict > 0 and not self._eval(self._buf, n_evict):
                n_evict += 1
            if n_evict:
                evict = self._buf.take(np.arange(n_evict)).with_types(ev.EXPIRED)
                evict.timestamps = np.full(len(evict), now, dtype=np.int64)
                outs.append(evict)
                self._buf = self._buf.take(np.arange(n_evict, len(self._buf)))
            outs.append(row)
        return EventBatch.concat(outs) if outs else _empty_like(cur)

    def buffered(self) -> Optional[EventBatch]:
        return self._buf

    def snapshot(self):
        return {"buf": self._buf}

    def restore(self, state):
        self._buf = state["buf"]


@extension("window", "expressionBatch")
class ExpressionBatchWindow(WindowProcessor):
    """Tumbling window flushed when the expression fails (reference:
    ExpressionBatchWindowProcessor.java:68-147): events accumulate while
    the expression (evaluated including the arriving event) holds; on
    failure the batch is flushed — previous flush expired, RESET, new
    CURRENT batch.  ``include.triggering.event`` puts the triggering
    event into the flushed batch; ``stream.current.event`` streams
    arrivals through immediately and only expires in batches."""

    PARAMETERS = (Param('expression', (AttrType.STRING,)),
                  Param('include.triggering.event', (AttrType.BOOL,)),
                  Param('stream.current.event', (AttrType.BOOL,)))
    OVERLOADS = (('expression',),
                 ('expression', 'include.triggering.event'),
                 ('expression', 'include.triggering.event', 'stream.current.event'))

    is_batch = True

    def __init__(self, args, attribute_names):
        super().__init__(args, attribute_names)
        expr = args[0].fn({})
        if not isinstance(expr, str):
            raise SiddhiAppCreationError("expressionBatch window expects a string expression")
        self._eval = _WindowExprEval(expr, attribute_names)
        self.include_triggering = bool(args[1].fn({})) if len(args) > 1 else False
        self.stream_current = bool(args[2].fn({})) if len(args) > 2 else False
        self._buf: Optional[EventBatch] = None
        self._last_flushed: Optional[EventBatch] = None

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        cur = batch.only(ev.CURRENT)
        if self._buf is None:
            self._buf = _empty_like(cur)
        outs: List[EventBatch] = []
        for i in range(len(cur)):
            row = cur.take(np.asarray([i]))
            if self.stream_current:
                outs.append(row)
            with_row = EventBatch.concat([self._buf, row])
            if self._eval(with_row):
                self._buf = with_row
                continue
            # expression failed including the arriving event -> flush
            if self.include_triggering:
                flush, rest = with_row, _empty_like(cur)
            else:
                flush, rest = self._buf, row
            outs.extend(self._flush(flush, now))
            self._buf = rest
        return EventBatch.concat(outs) if outs else _empty_like(cur)

    def _flush(self, flush: EventBatch, now: int) -> List[EventBatch]:
        outs: List[EventBatch] = []
        if self._last_flushed is not None and len(self._last_flushed):
            exp = self._last_flushed.with_types(ev.EXPIRED)
            exp.timestamps = np.full(len(exp), now, dtype=np.int64)
            outs.append(exp)
        if len(flush) or (self._last_flushed is not None and len(self._last_flushed)):
            outs.append(reset_marker(flush, now))
        if len(flush) and not self.stream_current:
            outs.append(flush)
        self._last_flushed = flush
        return outs

    def buffered(self) -> Optional[EventBatch]:
        return self._buf

    def snapshot(self):
        return {"buf": self._buf, "last": self._last_flushed}

    def restore(self, state):
        self._buf, self._last_flushed = state["buf"], state["last"]
