"""Window processors.

Re-design of the reference's 30 window implementations
(query/processor/stream/window/*WindowProcessor.java) as columnar
operators: each window keeps buffered rows as arrays and, per input
batch, returns a combined batch of CURRENT (arrivals) and EXPIRED
(evictions) events plus optional RESET markers for batch windows.
Downstream aggregators add CURRENT rows and subtract EXPIRED rows, which
reproduces the reference's windowed-aggregation semantics.

Time-driven windows receive ``on_time(now)`` ticks from the scheduler
(watermark-driven in playback mode).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core import event as ev
from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.extension.registry import extension
from siddhi_tpu.planner.expr import CompiledExpression


class WindowProcessor:
    """Base window operator.

    ``process(batch, now)`` -> output batch (CURRENT + EXPIRED [+ RESET]).
    ``on_time(now)`` -> output batch for scheduler ticks (time windows).
    ``next_wakeup()`` -> absolute ms when a tick is needed, or None.
    """

    needs_scheduler = False

    def __init__(self, args: List[CompiledExpression], attribute_names: List[str]):
        self.args = args
        self.attribute_names = attribute_names

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        raise NotImplementedError

    def on_time(self, now: int) -> Optional[EventBatch]:
        return None

    def next_wakeup(self) -> Optional[int]:
        return None

    # findable-processor surface for joins / on-demand queries
    def buffered(self) -> Optional[EventBatch]:
        return None

    def snapshot(self) -> Dict:
        return {}

    def restore(self, state: Dict):
        pass

    @staticmethod
    def _const_int(c: CompiledExpression, what: str) -> int:
        try:
            return int(c.fn({}))
        except Exception as e:
            raise SiddhiAppCreationError(f"{what} must be a constant") from e


def _empty_like(b: EventBatch) -> EventBatch:
    return EventBatch(
        b.stream_id,
        b.attribute_names,
        {k: v[:0] for k, v in b.columns.items()},
        b.timestamps[:0],
        b.types[:0],
    )


def reset_marker(template: EventBatch, now: int) -> EventBatch:
    """One-row RESET event (default-valued data) telling downstream
    aggregators to clear state — the ComplexEvent.Type.RESET analog."""
    cols = {}
    for k, v in template.columns.items():
        if v.dtype == object:
            col = np.empty(1, dtype=object)
            col[0] = None
        else:
            col = np.zeros(1, dtype=v.dtype)
        cols[k] = col
    return EventBatch(
        template.stream_id,
        template.attribute_names,
        cols,
        np.asarray([now], dtype=np.int64),
        np.asarray([ev.RESET], dtype=np.int8),
    )


@extension("window", "length")
class LengthWindow(WindowProcessor):
    """Sliding length window (reference: LengthWindowProcessor).

    Keeps the last N events; each arrival beyond capacity expires the
    oldest buffered event.
    """

    def __init__(self, args, attribute_names):
        super().__init__(args, attribute_names)
        self.length = self._const_int(args[0], "length window size")
        self._buf: Optional[EventBatch] = None

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        cur = batch.only(ev.CURRENT)
        if self._buf is None:
            self._buf = _empty_like(cur)
        prev_len = len(self._buf)
        combined = EventBatch.concat([self._buf, cur])
        n_total = len(combined)
        n_over = max(0, n_total - self.length)
        self._buf = combined.take(np.arange(n_over, n_total))
        if n_over == 0:
            return cur
        # interleave so each arrival's eviction directly precedes it
        # (reference inserts the evicted clone before the current event,
        # LengthWindowProcessor), keeping aggregate subtract-then-add order
        order: List[int] = []
        types: List[int] = []
        for i in range(len(cur)):
            evict_idx = prev_len + i - self.length
            if evict_idx >= 0:
                order.append(evict_idx)
                types.append(ev.EXPIRED)
            order.append(prev_len + i)
            types.append(ev.CURRENT)
        out = combined.take(np.asarray(order))
        out.types = np.asarray(types, dtype=np.int8)
        out.timestamps = np.where(
            out.types == ev.EXPIRED, now, out.timestamps
        ).astype(np.int64)
        return out

    def buffered(self) -> Optional[EventBatch]:
        return self._buf

    def snapshot(self):
        return {"buf": self._buf}

    def restore(self, state):
        self._buf = state["buf"]


@extension("window", "lengthBatch")
class LengthBatchWindow(WindowProcessor):
    """Tumbling length window (reference: LengthBatchWindowProcessor).

    Collects N events, then flushes them as CURRENT while expiring the
    previous batch; emits a RESET marker before each flush so downstream
    aggregators restart per batch.
    """

    is_batch = True  # selector emits last-row-per-group (ProcessingMode.BATCH)

    def __init__(self, args, attribute_names):
        super().__init__(args, attribute_names)
        self.length = self._const_int(args[0], "lengthBatch window size")
        self._pending: Optional[EventBatch] = None
        self._last_flushed: Optional[EventBatch] = None

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        cur = batch.only(ev.CURRENT)
        if self._pending is None:
            self._pending = _empty_like(cur)
        self._pending = EventBatch.concat([self._pending, cur])
        outs: List[EventBatch] = []
        while len(self._pending) >= self.length:
            flush = self._pending.take(np.arange(self.length))
            self._pending = self._pending.take(
                np.arange(self.length, len(self._pending))
            )
            if self._last_flushed is not None and len(self._last_flushed):
                exp = self._last_flushed.with_types(ev.EXPIRED)
                exp.timestamps = np.full(len(exp), now, dtype=np.int64)
                outs.append(exp)
            # RESET clears batch aggregators between tumbles
            outs.append(reset_marker(cur, now))
            outs.append(flush)
            self._last_flushed = flush
        if not outs:
            return _empty_like(cur)
        return EventBatch.concat(outs)

    def buffered(self) -> Optional[EventBatch]:
        return self._pending

    def snapshot(self):
        return {"pending": self._pending, "last": self._last_flushed}

    def restore(self, state):
        self._pending = state["pending"]
        self._last_flushed = state["last"]
