"""REST microservice wrapper: deploy/undeploy SiddhiQL apps over HTTP.

Re-design of the reference ``modules/siddhi-service``
(SiddhiApiServiceImpl.java:51 deploy, :100 undeploy) on the stdlib HTTP
server instead of MSF4J:

    POST /siddhi-artifact-deploy            body = SiddhiQL app string
    GET  /siddhi-artifact-undeploy/{name}
    GET  /siddhi-apps                       (list deployed app names)
    GET  /siddhi-persist/{name}             (checkpoint; @app:persist mode)
    GET  /siddhi-restore-last/{name}        (restore newest good revision)
    GET  /siddhi-trace/{name}               (flight recorder; ?format=chrome)
    GET  /siddhi-plan/{name}                (per-query plan: candidates,
                                             costs, pins, re-plan history)
    GET  /siddhi-replan/{name}?q0=path      (force a live re-lowering;
                                             pairs pin per-query paths)
    GET  /siddhi-health/{name}              (overload-protection health:
                                             200 healthy / 503 shedding,
                                             open breaker or wedged)
    GET  /metrics                           (Prometheus text exposition)

Responses are JSON ``{"status": "OK"|"ERROR", "message": ...}`` except
``/metrics`` (Prometheus text) and ``/siddhi-trace?format=chrome``
(raw Chrome ``chrome://tracing`` JSON array).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.parse import parse_qs, urlsplit

from siddhi_tpu.core.manager import SiddhiManager
from siddhi_tpu.observability.prometheus import (
    CONTENT_TYPE as PROMETHEUS_CONTENT_TYPE,
    app_histogram_entries,
    render_prometheus,
)


class SiddhiService:
    """In-process deploy/undeploy service around one SiddhiManager."""

    def __init__(self, manager: Optional[SiddhiManager] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.manager = manager or SiddhiManager()
        self._runtimes: Dict[str, object] = {}
        self._lock = threading.Lock()
        service = self

        class Handler(BaseHTTPRequestHandler):
            # per-request socket timeout: a stalled client (or a wedge
            # downstream of a blocking read) must not pin one of the
            # server's threads forever
            timeout = 10

            def log_message(self, *args):  # quiet test output
                pass

            def _send(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self._send_raw(code, body, "application/json")

            def _send_raw(self, code: int, body: bytes, content_type: str):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                if self.path.rstrip("/") != "/siddhi-artifact-deploy":
                    self._send(404, {"status": "ERROR", "message": "not found"})
                    return
                length = int(self.headers.get("Content-Length", "0"))
                app_str = self.rfile.read(length).decode("utf-8")
                code, payload = service.deploy(app_str)
                self._send(code, payload)

            def do_GET(self):
                url = urlsplit(self.path)
                parts = url.path.rstrip("/").split("/")
                if url.path.rstrip("/") == "/metrics":
                    self._send_raw(200, service.metrics_text().encode(),
                                   PROMETHEUS_CONTENT_TYPE)
                    return
                if len(parts) == 3 and parts[1] == "siddhi-trace":
                    fmt = parse_qs(url.query).get("format", [""])[0]
                    code, payload = service.trace(parts[2], fmt)
                    if code == 200 and fmt == "chrome":
                        self._send_raw(code, json.dumps(payload).encode(),
                                       "application/json")
                    else:
                        self._send(code, payload)
                    return
                if len(parts) == 3 and parts[1] == "siddhi-artifact-undeploy":
                    code, payload = service.undeploy(parts[2])
                    self._send(code, payload)
                elif len(parts) == 3 and parts[1] == "siddhi-pattern-state":
                    code, payload = service.pattern_state(parts[2])
                    self._send(code, payload)
                elif len(parts) == 3 and parts[1] == "siddhi-query-lowering":
                    code, payload = service.query_lowering(parts[2])
                    self._send(code, payload)
                elif len(parts) == 3 and parts[1] == "siddhi-plan":
                    code, payload = service.plan(parts[2])
                    self._send(code, payload)
                elif len(parts) == 3 and parts[1] == "siddhi-replan":
                    pins = {k: v[0]
                            for k, v in parse_qs(url.query).items()}
                    code, payload = service.replan(parts[2], pins)
                    self._send(code, payload)
                elif len(parts) == 3 and parts[1] == "siddhi-health":
                    code, payload = service.health(parts[2])
                    self._send(code, payload)
                elif len(parts) == 3 and parts[1] == "siddhi-statistics":
                    code, payload = service.statistics(parts[2])
                    self._send(code, payload)
                elif len(parts) == 3 and parts[1] == "siddhi-persist":
                    code, payload = service.persist(parts[2])
                    self._send(code, payload)
                elif len(parts) == 3 and parts[1] == "siddhi-restore-last":
                    code, payload = service.restore_last(parts[2])
                    self._send(code, payload)
                elif self.path.rstrip("/") == "/siddhi-apps":
                    self._send(200, {"status": "OK", "apps": service.app_names()})
                else:
                    self._send(404, {"status": "ERROR", "message": "not found"})

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- operations (also usable without HTTP) -------------------------------

    def deploy(self, app_str: str):
        """reference: SiddhiApiServiceImpl.siddhiArtifactDeployPost:51"""
        try:
            with self._lock:
                runtime = self.manager.create_siddhi_app_runtime(
                    app_str, register=False)
                if (runtime.name in self._runtimes
                        or self.manager.get_siddhi_app_runtime(runtime.name)
                        is not None):
                    # also reject apps registered directly on the shared
                    # manager: silently replacing that registration would
                    # leave the old runtime running untracked
                    runtime.shutdown()
                    return 409, {
                        "status": "ERROR",
                        "message": f"Siddhi app '{runtime.name}' already exists",
                    }
                try:
                    runtime.start()
                except Exception:
                    runtime.shutdown()
                    raise
                # register only once start() succeeded, so a failed deploy
                # does not squat the name
                self.manager._app_runtimes[runtime.name] = runtime
                self._runtimes[runtime.name] = runtime
            return 200, {
                "status": "OK",
                "message": "Siddhi app is deployed and runtime is created",
                "name": runtime.name,
            }
        except Exception as e:  # noqa: BLE001 — surface planning errors to client
            return 400, {"status": "ERROR", "message": str(e)}

    def undeploy(self, name: str):
        """reference: SiddhiApiServiceImpl.siddhiArtifactUndeploySiddhiAppGet:100"""
        with self._lock:
            runtime = self._runtimes.pop(name, None)
        if runtime is None:
            return 404, {
                "status": "ERROR",
                "message": f"there is no Siddhi app named '{name}'",
            }
        runtime.shutdown()
        return 200, {"status": "OK", "message": f"Siddhi app '{name}' undeployed"}

    @staticmethod
    def _overload_503(name: str, runtime):
        """503 + the health report when the target app is shedding, has
        an open breaker, or is wedged — for routes that would otherwise
        BLOCK on the app's process lock.  None when the app (or an app
        without @app:limits) can serve the request now."""
        if getattr(runtime.app_context, "robustness", None) is None:
            return None
        h = runtime.health()
        if h["healthy"]:
            return None
        return 503, {
            "status": "ERROR",
            "message": f"Siddhi app '{name}' is overloaded "
                       "(shedding, open breaker, or wedged) — "
                       "see /siddhi-health/" + name,
            "health": h,
        }

    def health(self, name: str):
        """Overload-protection health of a deployed app: admission
        budgets + shed counts, breaker states, watchdog and ladder
        state, and the full robustness counter block (the same live
        objects the statistics feed reads).  200 healthy / 503 not."""
        with self._lock:
            runtime = self._runtimes.get(name)
        if runtime is None:
            return 404, {
                "status": "ERROR",
                "message": f"there is no Siddhi app named '{name}'",
            }
        h = runtime.health()
        code = 200 if h["healthy"] else 503
        return code, {"status": "OK" if h["healthy"] else "UNHEALTHY", **h}

    def pattern_state(self, name: str):
        """Per-query pattern-engine occupancy of a deployed app (dense:
        partitions/instances/overflow; host: live instances)."""
        with self._lock:
            runtime = self._runtimes.get(name)
        if runtime is None:
            return 404, {
                "status": "ERROR",
                "message": f"there is no Siddhi app named '{name}'",
            }
        # pattern_state() takes the app lock — answer 503 with the
        # health report instead of parking the request thread behind a
        # shedding or wedged app
        busy = self._overload_503(name, runtime)
        if busy is not None:
            return busy
        return 200, {"status": "OK", "queries": runtime.pattern_state()}

    def query_lowering(self, name: str):
        """Per-query engine placement (host | dense | device) of a
        deployed app — which queries actually lowered to a device
        engine under @app:execution('tpu')."""
        with self._lock:
            runtime = self._runtimes.get(name)
        if runtime is None:
            return 404, {
                "status": "ERROR",
                "message": f"there is no Siddhi app named '{name}'",
            }
        return 200, {"status": "OK", "queries": runtime.lowering()}

    def statistics(self, name: str):
        """Metric feed of a deployed app — latency/throughput trackers
        plus the fault/recovery counters (registered ungated, so chaos
        and recovery events stay visible at statistics level 'off')."""
        with self._lock:
            runtime = self._runtimes.get(name)
        if runtime is None:
            return 404, {
                "status": "ERROR",
                "message": f"there is no Siddhi app named '{name}'",
            }
        return 200, {"status": "OK", "metrics": runtime.statistics()}

    def plan(self, name: str):
        """Chosen plan per query of a deployed app: the cost model's
        candidates with scores, the pick, the pin that forced it,
        rejected alternatives with reasons, and the live re-plan
        history (planner/costmodel.py PlanRecord)."""
        with self._lock:
            runtime = self._runtimes.get(name)
        if runtime is None:
            return 404, {
                "status": "ERROR",
                "message": f"there is no Siddhi app named '{name}'",
            }
        sm = runtime.app_context.statistics_manager
        plans = {}
        replans = []
        if sm is not None:
            plans = {q: rec.to_dict()
                     for q, rec in sorted(sm.plans.items())}
            replans = list(sm.replans)
        return 200, {"status": "OK", "app": name,
                     "lowering": runtime.lowering(),
                     "plans": plans, "replans": replans}

    def replan(self, name: str, pins: Optional[Dict[str, str]] = None):
        """Force a live re-lowering of a deployed app.  Query-string
        pairs pin per-query paths (``?q0=fuse%2Bshard``); with no pairs
        the cost model re-chooses every query.  Refused (409) without a
        full-history input journal — see SiddhiAppRuntime.replan."""
        with self._lock:
            runtime = self._runtimes.get(name)
        if runtime is None:
            return 404, {
                "status": "ERROR",
                "message": f"there is no Siddhi app named '{name}'",
            }
        busy = self._overload_503(name, runtime)
        if busy is not None:
            return busy
        try:
            lowering = runtime.replan(pins or {}, forced=True,
                                      reason="forced via REST")
        except Exception as e:  # noqa: BLE001 — surface refusals to client
            return 409, {"status": "ERROR", "message": str(e)}
        return 200, {"status": "OK", "queries": lowering}

    def persist(self, name: str):
        """Checkpoint a deployed app in its configured persist mode
        (@app:persist, default sync).  Async mode returns as soon as the
        capture lands — the revision commits on the checkpoint writer
        thread; poll /siddhi-statistics for persistCommits."""
        with self._lock:
            runtime = self._runtimes.get(name)
        if runtime is None:
            return 404, {
                "status": "ERROR",
                "message": f"there is no Siddhi app named '{name}'",
            }
        busy = self._overload_503(name, runtime)
        if busy is not None:
            return busy
        try:
            revision = runtime.persist()
        except Exception as e:  # noqa: BLE001 — surface persist errors to client
            return 500, {"status": "ERROR", "message": str(e)}
        return 200, {"status": "OK", "revision": revision,
                     "mode": runtime.app_context.persist_mode}

    def restore_last(self, name: str):
        """Restore the newest restorable revision of a deployed app
        (corrupt/torn revisions are walked past) and replay journaled
        post-checkpoint input."""
        with self._lock:
            runtime = self._runtimes.get(name)
        if runtime is None:
            return 404, {
                "status": "ERROR",
                "message": f"there is no Siddhi app named '{name}'",
            }
        try:
            revision = runtime.restore_last_revision()
        except Exception as e:  # noqa: BLE001 — surface restore errors to client
            return 500, {"status": "ERROR", "message": str(e)}
        if revision is None:
            return 404, {
                "status": "ERROR",
                "message": f"no persisted revision for app '{name}'",
            }
        return 200, {"status": "OK", "revision": revision}

    def trace(self, name: str, fmt: str = ""):
        """Flight-recorder feed of a deployed app: the live span ring
        plus the last crash dump (if any).  ``fmt='chrome'`` returns the
        ring as a Chrome ``chrome://tracing`` event array instead."""
        with self._lock:
            runtime = self._runtimes.get(name)
        if runtime is None:
            return 404, {
                "status": "ERROR",
                "message": f"there is no Siddhi app named '{name}'",
            }
        tracer = runtime.app_context.tracer
        if tracer is None:
            return 404, {
                "status": "ERROR",
                "message": f"tracing is off for app '{name}'",
            }
        if fmt == "chrome":
            return 200, tracer.recorder.chrome_trace()
        return 200, {
            "status": "OK",
            "app": name,
            "sample": tracer.sample,
            "trace": tracer.recorder.payload("live"),
            "last_dump": tracer.recorder.last_dump,
        }

    def metrics_text(self) -> str:
        """All deployed apps' metric feeds as one Prometheus
        text-exposition page (scrape target: GET /metrics)."""
        with self._lock:
            runtimes = sorted(self._runtimes.items())
        apps = []
        for name, rt in runtimes:
            sm = rt.app_context.statistics_manager
            apps.append((name, rt.statistics(),
                         app_histogram_entries(name, sm)))
        return render_prometheus(apps)

    def app_names(self):
        with self._lock:
            return sorted(self._runtimes)

    def get_runtime(self, name: str):
        with self._lock:
            return self._runtimes.get(name)

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="siddhi-service", daemon=True
        )
        self._thread.start()

    def stop(self):
        # HTTPServer.shutdown() blocks until serve_forever() acknowledges;
        # it deadlocks when the serving thread was never started.
        if self._thread is not None:
            self._server.shutdown()
            self._thread.join(timeout=5)
            self._thread = None
        self._server.server_close()
        with self._lock:
            runtimes, self._runtimes = dict(self._runtimes), {}
        for rt in runtimes.values():
            rt.shutdown()
