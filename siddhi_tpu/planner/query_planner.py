"""Query planner: Query AST -> QueryRuntime.

The analog of the reference QueryParser.parse (util/parser/QueryParser.java:90)
+ SingleInputStreamParser + SelectorParser + OutputParser, producing
columnar processors instead of per-event executor chains.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

from siddhi_tpu.core.exceptions import (
    DefinitionNotExistError,
    SiddhiAppCreationError,
)
from siddhi_tpu.core.query import (
    AggBinding,
    EventRateLimiter,
    GroupByEventRateLimiter,
    GroupByTimeRateLimiter,
    FilterProcessor,
    InsertIntoStreamCallback,
    PassThroughRateLimiter,
    ProcessStreamReceiver,
    QueryCallbackOutput,
    QueryRuntime,
    QuerySelector,
    SelectItem,
    SnapshotRateLimiter,
    TimeRateLimiter,
    WindowChainProcessor,
)
from siddhi_tpu.extension.validator import validate_extension_args
from siddhi_tpu.ops.aggregators import make_aggregator
from siddhi_tpu.planner.expr import (
    AGGREGATOR_NAMES,
    CompiledExpression,
    ExpressionCompiler,
    Scope,
)
from siddhi_tpu.query_api import (
    Annotation,
    ArithmeticOp,
    AndOp,
    Attribute,
    AttrType,
    CompareOp,
    Constant,
    Expression,
    Filter,
    FunctionCall,
    InOp,
    InsertIntoStream,
    IsNull,
    NotOp,
    OrOp,
    OutputAttribute,
    Query,
    ReturnStream,
    Selector,
    SingleInputStream,
    StreamDefinition,
    StreamFunction,
    Variable,
    WindowHandler,
)
from siddhi_tpu.query_api.annotation import find_annotation

_query_counter = itertools.count()


class _RateLimiterTask:
    """Scheduler task flushing time-based rate limiters.

    ``device_runtime`` (device-lowered queries): the query's device
    runtime — its pending-emit queue drains BEFORE the limiter's time
    decision, so queued matches land in the limiter in the same order
    the synchronous path would deliver them (async emit pipeline flush
    barrier)."""

    def __init__(self, qr, limiter, device_runtime=None):
        self.qr = qr
        self.limiter = limiter
        self.device_runtime = device_runtime

    def next_wakeup(self):
        return self.limiter.next_wakeup()

    def fire(self, now: int):
        if self.device_runtime is not None:
            self.device_runtime.drain()
        out = self.limiter.on_time(now)
        if out is not None and len(out):
            self.qr.output.send(out, now)


class _PatternStreamReceiver:
    """Junction subscriber feeding one source stream into the NFA
    (the Pattern/SequenceSingleProcessStreamReceiver analog)."""

    def __init__(self, processor, stream_key: str):
        self.processor = processor
        self.stream_key = stream_key

    def receive(self, batch):
        self.processor.process_stream_batch(self.stream_key, batch)


class AggregatorRewrite:
    """Walks a select expression, replacing aggregator calls with synthetic
    variables bound to aggregation outputs (the reference instead builds
    AttributeAggregatorExecutors inline in SelectorParser)."""

    def __init__(self, scope: Scope, compiler: ExpressionCompiler,
                 extensions=None):
        self.scope = scope
        self.compiler = compiler
        self.extensions = extensions
        self.bindings: List[AggBinding] = []

    def rewrite(self, expr: Expression) -> Expression:
        if isinstance(expr, FunctionCall):
            is_builtin = (expr.namespace is None
                          and expr.name in AGGREGATOR_NAMES)
            ext = None
            if not is_builtin and self.extensions is not None:
                # custom AttributeAggregatorExecutor analogs registered
                # via setExtension(..., kind='aggregator') (reference:
                # util/extension/holder/AttributeAggregatorExtensionHolder)
                ext = self.extensions.lookup(
                    "aggregator", expr.name, expr.namespace)
            if is_builtin or ext is not None:
                key = f"__agg_{len(self.bindings)}"
                arg: Optional[CompiledExpression] = None
                if expr.args:
                    if len(expr.args) > 1:
                        raise SiddhiAppCreationError(f"aggregator '{expr.name}' takes one argument")
                    arg = self.compiler.compile(self.rewrite(expr.args[0]))
                elif is_builtin and expr.name not in ("count",) and not expr.star:
                    raise SiddhiAppCreationError(f"aggregator '{expr.name}' needs an argument")
                if ext is not None:
                    import inspect

                    try:
                        params = [
                            p for p in
                            inspect.signature(ext).parameters.values()
                            if p.kind in (p.POSITIONAL_ONLY,
                                          p.POSITIONAL_OR_KEYWORD)
                        ]
                        takes_arg = len(params) >= 1
                    except (TypeError, ValueError):
                        takes_arg = True
                    executor = (ext(arg.type if arg is not None else None)
                                if takes_arg else ext())
                else:
                    executor = make_aggregator(expr.name, arg.type if arg is not None else None)
                self.bindings.append(AggBinding(key, executor, arg))
                self.scope.add_bare(key, executor.return_type)
                return Variable(attribute=key)
        if isinstance(expr, ArithmeticOp):
            return ArithmeticOp(expr.op, self.rewrite(expr.left), self.rewrite(expr.right))
        if isinstance(expr, CompareOp):
            return CompareOp(expr.op, self.rewrite(expr.left), self.rewrite(expr.right))
        if isinstance(expr, AndOp):
            return AndOp(self.rewrite(expr.left), self.rewrite(expr.right))
        if isinstance(expr, OrOp):
            return OrOp(self.rewrite(expr.left), self.rewrite(expr.right))
        if isinstance(expr, NotOp):
            return NotOp(self.rewrite(expr.expr))
        if isinstance(expr, IsNull):
            return IsNull(self.rewrite(expr.expr))
        if isinstance(expr, InOp):
            return InOp(self.rewrite(expr.expr), expr.source_id)
        if isinstance(expr, FunctionCall):
            return FunctionCall(
                expr.namespace, expr.name, tuple(self.rewrite(a) for a in expr.args), expr.star
            )
        return expr


def scope_for_definition(definition: StreamDefinition, stream_ref: str) -> Scope:
    scope = Scope()
    for a in definition.attributes:
        scope.add(stream_ref, a.name, a.name, a.type)
    return scope


class QueryPlanner:
    """Plans one query against the app's junction/definition registry."""

    def __init__(self, app_planner):
        self.app = app_planner  # AppPlanner
        # the PlanRecord for the query currently inside plan_query();
        # _want() consults it so the cost model's pick steers the same
        # gate sites the legacy annotations do
        self._active_record = None

    def _passthrough_selector(self, sel: Selector, out_names: List[str],
                              out_target: str) -> QuerySelector:
        """Column-passthrough selector applying only the query's
        order by / limit / offset over each chunk — the host tail of a
        device-lowered query (dense or device-single)."""
        order_by = []
        for ob in sel.order_by:
            if ob.variable.attribute not in out_names:
                raise SiddhiAppCreationError(
                    f"order by attribute '{ob.variable.attribute}' not "
                    "in select output")
            order_by.append((ob.variable.attribute, ob.ascending))
        const_compiler = ExpressionCompiler(Scope())
        limit = self._const_int(sel.limit, const_compiler, "limit")
        offset = self._const_int(sel.offset, const_compiler, "offset")
        return QuerySelector(
            out_target, None, out_names, [], [], None, order_by, limit,
            offset,
        )

    def _get_mesh(self, nd: int):
        """One app-wide device mesh, built on first use (shared by the
        dense pattern axis and the device-query group axis)."""
        mesh = getattr(self.app, "_tpu_mesh", None)
        if mesh is None:
            from siddhi_tpu.parallel import make_mesh

            mesh = make_mesh(nd)
            self.app._tpu_mesh = mesh
        return mesh

    def plan_query(self, query: Query, query_index: int) -> QueryRuntime:
        """Unified lowering entry: build the query's PlanRecord (cost
        candidates + pick), plan through the existing per-kind paths
        with the record steering the fast-path gates, then pin the
        realized lowering back onto the record for /siddhi-plan.

        In legacy (annotation-only) mode the record is informational —
        _want() keeps reading the annotation flags, so annotated apps
        lower exactly as before."""
        info = find_annotation(query.annotations, "info")
        name = (info.element("name") if info else None) \
            or f"query_{query_index}"
        from siddhi_tpu.planner.costmodel import build_plan_record

        record = build_plan_record(self.app, query, name)
        self._active_record = record
        try:
            qr = self.plan(query, query_index)
        finally:
            self._active_record = None
        record.actual = getattr(qr, "lowered_to", "host")
        sm = self.app.app_context.statistics_manager
        if sm is not None:
            sm.register_plan(name, record)
        return qr

    def _host_pinned(self) -> bool:
        """An explicit pin (replan override) naming 'host' disables the
        device fast-path gates entirely — the only way a tpu app drops a
        query back to the host chain on purpose."""
        rec = self._active_record
        return rec is not None and rec.mode == "pinned" \
            and rec.chosen == "host"

    def _want(self, path: str, name: str) -> bool:
        """Does this query want fast path ``path`` ('multiplex' |
        'hotkey') at its gate site?  Pin precedence: a replan pin names
        the exact composed path; else the legacy annotation; else — in
        auto mode — the cost model's pick.  The real eligibility gate
        of the path still runs after a True."""
        ctx = self.app.app_context
        pin = (getattr(ctx, "plan_pins", None) or {}).get(name)
        if pin is not None:
            return path in str(pin).split("+")
        if path == "multiplex" and ctx.multiplex:
            return True
        if path == "hotkey" and ctx.hotkeys:
            return True
        if path == "shard" and ctx.tpu_devices:
            # legacy: a declared mesh IS the shard pin
            return True
        if getattr(ctx, "plan_auto", False):
            rec = self._active_record
            if rec is None:
                # partition-instance planning bypasses plan_query(); the
                # hotkey router self-gates (promotion needs observed
                # skew) so auto mode opts partitioned dense state in
                return path == "hotkey"
            return path in rec.components()
        return False

    def plan(self, query: Query, query_index: int) -> QueryRuntime:
        info = find_annotation(query.annotations, "info")
        name = (info.element("name") if info else None) or f"query_{query_index}"

        in_stream = query.input_stream
        if isinstance(in_stream, SingleInputStream):
            return self._plan_single(query, name, in_stream)
        from siddhi_tpu.query_api import JoinInputStream, StateInputStream

        if isinstance(in_stream, StateInputStream):
            return self._plan_state(query, name, in_stream)
        if isinstance(in_stream, JoinInputStream):
            return self._plan_join(query, name, in_stream)
        raise SiddhiAppCreationError(
            f"query '{name}': input type {type(in_stream).__name__} not supported yet"
        )

    # -- join ----------------------------------------------------------------

    def _plan_join(self, query: Query, name: str, j) -> QueryRuntime:
        from siddhi_tpu.core.join import JoinRuntime, JoinSide, JoinStreamReceiver
        from siddhi_tpu.query_api import JoinInputStream

        sides = []
        batch_mode = False
        for s in (j.left, j.right):
            if not isinstance(s, SingleInputStream):
                raise SiddhiAppCreationError(
                    f"query '{name}': join side {type(s).__name__} not supported"
                )
            table = self.app.tables.get(s.stream_id)
            ref = s.alias or s.stream_id
            aggregation = getattr(self.app, "aggregations", {}).get(s.stream_id)
            if aggregation is not None:
                if s.handlers:
                    raise SiddhiAppCreationError(
                        f"query '{name}': aggregation '{s.stream_id}' cannot take "
                        "filters/windows in a join"
                    )
                sides.append(
                    JoinSide(
                        ref, aggregation.output_definition, [], None,
                        aggregation=aggregation, triggers=False,
                    )
                )
                continue
            if table is not None:
                if s.handlers:
                    raise SiddhiAppCreationError(
                        f"query '{name}': table '{s.stream_id}' cannot take "
                        "filters/windows in a join"
                    )
                sides.append(
                    JoinSide(ref, table.definition, [], None, table=table, triggers=False)
                )
                continue
            definition = self.app.resolve_stream_definition(s)
            # side-local scope: handler expressions see bare side attrs
            side_scope = scope_for_definition(definition, ref)
            side_compiler = ExpressionCompiler(side_scope, functions=self.app.functions, table_resolver=self.app.table_resolver)
            chain, b_mode, windows, _extra = self._plan_handlers(s, definition, side_compiler)
            batch_mode = batch_mode or b_mode
            window = None
            filters = []
            for p in chain:
                if isinstance(p, WindowChainProcessor):
                    if window is not None:
                        raise SiddhiAppCreationError(
                            f"query '{name}': one window per join side"
                        )
                    window = p.window
                else:
                    filters.append(p)
            nw = (
                self.app.named_windows.get(s.stream_id)
                if not (s.is_inner or s.is_fault)
                else None
            )
            if window is None and nw is not None:
                sides.append(
                    JoinSide(ref, definition, filters, None, named_window=nw)
                )
            else:
                sides.append(JoinSide(ref, definition, filters, window))
        left, right = sides
        if left.ref == right.ref:
            raise SiddhiAppCreationError(
                f"query '{name}': join sides need distinct names/aliases"
            )
        if left.table is not None and right.table is not None:
            raise SiddhiAppCreationError(
                f"query '{name}': cannot join two tables in a stream query"
            )

        # unidirectional trigger
        if j.trigger == "left":
            right.triggers = False
        elif j.trigger == "right":
            left.triggers = False

        # an outer join can only preserve a side that triggers — otherwise
        # unmatched rows of that side would silently never be emitted
        preserve_left = j.join_type in (JoinInputStream.LEFT_OUTER, JoinInputStream.FULL_OUTER)
        preserve_right = j.join_type in (JoinInputStream.RIGHT_OUTER, JoinInputStream.FULL_OUTER)
        if (preserve_left and not left.triggers) or (preserve_right and not right.triggers):
            raise SiddhiAppCreationError(
                f"query '{name}': outer join preserves a side that never "
                "triggers (table side or disabled by 'unidirectional')"
            )

        # join scope: qualified by ref (and by raw stream id when unambiguous)
        scope = Scope()
        for side, src in ((left, j.left), (right, j.right)):
            for a in side.definition.attributes:
                scope.add(side.ref, a.name, side.qualified_key(a.name), a.type)
            if src.stream_id != side.ref:
                scope.add_alias(src.stream_id, side.ref)
        compiler = ExpressionCompiler(scope, functions=self.app.functions, table_resolver=self.app.table_resolver)
        condition = compiler.compile(j.on_condition) if j.on_condition is not None else None
        if condition is not None and condition.type != AttrType.BOOL:
            raise SiddhiAppCreationError(f"query '{name}': 'on' condition must be boolean")

        # aggregation joins: compile `within`/`per` against the join scope so
        # they may reference the probing stream's attributes
        for side in sides:
            if side.aggregation is None:
                continue
            if getattr(j, "per", None) is None:
                raise SiddhiAppCreationError(
                    f"query '{name}': join with aggregation "
                    f"'{side.aggregation.name}' requires a 'per' clause"
                )
            side.agg_per = compiler.compile(j.per)
            w = getattr(j, "within", None)
            if w is not None:
                if isinstance(w, tuple):
                    side.agg_within = (compiler.compile(w[0]), compiler.compile(w[1]))
                else:
                    side.agg_within = (compiler.compile(w), None)

        selector, out_def = self._plan_selector(
            query.selector, scope, compiler, name, query, batch_mode,
            star_sources=[left, right],
        )
        output = self._plan_output(query, out_def, qname=name)
        rate_limiter = self._plan_rate_limiter(query)
        qr = QueryRuntime(name, [[]], selector, rate_limiter, output, self.app.app_context)
        if rate_limiter.needs_scheduler_task:
            self.app.scheduler.register_task(_RateLimiterTask(qr, rate_limiter))

        jr = JoinRuntime(
            left, right, j.join_type, condition,
            emit=lambda batch, now: qr.process(batch, 0),
            out_stream_id=f"#join_{name}",
        )
        qr.join_runtime = jr
        # @app:devtables: an inner join against a DeviceTable side lowers
        # to the [B,C] masked device probe (devtable/join.py) — the
        # stream side subscribes the devtable receiver INSTEAD of the
        # host JoinStreamReceiver, so matched pairs never materialize on
        # the host between ingest and emit
        devtable_runtime = None
        if self.app.app_context.devtables and (
                left.table is not None or right.table is not None):
            import logging

            from siddhi_tpu.devtable import (
                DeviceTable,
                DevTableJoinReceiver,
                try_plan_devtable_join,
            )

            if isinstance(left.table, DeviceTable) or \
                    isinstance(right.table, DeviceTable):
                try:
                    devtable_runtime = try_plan_devtable_join(
                        name, j, left, right, condition, compiler,
                        emit=lambda batch: qr.process(batch, 0),
                        app_context=self.app.app_context)
                    qr.device_runtime = devtable_runtime
                    qr.lowered_to = "devtable"
                    logging.getLogger("siddhi_tpu").info(
                        "query '%s': stream-table join lowered to the "
                        "device-resident table probe", name)
                except SiddhiAppCreationError as e:
                    logging.getLogger("siddhi_tpu").warning(
                        "query '%s': devtable join unavailable (%s); "
                        "host join path used", name, e)
                    sm = self.app.app_context.statistics_manager
                    if sm is not None:
                        sm.record_devtable_fallback(name, str(e))
        if devtable_runtime is not None:
            for side, src in ((left, j.left), (right, j.right)):
                if side.table is not None or side.aggregation is not None:
                    continue
                junction = self.app.junction_for_input(src)
                junction.subscribe(DevTableJoinReceiver(devtable_runtime))
            return qr
        # @app:execution('tpu'): run the O(B*W) cross-product condition
        # as a jitted device kernel (buffering/expiry/materialization
        # keep the host runtime's exact semantics — SURVEY §7 step 7's
        # masked in-batch cross products)
        if (self.app.app_context.execution_mode == "tpu"
                and condition is not None):
            import logging

            from siddhi_tpu.core.join import DeviceJoinProbe

            try:
                jr.device_probe = DeviceJoinProbe(condition, left, right)
                qr.lowered_to = "device_probe"
                logging.getLogger("siddhi_tpu").info(
                    "query '%s': join condition lowered to the jitted "
                    "device probe", name)
            except SiddhiAppCreationError as e:
                logging.getLogger("siddhi_tpu").warning(
                    "query '%s': join device probe unavailable (%s); "
                    "numpy probe used", name, e)
                sm = self.app.app_context.statistics_manager
                if sm is not None:
                    sm.record_device_fallback(name, f"join probe: {e}")
        if any(s.window is not None and getattr(s.window, "needs_scheduler", False) for s in sides):
            self.app.scheduler.register_task(jr)
        for side, src, is_left in ((left, j.left, True), (right, j.right, False)):
            if side.table is not None or side.aggregation is not None:
                continue
            junction = self.app.junction_for_input(src)
            junction.subscribe(JoinStreamReceiver(jr, is_left, self.app.app_context))
        return qr

    # -- pattern / sequence --------------------------------------------------

    def _plan_state(self, query: Query, name: str, st) -> QueryRuntime:
        from siddhi_tpu.ops.nfa import (
            NFABuilder,
            PatternProcessor,
            PatternScope,
            _collect_presence,
        )

        # @app:execution('tpu'): attempt the jitted dense-NFA path first
        # (reference analog: StateInputStreamParser wiring the pattern hot
        # path, StateInputStreamParser.java:76-146); host fallback below
        if (
            self.app.app_context.execution_mode == "tpu"
            and not getattr(self.app, "in_partition_instance", False)
            and not self._host_pinned()
        ):
            import logging

            # @app:multiplex (or the cost model's pick): try seating the
            # pattern in a manager-wide shared dense engine first;
            # ineligibility is counted (multiplexFallbackReason) and
            # falls through to the dedicated dense path below
            if self._want("multiplex", name):
                from siddhi_tpu.multiplex.planner import MultiplexPlanner

                qr = MultiplexPlanner(self).try_state(query, name, st)
                if qr is not None:
                    return qr
            try:
                qr = self._plan_dense_state(query, name, st)
                logging.getLogger("siddhi_tpu").info(
                    "query '%s': pattern lowered to the dense TPU path", name)
                return qr
            except SiddhiAppCreationError as e:
                # WARN: the user asked for execution('tpu') and is
                # getting host execution — must be visible
                logging.getLogger("siddhi_tpu").warning(
                    "query '%s': dense TPU path unavailable (%s); "
                    "using host pattern engine", name, e)
                sm = self.app.app_context.statistics_manager
                if sm is not None:
                    sm.record_device_fallback(name, f"dense pattern: {e}")

        builder = NFABuilder(st, self.app.resolve_stream_definition)
        nodes = builder.build()

        # selector scope over event refs; bare attrs resolve when unambiguous
        scope = PatternScope(builder.ref_defs, builder.stream_to_ref, cand_def=None)
        compiler = ExpressionCompiler(scope, functions=self.app.functions, table_resolver=self.app.table_resolver)
        selector, out_def = self._plan_selector(
            query.selector, scope, compiler, name, query, batch_mode=False
        )
        output = self._plan_output(query, out_def, qname=name)
        rate_limiter = self._plan_rate_limiter(query)
        qr = QueryRuntime(name, [[]], selector, rate_limiter, output, self.app.app_context)
        if rate_limiter.needs_scheduler_task:
            self.app.scheduler.register_task(_RateLimiterTask(qr, rate_limiter))

        # presence keys used anywhere in the selector expressions
        presence = {}
        sel = query.selector
        exprs = []
        if sel.selection:
            exprs.extend(oa.expression for oa in sel.selection)
        if sel.having is not None:
            exprs.append(sel.having)
        for e in exprs:
            presence.update(_collect_presence(e, builder.ref_defs, builder.stream_to_ref))

        processor = PatternProcessor(
            nodes=nodes,
            mode=st.type,
            within_ms=st.within_ms,
            ref_defs=builder.ref_defs,
            output_keys=dict(scope.used_captures),
            presence_keys=presence,
            emit=lambda batch: qr.process(batch, 0),
            out_stream_id=f"#matches_{name}",
        )
        qr.pattern_processor = processor
        self.app.scheduler.register_task(processor)

        # subscribe one receiver per distinct source junction
        seen = set()
        for node in nodes:
            for spec in node.specs:
                if spec.stream_key in seen:
                    continue
                seen.add(spec.stream_key)
                junction = self.app.junctions.get(spec.stream_key)
                if junction is None:
                    raise DefinitionNotExistError(
                        f"stream '{spec.stream_key}' is not defined"
                    )
                junction.subscribe(_PatternStreamReceiver(processor, spec.stream_key))
        return qr

    def _plan_dense_state(
        self, query: Query, name: str, st, key_fn=None,
        n_partitions: Optional[int] = None, subscribe: bool = True,
    ) -> QueryRuntime:
        """Plan a pattern query onto the dense jitted engine; raises
        SiddhiAppCreationError when the query is outside the dense
        subset (caller falls back to the host engine).

        ``key_fn``/``n_partitions`` come from the partitioned form
        (one engine, interned keys); ``subscribe=False`` lets the
        partition runtime do its own key-routed wiring."""
        from siddhi_tpu.core.dense_pattern import (
            DensePatternRuntime,
            _DenseStreamReceiver,
            build_dense_engine,
            output_attr_types,
        )

        if n_partitions is None:
            n_partitions = 1 if key_fn is None else self.app.app_context.tpu_partitions
        partitioned = key_fn is not None or n_partitions > 1
        if partitioned and query.output_rate is not None:
            # the host partitioned form gives each key instance its OWN
            # rate limiter; one shared limiter would pool emission
            # windows across keys
            raise SiddhiAppCreationError(
                "dense path: partitioned queries with output rate limits "
                "need per-key limiters — host instances used")

        sel = query.selector
        aggregating = bool(sel.group_by) or sel.having is not None \
            or self._has_aggregators(sel)
        if aggregating:
            # aggregating-selector form: the dense engine emits the RAW
            # captured columns (keyed exactly like the host pattern
            # scope, e.g. "e1.amount") and the ordinary host
            # QuerySelector aggregates/groups/filters the match rows —
            # matches are sparse, so selector cost is negligible next to
            # the jitted NFA step (reference analog: QuerySelector over
            # StateEvent chunks, QuerySelector.java:76-99)
            if partitioned and (sel.order_by or sel.limit is not None
                                or sel.offset is not None):
                # order-by/limit slice each output chunk; dense chunks
                # mix partition keys, which would slice ACROSS keys —
                # the host form slices per key instance
                raise SiddhiAppCreationError(
                    "dense path: partitioned aggregating selectors with "
                    "order by/limit need per-key chunks — host "
                    "instances used")
            from siddhi_tpu.ops.nfa import NFABuilder, PatternScope

            builder = NFABuilder(st, self.app.resolve_stream_definition)
            builder.build()
            scope = PatternScope(builder.ref_defs, builder.stream_to_ref,
                                 cand_def=None)
            compiler = ExpressionCompiler(
                scope, functions=self.app.functions,
                table_resolver=self.app.table_resolver)
            selector, out_def = self._plan_selector(
                query.selector, scope, compiler, name, query, batch_mode=False
            )
            select_vars = [
                Variable(stream_id=ref, attribute=attr, stream_index=idx)
                for _key, (ref, idx, attr, _t) in scope.used_captures.items()
            ]
            select_names = list(scope.used_captures.keys())
            engine = build_dense_engine(
                query, st, self.app.resolve_stream_definition, n_partitions,
                n_instances=self.app.app_context.tpu_instances,
                select_override=(select_vars, select_names),
                builder=builder)
            if partitioned:
                # ONE shared selector keeps per-(key, group) state via
                # the partition-key side channel on match rows (timer
                # matches map engine rows back through the runtime's
                # reverse row->key map)
                selector.partition_axis = True
        else:
            engine = build_dense_engine(
                query, st, self.app.resolve_stream_definition, n_partitions,
                n_instances=self.app.app_context.tpu_instances)

            out_target = getattr(query.output_stream, "target", None) or f"__ret_{name}"
            out_names = engine.output_names
            out_attrs = [
                Attribute(nm, t) for nm, t in zip(out_names, output_attr_types(engine))
            ]
            selector = self._passthrough_selector(sel, out_names, out_target)
            out_def = StreamDefinition(id=out_target, attributes=out_attrs)
        output = self._plan_output(query, out_def, qname=name)
        rate_limiter = self._plan_rate_limiter(query)
        qr = QueryRuntime(name, [[]], selector, rate_limiter, output, self.app.app_context)

        # @app:execution('tpu', devices='N'): shard the partition axis
        # over an N-device mesh (BASELINE config 5's scale-out form);
        # pointless for single-partition queries
        mesh = None
        nd = self.app.app_context.tpu_devices
        if nd and n_partitions > 1 and self._want("shard", name):
            mesh = self._get_mesh(nd)
        runtime = DensePatternRuntime(
            engine, f"#matches_{name}", emit=lambda b: qr.process(b, 0),
            key_fn=key_fn, mesh=mesh, app_context=self.app.app_context,
            emit_depth=self.app.app_context.tpu_emit_depth,
            ingest_depth=self.app.app_context.tpu_ingest_depth,
        )
        if getattr(selector, "partition_axis", False):
            # idle-key purges must also drop the shared selector's
            # per-key aggregation state (host: the instance dies whole)
            runtime.on_purge_keys = selector.drop_partition_keys
        # @app:hotkeys: wrap eligible partitioned passthrough patterns
        # in the skew router (heavy keys ride the associative scan,
        # cold keys stay dense).  Mesh-sharded and aggregating forms
        # stay dense: the router's state handoff assumes single-device
        # rows and final-node-only selects.
        if (self._want("hotkey", name) and partitioned
                and key_fn is None and mesh is None and not aggregating):
            from siddhi_tpu.planner.hotkeys import try_wrap_hotkey

            wrapped = try_wrap_hotkey(self.app, st, runtime, name)
            if wrapped is not None:
                runtime = wrapped
        elif (self.app.app_context.hotkeys and partitioned
                and key_fn is None and mesh is not None and not aggregating):
            # pinned @app:hotkeys lost to the mesh pin: the router's
            # promote/demote state handoff assumes single-device
            # partition rows (precedence: shard > hotkeys) — count the
            # losing pin so the resolution is visible
            sm = self.app.app_context.statistics_manager
            if sm is not None:
                sm.record_planner_conflict(
                    name, "@app:hotkeys pinned but the partition axis is "
                    "mesh-sharded (precedence: shard > hotkeys)")
        # @app:kernels: swap the hot inner step for Pallas kernels where
        # the runtime is eligible; counted fallback otherwise.  After the
        # hotkey wrap so the router's dense and scan halves gate
        # independently.
        if self.app.app_context.kernels:
            from siddhi_tpu.planner.kernels import try_enable_query_kernels

            try_enable_query_kernels(self.app, runtime, name)
        qr.pattern_processor = runtime
        if subscribe:
            for sk in engine.stream_keys:
                junction = self.app.junctions.get(sk)
                if junction is None:
                    raise DefinitionNotExistError(f"stream '{sk}' is not defined")
                junction.subscribe(_DenseStreamReceiver(runtime, sk))
        # registered LAST: nothing above may raise afterwards, so a
        # fallback to the host path never leaks a live scheduler task;
        # the task handles are kept so multi-query callers (partition
        # lowering) can unregister if a LATER query fails eligibility
        if rate_limiter.needs_scheduler_task:
            task = _RateLimiterTask(qr, rate_limiter, device_runtime=runtime)
            qr._rate_task = task
            self.app.scheduler.register_task(task)
        if getattr(engine, "has_deadlines", False):
            # absent-node deadlines fire from the app scheduler (the
            # dense analog of registering the PatternProcessor's
            # on_time; reference: AbsentStreamPreStateProcessor's
            # scheduler arming)
            qr._dense_timer_task = runtime
            self.app.scheduler.register_task(runtime)
        qr.lowered_to = getattr(runtime, "lowered_to", "dense")
        return qr

    # -- single stream ------------------------------------------------------

    def _plan_single(self, query: Query, name: str, s: SingleInputStream) -> QueryRuntime:
        # @app:execution('tpu'): attempt the jitted device query path
        # first (reference analog: QueryParser wiring receiver ->
        # filter -> window -> selector, QueryParser.java:90); host
        # fallback below — same contract as the dense pattern gate
        if (
            self.app.app_context.execution_mode == "tpu"
            and not getattr(self.app, "in_partition_instance", False)
            and not self._host_pinned()
        ):
            import logging

            # @app:multiplex (or the cost model's pick): shared tumbling
            # engine attempt first, with counted fallback to the
            # dedicated device path
            if self._want("multiplex", name):
                from siddhi_tpu.multiplex.planner import MultiplexPlanner

                qr = MultiplexPlanner(self).try_single(query, name, s)
                if qr is not None:
                    return qr
            try:
                qr = self._plan_device_single(query, name, s)
                logging.getLogger("siddhi_tpu").info(
                    "query '%s': lowered to the jitted device query path",
                    name)
                return qr
            except SiddhiAppCreationError as e:
                # WARN: the user asked for execution('tpu') and is
                # getting host execution — must be visible
                logging.getLogger("siddhi_tpu").warning(
                    "query '%s': device query path unavailable (%s); "
                    "using host engine", name, e)
                sm = self.app.app_context.statistics_manager
                if sm is not None:
                    sm.record_device_fallback(name, f"device query: {e}")

        definition = self.app.resolve_stream_definition(s)
        ref = s.unique_id
        scope = scope_for_definition(definition, ref)
        if s.alias and s.alias != s.stream_id:
            scope.add_alias(s.stream_id, s.alias)
        compiler = ExpressionCompiler(scope, functions=self.app.functions, table_resolver=self.app.table_resolver)

        chain, batch_mode, windows, extra_attrs = self._plan_handlers(s, definition, compiler)
        selector, out_def = self._plan_selector(
            query.selector, scope, compiler, name, query, batch_mode,
            extra_attrs=extra_attrs,
        )
        output = self._plan_output(query, out_def, qname=name)
        rate_limiter = self._plan_rate_limiter(query)

        qr = QueryRuntime(name, [chain], selector, rate_limiter, output, self.app.app_context)
        for w in windows:
            if w.needs_scheduler:
                self.app.scheduler.register_window(qr, w)
        if rate_limiter.needs_scheduler_task:
            self.app.scheduler.register_task(_RateLimiterTask(qr, rate_limiter))
        junction = self.app.junction_for_input(s)
        junction.subscribe(ProcessStreamReceiver(qr))
        return qr

    def _plan_device_single(
        self, query: Query, name: str, s: SingleInputStream,
        partition_mode: bool = False, subscribe: bool = True,
    ) -> QueryRuntime:
        """Plan a single-stream query onto the jitted device engine;
        raises SiddhiAppCreationError when the query is outside the
        device subset (caller falls back to the host chain).

        ``partition_mode``/``subscribe=False`` come from the partitioned
        form (PartitionRuntime._plan_dense): the partition key arrives
        per batch from the partition receiver and composes into the
        engine's group axis — per-key state rows in device memory
        instead of per-key Python instances (reference semantics:
        partition/PartitionStreamReceiver.java:82-118 +
        util/snapshot/state/PartitionStateHolder.java:43)."""
        from siddhi_tpu.core.device_single import (
            DeviceQueryRuntime,
            _DeviceQueryReceiver,
        )
        from siddhi_tpu.ops.device_query import DeviceQueryEngine

        out = query.output_stream
        if out is not None and getattr(out, "event_type", "current") != "current":
            raise SiddhiAppCreationError(
                "device path emits CURRENT events only")
        # per-group first/last and snapshot rate limiters work: the
        # device runtime attaches the same group-key side channel the
        # host selector does (engine.last_group_keys -> batch.aux)
        if not (s.is_inner or s.is_fault):
            if s.stream_id in self.app.named_windows:
                raise SiddhiAppCreationError(
                    "named-window inputs need CURRENT+EXPIRED semantics")
            if s.stream_id in self.app.tables or s.stream_id in getattr(
                    self.app, "aggregations", {}):
                raise SiddhiAppCreationError(
                    "table/aggregation inputs need the host planner")

        if partition_mode and query.output_rate is not None:
            # the host partitioned form gives each key instance its OWN
            # rate limiter; one shared limiter would pool emission
            # windows across keys (same contract as the dense NFA gate)
            raise SiddhiAppCreationError(
                "partitioned queries with output rate limits need "
                "per-key limiters — host instances used")
        if partition_mode and (
                query.selector.order_by
                or query.selector.limit is not None
                or query.selector.offset is not None):
            # per-key instances slice order-by/limit PER KEY; a shared
            # chunk mixes keys and would slice across them
            raise SiddhiAppCreationError(
                "partitioned queries with order by/limit need per-key "
                "chunks — host instances used")
        definition = self.app.resolve_stream_definition(s)
        engine = DeviceQueryEngine(
            query, definition,
            n_groups=self.app.app_context.tpu_partitions,
            partition_mode=partition_mode,
            n_wgroups=(self.app.app_context.tpu_partitions
                       if partition_mode else None),
            defer_order_by=True,  # applied by the selector built below
        )
        # @app:execution('tpu', devices='N'): shard the query's windowed
        # state (group axis, key axis, or — for the global sliding ring —
        # the batch axis) over an N-device mesh; same treatment as
        # DensePatternRuntime's partition axis
        # chaos harness: the step hook reads engine.faults — set on the
        # BASE engine so the sharded wrapper's __getattr__ still sees it
        engine.faults = self.app.app_context.fault_injector
        nd = self.app.app_context.tpu_devices
        if nd and self._want("shard", name):
            from siddhi_tpu.parallel import ShardedDeviceQueryEngine

            import logging

            try:
                engine = ShardedDeviceQueryEngine(engine,
                                                  self._get_mesh(nd))
                logging.getLogger("siddhi_tpu").info(
                    "query '%s': device %s state sharded over %d devices",
                    name, engine.engine.kind, nd)
            except SiddhiAppCreationError as e:
                # NOT silent: the mesh stays idle for this query, so log
                # the reason once and count it on the statistics feed
                # (Queries.<name>.shardedFallbacks, served over REST)
                logging.getLogger("siddhi_tpu").warning(
                    "query '%s': mesh sharding unavailable, running "
                    "single-device: %s", name, e)
                sm = self.app.app_context.statistics_manager
                if sm is not None:
                    sm.record_sharded_fallback(name, str(e))
        out_target = getattr(query.output_stream, "target", None) or f"__ret_{name}"
        out_attrs = [
            Attribute(nm, t)
            for nm, t in zip(engine.output_names, engine.out_types)
        ]
        # order by / limit / offset run host-side over each emitted
        # chunk (the host engine's per-chunk _order_limit position)
        selector = self._passthrough_selector(
            query.selector, engine.output_names, out_target)
        out_def = StreamDefinition(id=out_target, attributes=out_attrs)
        output = self._plan_output(query, out_def, qname=name)
        rate_limiter = self._plan_rate_limiter(query)
        qr = QueryRuntime(
            name, [[]], selector, rate_limiter, output, self.app.app_context)

        runtime = DeviceQueryRuntime(
            engine, f"#device_{name}", emit=lambda b: qr.process(b, 0),
            emit_depth=self.app.app_context.tpu_emit_depth,
            clock=self.app.app_context.timestamp_generator.current_time,
            faults=self.app.app_context.fault_injector,
            ingest_depth=self.app.app_context.tpu_ingest_depth,
            tracer=self.app.app_context.tracer)
        qr.device_runtime = runtime
        if subscribe:
            junction = self.app.junction_for_input(s)
            junction.subscribe(_DeviceQueryReceiver(runtime))
        # registered LAST: nothing below may raise, so a fallback to the
        # host path never leaks a live scheduler task.  Partition mode
        # registers nothing: tumbling panes (the only timer need) are
        # ineligible there, and the partition runtime owns purge timing.
        if not partition_mode:
            self.app.scheduler.register_task(runtime)
            if rate_limiter.needs_scheduler_task:
                task = _RateLimiterTask(qr, rate_limiter,
                                        device_runtime=runtime)
                qr._rate_task = task
                self.app.scheduler.register_task(task)
        qr.lowered_to = "device"
        return qr

    def _plan_rate_limiter(self, query: Query):
        from siddhi_tpu.query_api import (
            EventOutputRate,
            SnapshotOutputRate,
            TimeOutputRate,
        )

        r = query.output_rate
        if r is None:
            return PassThroughRateLimiter()
        if isinstance(r, EventOutputRate):
            if r.type in ("first", "last") and query.selector.group_by:
                return GroupByEventRateLimiter(r.events, r.type)
            return EventRateLimiter(r.events, r.type)
        if isinstance(r, TimeOutputRate):
            if r.type in ("first", "last") and query.selector.group_by:
                return GroupByTimeRateLimiter(r.value_ms, r.type)
            return TimeRateLimiter(r.value_ms, r.type)
        if isinstance(r, SnapshotOutputRate):
            group_names = [g.attribute for g in query.selector.group_by]
            return SnapshotRateLimiter(r.value_ms, group_names)
        raise SiddhiAppCreationError(f"unsupported output rate {r}")

    def _plan_handlers(self, s: SingleInputStream, definition, compiler):
        chain = []
        windows = []
        batch_mode = False
        extra_attrs = []  # schema-extending stream functions' outputs
        for h in s.handlers:
            if isinstance(h, Filter):
                chain.append(FilterProcessor(compiler.compile(h.expression)))
            elif isinstance(h, WindowHandler):
                factory = self.app.extensions.lookup("window", h.name, h.namespace)
                if factory is None:
                    raise SiddhiAppCreationError(f"unknown window '#{'window.'}{h.name}()'")
                args = [compiler.compile(a) for a in h.args]
                validate_extension_args(
                    factory, h.name, [a.type for a in args],
                    where=f"window '#window.{h.name}' on stream '{s.stream_id}'")
                w = factory(args, definition.attribute_names)
                windows.append(w)
                batch_mode = batch_mode or getattr(w, "is_batch", False)
                chain.append(WindowChainProcessor(w))
            elif isinstance(h, StreamFunction):
                factory = self.app.extensions.lookup(
                    "stream_processor", h.name, h.namespace
                ) or self.app.extensions.lookup("stream_function", h.name, h.namespace)
                if factory is None:
                    raise SiddhiAppCreationError(f"unknown stream function '#{h.name}()'")
                args = [compiler.compile(a) for a in h.args]
                validate_extension_args(
                    factory, h.name, [a.type for a in args],
                    where=f"stream function '#{h.name}' on stream '{s.stream_id}'")
                from siddhi_tpu.core.query import StreamFunctionChainProcessor

                fn_obj = factory(args, definition.attribute_names)
                out_attrs = getattr(fn_obj, "output_attributes", None)
                if out_attrs:
                    # schema-extending stream functions (reference:
                    # StreamProcessor.getReturnAttributes, e.g.
                    # #pol2Cart appending x/y): the new columns resolve
                    # downstream — filters later in this chain and the
                    # selector share this scope object
                    for a_ in out_attrs:
                        compiler.scope.add(
                            s.stream_id, a_.name, a_.name, a_.type)
                        uid = getattr(s, "unique_id", s.stream_id)
                        if uid != s.stream_id:
                            compiler.scope.add(
                                uid, a_.name, a_.name, a_.type)
                    extra_attrs.extend(out_attrs)
                chain.append(StreamFunctionChainProcessor(fn_obj))
            else:
                raise SiddhiAppCreationError(f"unsupported stream handler {h}")
        return chain, batch_mode, windows, extra_attrs

    # -- selector -----------------------------------------------------------

    def _plan_selector(
        self,
        sel: Selector,
        scope: Scope,
        compiler: ExpressionCompiler,
        qname: str,
        query: Query,
        batch_mode: bool,
        star_sources=None,
        extra_attrs=None,
    ) -> Tuple[QuerySelector, StreamDefinition]:
        out_target = getattr(query.output_stream, "target", None) or f"__ret_{qname}"
        rewriter = AggregatorRewrite(scope, compiler,
                                     extensions=self.app.extensions)

        items: Optional[List[SelectItem]] = None
        out_attrs: List[Attribute] = []
        if sel.is_select_all and star_sources is not None:
            # join 'select *': all attrs of both sides, plain names
            items = []
            for side in star_sources:
                for a in side.definition.attributes:
                    if any(o.name == a.name for o in out_attrs):
                        raise SiddhiAppCreationError(
                            f"query '{qname}': 'select *' is ambiguous — "
                            f"attribute '{a.name}' exists on both join sides"
                        )
                    compiled = compiler.compile(
                        Variable(stream_id=side.ref, attribute=a.name)
                    )
                    items.append(SelectItem(a.name, compiled))
                    out_attrs.append(Attribute(a.name, a.type))
            out_names = [i.name for i in items]
            for a in out_attrs:
                scope.add_bare(a.name, a.type)
        elif sel.is_select_all:
            # select * — passthrough of the input definition
            if not isinstance(query.input_stream, SingleInputStream):
                raise SiddhiAppCreationError(
                    f"query '{qname}': 'select *' needs an explicit select "
                    "clause for pattern/join inputs"
                )
            in_def = self.app.resolve_stream_definition(query.input_stream)
            # schema-extending stream functions (#pol2Cart) append to
            # the flowing schema, so `select *` includes their outputs
            out_attrs = list(in_def.attributes) + list(extra_attrs or [])
            out_names = [a.name for a in out_attrs]
        else:
            items = []
            for oa in sel.selection:
                rewritten = rewriter.rewrite(oa.expression)
                compiled = compiler.compile(rewritten)
                nm = oa.rename or (
                    oa.expression.attribute
                    if isinstance(oa.expression, Variable)
                    else None
                )
                if nm is None:
                    raise SiddhiAppCreationError(
                        f"query '{qname}': select expression needs 'as <name>'"
                    )
                items.append(SelectItem(nm, compiled))
                out_attrs.append(Attribute(nm, compiled.type))
            out_names = [i.name for i in items]
            # output attributes are referencable in having/order-by
            for a in out_attrs:
                scope.add_bare(a.name, a.type)

        group_keys = [compiler.compile(g) for g in sel.group_by]
        having = compiler.compile(rewriter.rewrite(sel.having)) if sel.having is not None else None
        order_by = []
        for ob in sel.order_by:
            if ob.variable.attribute not in out_names:
                raise SiddhiAppCreationError(
                    f"order by attribute '{ob.variable.attribute}' not in select output"
                )
            order_by.append((ob.variable.attribute, ob.ascending))
        limit = self._const_int(sel.limit, compiler, "limit")
        offset = self._const_int(sel.offset, compiler, "offset")

        selector = QuerySelector(
            out_target,
            items,
            out_names,
            rewriter.bindings,
            group_keys,
            having,
            order_by,
            limit,
            offset,
            batch_mode=batch_mode,
        )
        out_def = StreamDefinition(id=out_target, attributes=out_attrs)
        return selector, out_def

    @staticmethod
    def _has_aggregators(sel: Selector) -> bool:
        """Does any select item call an aggregator (sum/count/...)?"""
        def walk(e) -> bool:
            if isinstance(e, FunctionCall):
                if e.namespace is None and e.name in AGGREGATOR_NAMES:
                    return True
                return any(walk(a) for a in e.args)
            for attr in ("left", "right", "expr"):
                child = getattr(e, attr, None)
                if isinstance(child, Expression) and walk(child):
                    return True
            return False

        return any(walk(oa.expression) for oa in (sel.selection or []))

    @staticmethod
    def _const_int(expr, compiler, what) -> Optional[int]:
        if expr is None:
            return None
        c = compiler.compile(expr)
        try:
            return int(c.fn({}))
        except Exception as e:
            raise SiddhiAppCreationError(f"{what} must be a constant") from e

    # -- output -------------------------------------------------------------

    def _plan_output(self, query: Query, out_def: StreamDefinition,
                     qname: Optional[str] = None):
        from siddhi_tpu.query_api import DeleteStream, UpdateOrInsertStream, UpdateStream
        from siddhi_tpu.table import (
            DeleteTableCallback,
            InsertIntoTableCallback,
            UpdateOrInsertTableCallback,
            UpdateTableCallback,
            compile_set_clause,
            compile_table_condition,
        )

        out = query.output_stream
        if isinstance(out, InsertIntoStream):
            from siddhi_tpu.core.window import InsertIntoWindowCallback

            nw = self.app.named_windows.get(out.target)
            if nw is not None and not out.is_inner and not out.is_fault:
                return InsertIntoWindowCallback(
                    nw, out.event_type, [a.name for a in out_def.attributes]
                )
            table = self.app.tables.get(out.target)
            if table is not None and not out.is_inner and not out.is_fault:
                return InsertIntoTableCallback(
                    table, out.event_type, [a.name for a in out_def.attributes]
                )
            junction = self.app.get_or_create_junction(
                out.target, out_def, is_inner=out.is_inner, is_fault=out.is_fault
            )
            return InsertIntoStreamCallback(junction, out.event_type)
        if isinstance(out, (DeleteStream, UpdateStream, UpdateOrInsertStream)):
            table = self.app.tables.get(out.target)
            if table is None:
                raise SiddhiAppCreationError(
                    f"'{out.target}' is not a defined table (delete/update "
                    "targets must be tables)"
                )
            # condition + set expressions see the query's *output* attrs,
            # bare and qualified by the source stream's name (reference
            # allows `on T.k == S.k` in update/delete conditions)
            out_scope = Scope()
            src_id = getattr(query.input_stream, "stream_id", None)
            for a in out_def.attributes:
                out_scope.add_bare(a.name, a.type)
                if src_id:
                    out_scope.add(src_id, a.name, a.name, a.type)
            condition = compile_table_condition(
                table, out.on_condition, out_scope, table_resolver=self.app.table_resolver
            )
            if isinstance(out, DeleteStream):
                cb = DeleteTableCallback(table, condition, out.event_type)
            else:
                set_ops = compile_set_clause(
                    table,
                    out.set_clause,
                    out_scope,
                    [a.name for a in out_def.attributes],
                    table_resolver=self.app.table_resolver,
                )
                if isinstance(out, UpdateOrInsertStream):
                    cb = UpdateOrInsertTableCallback(
                        table, condition, set_ops, out.event_type,
                        [a.name for a in out_def.attributes],
                    )
                else:
                    cb = UpdateTableCallback(
                        table, condition, set_ops, out.event_type)
            # @app:devtables: lower the mutation to one scatter step per
            # batch when the gates pass; the generic callback rides along
            # as the per-batch delegate for kernel-inexpressible shapes
            if self.app.app_context.devtables:
                from siddhi_tpu.devtable import DeviceTable, plan_devtable_mutation

                if isinstance(table, DeviceTable):
                    import logging

                    who = qname or f"table:{out.target}"
                    try:
                        return plan_devtable_mutation(
                            who, out, out_def, out_scope, table, cb,
                            functions=self.app.functions,
                            table_resolver=self.app.table_resolver)
                    except SiddhiAppCreationError as e:
                        logging.getLogger("siddhi_tpu").warning(
                            "query '%s': devtable mutation lowering "
                            "unavailable (%s); per-row host callback "
                            "used", who, e)
                        sm = self.app.app_context.statistics_manager
                        if sm is not None:
                            sm.record_devtable_fallback(who, str(e))
            return cb
        if isinstance(out, ReturnStream) or out is None:
            return QueryCallbackOutput()
        raise SiddhiAppCreationError(
            f"output type {type(out).__name__} not supported yet"
        )
