"""Online plan refinement: live re-lowering from the observability feed.

The cost model (planner/costmodel.py) scores candidates at plan time
with a static batch-width hint.  ``PlanMonitor`` closes the loop: it
reads the statistics feed — per-query latency trackers (observed batch
width = events/batches), hotkey router promotion/routing counters — and
re-scores the active plan's candidates with what the app actually sees.
When an alternative's cost beats the active plan's re-scored cost by
the hysteresis margin (``@app:plan(hysteresis='0.3')``: 30% cheaper),
it triggers :meth:`SiddhiAppRuntime.replan` with the winner as a pin;
the re-plan protocol (pause → rebuild → journal full replay) keeps the
switch bit-exact, so a wrong decision here costs throughput, never
correctness.

A switched query comes back PINNED in the replacement build, so the
monitor never flip-flops it: one observed-cost correction per query,
with the hysteresis margin guarding the trigger.  ``decide()`` is the
side-effect-free seam the tests drive directly; the interval daemon
(``@app:plan(interval='5 sec')``) just calls ``maybe_replan()`` on a
timer.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

from siddhi_tpu.core.exceptions import SiddhiAppCreationError

log = logging.getLogger("siddhi_tpu")

#: latency batches required before the observed width is trusted
MIN_BATCHES = 3


class PlanMonitor:
    def __init__(self, runtime, hysteresis: Optional[float] = None,
                 interval_ms: Optional[int] = None):
        self.runtime = runtime
        ctx = runtime.app_context
        self.hysteresis = (ctx.plan_hysteresis if hysteresis is None
                           else float(hysteresis))
        self.interval_ms = (ctx.plan_interval_ms if interval_ms is None
                            else int(interval_ms))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- observability reads (each a test seam) -----------------------------

    def observed_batch(self, name: str) -> Optional[float]:
        """Observed mean batch width of query ``name``; None until the
        latency tracker has seen enough batches to trust it."""
        sm = self.runtime.app_context.statistics_manager
        lt = sm.latency.get(name) if sm is not None else None
        if lt is None or lt.batches < MIN_BATCHES:
            return None
        return max(1.0, lt.events / lt.batches)

    def observed_skew(self, name: str) -> Optional[float]:
        """Observed hot-traffic share from the query's hotkey router
        (routed events / total events) — replaces the model's static
        skew prior when the router is live."""
        sm = self.runtime.app_context.statistics_manager
        if sm is None:
            return None
        router = sm.hotkey_routers.get(name)
        lt = sm.latency.get(name)
        if router is None or lt is None or lt.events <= 0:
            return None
        try:
            routed = float(router.hot_metrics().get("hotkeyRoutedEvents", 0))
        except Exception:  # noqa: BLE001 — telemetry must not kill the loop
            return None
        return min(1.0, routed / lt.events)

    # -- the decision -------------------------------------------------------

    def decide(self) -> Dict[str, str]:
        """Re-score every auto-planned query with observed widths; return
        ``{query: path}`` pins for those whose active plan is beaten by
        more than the hysteresis margin.  Side-effect free."""
        from siddhi_tpu.planner import costmodel as cm

        ctx = self.runtime.app_context
        sm = ctx.statistics_manager
        if sm is None:
            return {}
        pins: Dict[str, str] = {}
        for name, rec in list(sm.plans.items()):
            # pins stay pinned (including our own past switches); legacy
            # annotation apps never auto-switch
            if rec.mode != "auto" or rec.traits is None:
                continue
            batch = self.observed_batch(name)
            if batch is None:
                continue
            traits = rec.traits
            skew = self.observed_skew(name)

            def cost_of(path: str) -> float:
                c = cm.score_path(path, traits, ctx, batch)
                if skew is not None and "hotkey" in path.split("+"):
                    # swap the static skew prior for the router's
                    # observed hot-traffic share: undo the prior's
                    # dense-residual credit and scan debit, re-apply
                    # both at the observed share
                    dense_ev = (cm.DENSE_NODE_PER_EVENT
                                * traits.n_nodes * batch)
                    c += dense_ev * (cm.HOTKEY_SKEW - skew)
                    c += (cm.DEVICE_PER_EVENT * batch
                          * (skew - cm.HOTKEY_SKEW))
                return max(c, 0.1)

            active = rec.actual or rec.chosen
            active_cost = cost_of(active)
            best_path = None
            best_cost = 0.0
            for cand in rec.candidates:
                if cand.path == active:
                    continue
                try:
                    cm._check_composable(cand.path, traits, ctx)
                except SiddhiAppCreationError:
                    continue
                c = cost_of(cand.path)
                if best_path is None or c < best_cost:
                    best_path, best_cost = cand.path, c
            if best_path is None:
                continue
            if best_cost * (1.0 + self.hysteresis) < active_cost:
                log.info(
                    "plan monitor: query '%s' active '%s' costs %.1f "
                    "observed vs %.1f for '%s' — past the %.0f%% "
                    "hysteresis margin", name, active, active_cost,
                    best_cost, best_path, self.hysteresis * 100)
                pins[name] = best_path
        return pins

    def maybe_replan(self) -> bool:
        """One monitor tick: decide, and re-lower live when warranted.
        Refusals (no journal, journal overflow) are already counted by
        ``replan`` — here they just skip the tick."""
        pins = self.decide()
        if not pins:
            return False
        try:
            self.runtime.replan(
                pins, forced=False,
                reason="observed cost exceeded a cheaper candidate by "
                       "the hysteresis margin")
            return True
        except Exception:
            log.warning("plan monitor: re-plan attempt failed",
                        exc_info=True)
            return False

    # -- interval daemon ----------------------------------------------------

    def start(self):
        if self._thread is not None or self.interval_ms <= 0:
            return
        self._stop.clear()
        t = threading.Thread(
            target=self._loop,
            name=f"plan-monitor-{self.runtime.name}", daemon=True)
        self._thread = t
        t.start()

    def _loop(self):
        interval_s = self.interval_ms / 1000.0
        while not self._stop.wait(interval_s):
            try:
                self.maybe_replan()
            except Exception:
                log.exception("plan monitor tick failed")
            except BaseException as e:
                # simulated crash on the monitor thread: stop ticking —
                # the harness kills the app elsewhere
                log.error("plan monitor stopped: %s", e)
                break

    def stop(self):
        self._stop.set()
        t = self._thread
        # replan() itself tears the old runtime down (which stops the
        # monitor): never join the thread we are running on
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2)
        self._thread = None
