"""App planner: SiddhiApp AST -> SiddhiAppRuntime.

The analog of the reference SiddhiAppParser.parse + SiddhiAppRuntimeBuilder
(util/parser/SiddhiAppParser.java:91, util/SiddhiAppRuntimeBuilder.java:64):
wires junctions for every stream definition (plus @OnError fault streams),
plans queries/partitions, and assembles the runtime.
"""

from __future__ import annotations

from typing import Dict, Optional

from siddhi_tpu.core.context import SiddhiAppContext, SiddhiContext
from siddhi_tpu.core.exceptions import (
    DefinitionNotExistError,
    OnErrorAction,
    SiddhiAppCreationError,
)
from siddhi_tpu.core.stream import InputManager, StreamJunction
from siddhi_tpu.extension.validator import validate_extension_args
from siddhi_tpu.query_api import (
    Attribute,
    AttrType,
    Partition,
    Query,
    SiddhiApp,
    SingleInputStream,
    StreamDefinition,
)
from siddhi_tpu.query_api.annotation import find_annotation
from siddhi_tpu.util.scheduler import Scheduler


class AppPlanner:
    def __init__(self, siddhi_app: SiddhiApp, app_string: str, siddhi_context: SiddhiContext):
        self.siddhi_app = siddhi_app
        self.app_string = app_string
        self.siddhi_context = siddhi_context
        self.extensions = siddhi_context.extensions

        self.handler_registrations = []  # (manager, element_id) to drop on shutdown
        name_ann = find_annotation(siddhi_app.annotations, "app:name")
        import uuid

        self.name = (name_ann.element() if name_ann else None) or f"app_{uuid.uuid4().hex[:8]}"
        self.app_context = SiddhiAppContext(siddhi_context, self.name)
        playback = find_annotation(siddhi_app.annotations, "app:playback")
        if playback is not None:
            from siddhi_tpu.compiler.parser import parse_time_string

            def time_ms(v):
                if v is None:
                    return 0
                try:
                    return int(v)
                except ValueError:
                    return parse_time_string(v)

            self.app_context.set_playback(True, time_ms(playback.element("increment")))
            self.app_context.playback_idle_ms = time_ms(playback.element("idle.time"))
        if find_annotation(siddhi_app.annotations, "app:enforceOrder") is not None:
            # the sync dispatch path is ordered by construction; the flag is
            # kept for API parity (reference: SiddhiAppParser.java:199-213)
            self.app_context.enforce_order = True
        exec_ann = find_annotation(siddhi_app.annotations, "app:execution")
        if exec_ann is not None:
            mode = (exec_ann.element() or "host").lower()
            if mode not in ("host", "tpu"):
                raise SiddhiAppCreationError(
                    f"@app:execution('{mode}'): mode must be 'host' or 'tpu'")
            self.app_context.execution_mode = mode
            parts = exec_ann.element("partitions")
            if parts:
                try:
                    n = int(parts)
                except ValueError:
                    n = -1
                if n < 1:
                    raise SiddhiAppCreationError(
                        f"@app:execution: partitions='{parts}' must be a "
                        "positive integer")
                self.app_context.tpu_partitions = n
            insts = exec_ann.element("instances")
            if insts:
                try:
                    ni = int(insts)
                except ValueError:
                    ni = -1
                if ni < 1:
                    raise SiddhiAppCreationError(
                        f"@app:execution: instances='{insts}' must be a "
                        "positive integer")
                self.app_context.tpu_instances = ni
            devs = exec_ann.element("devices")
            if devs:
                try:
                    nd = int(devs)
                except ValueError:
                    nd = -1
                if nd < 1:
                    raise SiddhiAppCreationError(
                        f"@app:execution: devices='{devs}' must be a "
                        "positive integer")
                self.app_context.tpu_devices = nd
                if self.app_context.tpu_partitions % nd:
                    raise SiddhiAppCreationError(
                        f"@app:execution: partitions="
                        f"{self.app_context.tpu_partitions} must be "
                        f"divisible by devices={nd}")
            depth = exec_ann.element("emit.depth")
            if depth:
                if depth.lower() == "auto":
                    # adaptive: the emit queue derives its effective
                    # depth from observed transfer RTT vs batch cadence
                    # (core/emit_queue.py EmitDepthController)
                    self.app_context.tpu_emit_depth = "auto"
                else:
                    try:
                        ed = int(depth)
                    except ValueError:
                        ed = -1
                    if ed < 1:
                        raise SiddhiAppCreationError(
                            f"@app:execution: emit.depth='{depth}' must be "
                            "a positive integer or 'auto'")
                    self.app_context.tpu_emit_depth = ed
            idepth = exec_ann.element("ingest.depth")
            if idepth:
                if idepth.lower() == "auto":
                    # adaptive: the staging window derives its depth
                    # from observed count-fetch RTT vs batch cadence
                    # (core/ingest_stage.py, same controller as
                    # emit.depth='auto')
                    self.app_context.tpu_ingest_depth = "auto"
                else:
                    try:
                        nid = int(idepth)
                    except ValueError:
                        nid = -1
                    if nid < 1:
                        raise SiddhiAppCreationError(
                            f"@app:execution: ingest.depth='{idepth}' must "
                            "be a positive integer or 'auto'")
                    self.app_context.tpu_ingest_depth = nid
            amb = exec_ann.element("agg.device.min.batch")
            if amb:
                try:
                    nab = int(amb)
                except ValueError:
                    nab = -1
                if nab < 1:
                    raise SiddhiAppCreationError(
                        f"@app:execution: agg.device.min.batch='{amb}' must "
                        "be a positive integer")
                self.app_context.tpu_agg_min_batch = nab

        # @app:multiplex(slots='N'): opt this app's eligible queries into
        # manager-wide shared device engines (multiplex/) — one jitted
        # step per cycle serves every structurally-compatible tenant
        # across ALL apps under the manager.  Ineligible queries fall
        # back to dedicated engines with a counted reason.
        mux_ann = find_annotation(siddhi_app.annotations, "app:multiplex")
        if mux_ann is not None:
            if self.app_context.execution_mode != "tpu":
                raise SiddhiAppCreationError(
                    "@app:multiplex needs @app:execution('tpu')")
            self.app_context.multiplex = True
            slots = mux_ann.element("slots") or mux_ann.element()
            if slots:
                try:
                    ns = int(slots)
                except ValueError:
                    ns = -1
                if ns < 2 or ns > 64:
                    raise SiddhiAppCreationError(
                        f"@app:multiplex: slots='{slots}' must be an "
                        "integer in 2..64")
                self.app_context.multiplex_slots = ns

        # @app:fuse: fuse chains of device-lowered queries linked by
        # `insert into` streams into ONE jitted multi-stage program per
        # chain — intermediate event columns stay in HBM, no EventBatch
        # builds or junction dispatches between stages
        # (planner/fusion.py).  Ineligible chains fall back to the
        # junction path with counted fusedFallbackReasons.
        fuse_ann = find_annotation(siddhi_app.annotations, "app:fuse")
        if fuse_ann is not None:
            if self.app_context.execution_mode != "tpu":
                raise SiddhiAppCreationError(
                    "@app:fuse needs @app:execution('tpu')")
            v = (fuse_ann.element() or "true").lower()
            if v not in ("true", "false"):
                raise SiddhiAppCreationError(
                    f"@app:fuse('{v}'): expected 'true' or 'false'")
            self.app_context.fuse = v == "true"

        # @app:hotkeys(k='8', promote='0.25', demote='0.10'): skew-aware
        # hot-key routing — partitioned dense patterns promote heavy
        # partition keys onto the batched associative-scan engine
        # (planner/hotkeys.py); ineligible queries stay dense with
        # counted hotkeyFallbackReasons.
        hk_ann = find_annotation(siddhi_app.annotations, "app:hotkeys")
        if hk_ann is not None:
            if self.app_context.execution_mode != "tpu":
                raise SiddhiAppCreationError(
                    "@app:hotkeys needs @app:execution('tpu')")
            self.app_context.hotkeys = True
            k = hk_ann.element("k") or hk_ann.element()
            if k:
                try:
                    nk = int(k)
                except ValueError:
                    nk = -1
                if nk < 1 or nk > 256:
                    raise SiddhiAppCreationError(
                        f"@app:hotkeys: k='{k}' must be an integer in "
                        "1..256 (scan slots per query)")
                self.app_context.hotkey_k = nk
            pr = hk_ann.element("promote")
            dm = hk_ann.element("demote")
            try:
                promote = float(pr) if pr else self.app_context.hotkey_promote
                demote = float(dm) if dm else self.app_context.hotkey_demote
            except ValueError:
                raise SiddhiAppCreationError(
                    f"@app:hotkeys: promote='{pr}'/demote='{dm}' must be "
                    "fractions of total traffic")
            if not (0.0 < promote <= 1.0) or not (0.0 <= demote < promote):
                raise SiddhiAppCreationError(
                    f"@app:hotkeys: need 0 <= demote < promote <= 1 "
                    f"(got promote={promote}, demote={demote}) — the "
                    "hysteresis band prevents promote/demote thrash")
            self.app_context.hotkey_promote = promote
            self.app_context.hotkey_demote = demote

        # @app:kernels / @app:kernels('nfa,bank,scan'): hand-written
        # Pallas kernels for the hot step of eligible runtimes
        # (planner/kernels.py); ineligible cases stay on the XLA
        # formulation with counted kernelFallbackReasons.
        kn_ann = find_annotation(siddhi_app.annotations, "app:kernels")
        if kn_ann is not None:
            if self.app_context.execution_mode != "tpu":
                raise SiddhiAppCreationError(
                    "@app:kernels needs @app:execution('tpu')")
            v = (kn_ann.element() or "true").strip().lower()
            if v == "false":
                pass  # explicit off: annotation present but disabled
            elif v == "true":
                self.app_context.kernels = True
            else:
                kinds = tuple(
                    k.strip() for k in v.split(",") if k.strip())
                bad = [k for k in kinds if k not in ("nfa", "bank", "scan")]
                if bad or not kinds:
                    raise SiddhiAppCreationError(
                        f"@app:kernels: unknown kernel kind(s) "
                        f"{bad or [v]} — valid kinds are 'nfa', 'bank', "
                        "'scan'")
                self.app_context.kernels = True
                self.app_context.kernel_kinds = kinds

        # @app:devtables / @app:devtables(capacity='N'): device-resident
        # columnar tables (siddhi_tpu/devtable/); ineligible tables and
        # queries keep the host path with counted devtableFallbackReasons.
        dt_ann = find_annotation(siddhi_app.annotations, "app:devtables")
        if dt_ann is not None:
            if self.app_context.execution_mode != "tpu":
                raise SiddhiAppCreationError(
                    "@app:devtables needs @app:execution('tpu')")
            v = (dt_ann.element() or "true").strip().lower()
            if v != "false":
                self.app_context.devtables = True
            cap = dt_ann.element("capacity")
            if cap:
                try:
                    ncap = int(cap)
                except ValueError:
                    ncap = -1
                if ncap < 1 or ncap > 1 << 24:
                    raise SiddhiAppCreationError(
                        f"@app:devtables: capacity='{cap}' must be an "
                        "integer in 1..16777216 (device slots per table)")
                self.app_context.devtable_capacity = ncap

        # @app:plan(auto='true', hysteresis='0.3', interval='5 sec'):
        # cost-based unified lowering (planner/costmodel.py) — auto
        # enumerates + scores every eligible lowering per un-annotated
        # query and picks the cheapest (the legacy fast-path annotations
        # stay pins that override it); hysteresis is the PlanMonitor's
        # re-plan margin and interval paces its background sweep
        # (0 = decide() on demand only).
        plan_ann = find_annotation(siddhi_app.annotations, "app:plan")
        if plan_ann is not None:
            if self.app_context.execution_mode != "tpu":
                raise SiddhiAppCreationError(
                    "@app:plan needs @app:execution('tpu')")
            v = (plan_ann.element("auto") or plan_ann.element()
                 or "true").strip().lower()
            if v not in ("true", "false"):
                raise SiddhiAppCreationError(
                    f"@app:plan: auto='{v}' must be 'true' or 'false'")
            self.app_context.plan_auto = v == "true"
            hy = plan_ann.element("hysteresis")
            if hy:
                try:
                    h = float(hy)
                except ValueError:
                    h = -1.0
                if not (0.0 <= h <= 10.0):
                    raise SiddhiAppCreationError(
                        f"@app:plan: hysteresis='{hy}' must be a fraction "
                        "in 0..10 (margin before a live re-plan)")
                self.app_context.plan_hysteresis = h
            iv = plan_ann.element("interval")
            if iv:
                try:
                    ims = int(iv)
                except ValueError:
                    from siddhi_tpu.compiler.parser import parse_time_string

                    ims = parse_time_string(iv)
                if ims <= 0:
                    raise SiddhiAppCreationError(
                        f"@app:plan: interval {iv!r} must be > 0")
                self.app_context.plan_interval_ms = ims

        from siddhi_tpu.util.statistics import Level, StatisticsManager

        stats_ann = find_annotation(siddhi_app.annotations, "app:statistics")
        level = Level.OFF
        interval_s = 60.0
        if stats_ann is not None:
            v = (stats_ann.element() or "true").lower()
            level = {
                "true": Level.BASIC, "false": Level.OFF,
                "basic": Level.BASIC, "detail": Level.DETAIL,
            }.get(v, Level.BASIC)
            iv = stats_ann.element("interval")
            if iv:
                interval_s = float(iv)
        self.app_context.root_metrics_level = level
        self.app_context.statistics_manager = StatisticsManager(self.name, interval_s)

        # @app:trace(sample='1/64', cycles='64', dir='/path'): cycle-
        # correlated span tracing + flight recorder (observability/).
        # Default ON at 1-in-64 sampling — the recorder is the black box
        # every fault dump reads, so it must not require opting in;
        # sample='off' disables span recording (the tracer object stays
        # and every hook short-circuits on the None token).
        from siddhi_tpu.observability import Tracer

        trace_ann = find_annotation(siddhi_app.annotations, "app:trace")
        trace_sample = Tracer.DEFAULT_SAMPLE
        trace_cycles = Tracer.DEFAULT_CYCLES
        trace_dir = None
        if trace_ann is not None:
            sv = (trace_ann.element("sample") or trace_ann.element() or "")
            if sv.strip():
                trace_sample = self._parse_trace_sample(sv.strip())
            cv = trace_ann.element("cycles")
            if cv:
                try:
                    nc = int(cv)
                except ValueError:
                    nc = -1
                if nc < 1 or nc > 4096:
                    raise SiddhiAppCreationError(
                        f"@app:trace: cycles='{cv}' must be an integer in "
                        "1..4096 (flight-recorder depth in batch cycles)")
                trace_cycles = nc
            trace_dir = trace_ann.element("dir") or None
        tracer = Tracer(self.name, sample=trace_sample,
                        cycles=trace_cycles, dump_dir=trace_dir)
        self.app_context.tracer = tracer
        self.app_context.statistics_manager.register_tracer(tracer)

        # @app:faults(...): deterministic chaos harness + crash-recovery
        # journal.  The injector itself is cheap (every hook is a None
        # check when the annotation is absent); the journal is keyed by
        # app name on the MANAGER context so a replacement runtime built
        # after a simulated crash inherits the pre-crash input history.
        faults_ann = find_annotation(siddhi_app.annotations, "app:faults")
        if faults_ann is not None:
            from siddhi_tpu.util.faults import FaultInjector, InputJournal

            fi = FaultInjector()
            journal_depth = fi.configure_from_options(
                self._ann_options(faults_ann))
            fi.listeners = self.app_context.exception_listeners
            # a simulated crash kill is exactly what the flight recorder
            # exists for: the injector dumps the span ring on its way out
            fi.tracer = tracer
            self.app_context.fault_injector = fi
            if journal_depth:
                jr = siddhi_context.input_journals.get(self.name)
                if jr is None or jr.depth != journal_depth:
                    jr = InputJournal(depth=journal_depth)
                    siddhi_context.input_journals[self.name] = jr
                else:
                    # a reused (post-crash) journal carries its counter
                    # history into the replacement runtime's feed
                    for k, v in jr.stats.as_dict().items():
                        setattr(fi.stats, k, getattr(fi.stats, k) + v)
                jr.stats = fi.stats
                self.app_context.input_journal = jr
                # journal overflow spills cold segments to the app's
                # persistence store instead of dropping them — replay
                # stitches spilled + in-memory segments (durability/)
                from siddhi_tpu.durability.spill import JournalSpillSink

                jr.spill_sink = JournalSpillSink(
                    siddhi_context, self.name, self.app_context)

        # @app:persist(interval='30 sec', mode='async'): default persist
        # mode + optional periodic-checkpoint daemon (durability/)
        persist_ann = find_annotation(siddhi_app.annotations, "app:persist")
        if persist_ann is not None:
            mode = (persist_ann.element("mode")
                    or persist_ann.element() or "async").lower()
            if mode not in ("sync", "async"):
                raise SiddhiAppCreationError(
                    f"@app:persist: mode {mode!r} must be 'sync' or 'async'")
            self.app_context.persist_mode = mode
            iv = persist_ann.element("interval")
            if iv:
                try:
                    interval_ms = int(iv)
                except ValueError:
                    from siddhi_tpu.compiler.parser import parse_time_string

                    interval_ms = parse_time_string(iv)
                if interval_ms <= 0:
                    raise SiddhiAppCreationError(
                        f"@app:persist: interval {iv!r} must be > 0")
                self.app_context.persist_interval_ms = interval_ms

        # @app:limits(rate='N/s', burst='M', shed='drop|oldest|block',
        # block.max='1 sec', watchdog='2 sec', breaker='3',
        # breaker.cooldown='1 sec', ladder='true'): overload protection
        # (robustness/) — admission control at ingest, watchdog-driven
        # self-healing, transport circuit breakers, and the unified
        # degradation ladder.  Absent ⇒ every hook stays None and the
        # engine is bit-identical to an unprotected app.
        limits_ann = find_annotation(siddhi_app.annotations, "app:limits")
        if limits_ann is not None:
            from siddhi_tpu.compiler.parser import parse_time_string
            from siddhi_tpu.robustness import (
                AdmissionController,
                RobustnessStats,
            )
            from siddhi_tpu.robustness.admission import SHED_POLICIES

            ctx = self.app_context

            def limits_time_ms(key):
                v = limits_ann.element(key)
                if v is None:
                    return None
                try:
                    ms = int(v)
                except ValueError:
                    ms = parse_time_string(v)
                if ms <= 0:
                    raise SiddhiAppCreationError(
                        f"@app:limits: {key}={v!r} must be > 0")
                return ms

            rate = limits_ann.element("rate") or limits_ann.element()
            if rate:
                r = rate.strip().lower()
                for suffix in ("/sec", "/s"):
                    if r.endswith(suffix):
                        r = r[: -len(suffix)]
                        break
                try:
                    ctx.limits_rate = float(r)
                except ValueError:
                    ctx.limits_rate = -1.0
                if ctx.limits_rate <= 0:
                    raise SiddhiAppCreationError(
                        f"@app:limits: rate='{rate}' must be a positive "
                        "events-per-second figure ('1000' or '1000/s')")
            burst = limits_ann.element("burst")
            if burst:
                try:
                    ctx.limits_burst = float(burst)
                except ValueError:
                    ctx.limits_burst = -1.0
                if ctx.limits_burst < 1:
                    raise SiddhiAppCreationError(
                        f"@app:limits: burst='{burst}' must be >= 1 "
                        "(token-bucket depth in events)")
                if not ctx.limits_rate:
                    raise SiddhiAppCreationError(
                        "@app:limits: burst needs rate")
            elif ctx.limits_rate:
                ctx.limits_burst = max(ctx.limits_rate, 1.0)
            shed = limits_ann.element("shed")
            if shed:
                if shed not in SHED_POLICIES:
                    raise SiddhiAppCreationError(
                        f"@app:limits: shed='{shed}' must be one of "
                        f"{', '.join(SHED_POLICIES)}")
                if not ctx.limits_rate:
                    raise SiddhiAppCreationError(
                        "@app:limits: shed needs rate")
                ctx.limits_shed = shed
            bm = limits_time_ms("block.max")
            if bm is not None:
                ctx.limits_block_max_ms = bm
            wd = limits_time_ms("watchdog")
            if wd is not None:
                ctx.watchdog_deadline_ms = wd
            br = limits_ann.element("breaker")
            if br:
                try:
                    nb = int(br)
                except ValueError:
                    nb = -1
                if nb < 1:
                    raise SiddhiAppCreationError(
                        f"@app:limits: breaker='{br}' must be a positive "
                        "integer (consecutive failures before opening)")
                ctx.breaker_threshold = nb
            bc = limits_time_ms("breaker.cooldown")
            if bc is not None:
                ctx.breaker_cooldown_ms = bc
            lv = (limits_ann.element("ladder") or "false").strip().lower()
            if lv not in ("true", "false"):
                raise SiddhiAppCreationError(
                    f"@app:limits: ladder='{lv}' must be 'true' or 'false'")
            ctx.ladder = lv == "true"
            if ctx.ladder and not ctx.watchdog_deadline_ms:
                raise SiddhiAppCreationError(
                    "@app:limits: ladder='true' needs watchdog='<deadline>'"
                    " — the watchdog tick is what drives the ladder")
            if not (ctx.limits_rate or ctx.watchdog_deadline_ms
                    or ctx.breaker_threshold):
                raise SiddhiAppCreationError(
                    "@app:limits: needs at least one of rate, watchdog, "
                    "breaker")
            ctx.robustness = RobustnessStats()
            if ctx.limits_rate:
                ctx.admission = AdmissionController(ctx, ctx.robustness)

        self.scheduler = Scheduler(self.app_context)
        self.app_context.scheduler = self.scheduler

        self.junctions: Dict[str, StreamJunction] = {}
        self.definitions: Dict[str, StreamDefinition] = {}
        self.sources = []
        self.sinks = []
        self.query_runtimes: Dict[str, object] = {}
        self.tables: Dict[str, object] = {}  # name -> InMemoryTable
        self.named_windows: Dict[str, object] = {}  # name -> NamedWindowRuntime
        self.trigger_runtimes: Dict[str, object] = {}

    # -- junction / definition registry -------------------------------------

    @staticmethod
    def _key(stream_id: str, is_inner: bool = False, is_fault: bool = False) -> str:
        if is_inner:
            return "#" + stream_id
        if is_fault:
            return "!" + stream_id
        return stream_id

    def define_stream(self, definition: StreamDefinition, key: Optional[str] = None):
        key = key or definition.id
        if key in self.junctions:
            return self.junctions[key]
        is_async = False
        buffer_size = 1024
        batch_max = None
        on_error = OnErrorAction.LOG
        async_ann = find_annotation(definition.annotations, "async")
        if async_ann is not None:
            is_async = True
            bs = async_ann.element("buffer.size")
            bm = async_ann.element("batch.size.max")
            buffer_size = int(bs) if bs else 1024
            batch_max = int(bm) if bm else None
        onerror_ann = find_annotation(definition.annotations, "OnError")
        fault_junction = None
        if onerror_ann is not None and (onerror_ann.element("action") or "log").lower() == "stream":
            on_error = OnErrorAction.STREAM
            fault_def = StreamDefinition(
                id="!" + definition.id,
                attributes=list(definition.attributes) + [Attribute("_error", AttrType.OBJECT)],
            )
            fault_junction = self.define_stream(fault_def, key="!" + definition.id)
        j = StreamJunction(
            definition,
            self.app_context,
            is_async=is_async,
            buffer_size=buffer_size,
            batch_size_max=batch_max,
            on_error=on_error,
            fault_junction=fault_junction,
        )
        self.junctions[key] = j
        self.definitions[key] = definition
        self._attach_transports(definition, j)
        return j

    # -- @source / @sink ----------------------------------------------------

    @staticmethod
    def _ann_options(ann) -> Dict[str, str]:
        return {k: v for k, v in ann.elements if k is not None and k.lower() != "type"}

    @staticmethod
    def _parse_trace_sample(value: str) -> int:
        """@app:trace sample grammar: 'off' (no spans), '1' (every
        cycle), '1/N' or bare 'N' (every Nth cycle)."""
        v = value.lower()
        if v in ("off", "false", "none"):
            return 0
        num, sep, den = v.partition("/")
        try:
            n = int(den) if sep else int(num)
            if sep and int(num) != 1:
                raise ValueError(num)
        except ValueError:
            raise SiddhiAppCreationError(
                f"@app:trace: sample='{value}' must be 'off', '1', 'N' or "
                "'1/N' (record every Nth batch cycle)")
        if n < 1 or n > 1_000_000:
            raise SiddhiAppCreationError(
                f"@app:trace: sample='{value}' out of range — the sampling "
                "stride must be in 1..1000000")
        return n

    def _resolve_ref(self, ann) -> Dict[str, str]:
        """Options for @source/@sink/@store with ``ref=`` merged from the
        config manager's refs (reference: ConfigManager.extractSystemConfigs);
        inline options win over ref properties."""
        opts = self._ann_options(ann)
        ref = opts.pop("ref", None)
        if ref is not None:
            cm = self.siddhi_context.config_manager
            ref_configs = dict(cm.extract_system_configs(ref))
            if not ref_configs:
                raise SiddhiAppCreationError(f"undefined ref '{ref}'")
            ref_configs.update(opts)
            opts = ref_configs
        return opts

    def _transport_config(self, ann, what: str):
        """-> (type, init options) with ``ref=`` resolved exactly once."""
        opts = self._resolve_ref(ann)
        stype = ann.element("type") or opts.get("type")
        if stype is None:
            raise SiddhiAppCreationError(
                f"@{what} on a definition: 'type' is required (inline or via ref)")
        opts.pop("type", None)
        return stype, opts

    def _mapper(self, ann, kind: str):
        """Build the (source|sink) mapper from a nested @map annotation
        (default passThrough)."""
        map_ann = ann.nested("map")
        map_type = map_ann.element("type") if map_ann else None
        map_type = map_type or "passThrough"
        factory = self.extensions.lookup(f"{kind}_mapper", map_type)
        if factory is None:
            raise SiddhiAppCreationError(f"unknown @map(type='{map_type}') for {kind}")
        return factory(), self._ann_options(map_ann) if map_ann else {}

    def _make_breaker(self, name: str):
        """@app:limits(breaker='N'): one CircuitBreaker per transport
        endpoint, all counting on the app's RobustnessStats."""
        from siddhi_tpu.robustness import CircuitBreaker

        ctx = self.app_context
        return CircuitBreaker(
            name,
            threshold=ctx.breaker_threshold,
            cooldown_ms=ctx.breaker_cooldown_ms,
            stats=ctx.robustness,
            fault_injector=ctx.fault_injector,
        )

    def _attach_transports(self, definition, junction):
        from siddhi_tpu.transport.sink import DistributedSink, SinkStreamCallback

        for ann in definition.annotations:
            nm = ann.name.lower()
            if nm == "source":
                stype, opts = self._transport_config(ann, "source")
                factory = self.extensions.lookup("source", stype)
                if factory is None:
                    raise SiddhiAppCreationError(f"unknown @source(type='{stype}')")
                mapper, map_opts = self._mapper(ann, "source")
                mapper.init(definition, map_opts)
                src = factory()
                src.config_reader = self.siddhi_context.config_manager.generate_config_reader(
                    "source", stype)
                shm = self.siddhi_context.source_handler_manager
                if shm is not None:
                    src.handler = shm.generate(self.name, definition.id)
                    self.handler_registrations.append((shm, src.handler.element_id))
                src.init(definition, opts, mapper, junction, self.app_context)
                if self.app_context.breaker_threshold:
                    # sources have nothing to spool (their transport
                    # holds the data); the breaker just spaces out
                    # doomed connect attempts on the mixin's chain
                    src._breaker = self._make_breaker(
                        f"source:{definition.id}")
                self.sources.append(src)
            elif nm == "sink":
                stype, opts = self._transport_config(ann, "sink")
                factory = self.extensions.lookup("sink", stype)
                if factory is None:
                    raise SiddhiAppCreationError(f"unknown @sink(type='{stype}')")
                mapper, map_opts = self._mapper(ann, "sink")
                mapper.init(definition, map_opts)
                dist = ann.nested("distribution")
                if dist is not None:
                    dests = [
                        self._ann_options(d)
                        for d in dist.annotations
                        if d.name.lower() == "destination"
                    ]
                    if not dests:
                        raise SiddhiAppCreationError(
                            "@distribution needs at least one @destination"
                        )
                    sink = DistributedSink(
                        factory, dests,
                        dist.element("strategy") or "roundRobin",
                        self._ann_options(dist),
                    )
                else:
                    sink = factory()
                sink.config_reader = self.siddhi_context.config_manager.generate_config_reader(
                    "sink", stype)
                khm = self.siddhi_context.sink_handler_manager
                if khm is not None:
                    sink.handler = khm.generate(self.name, definition.id)
                    self.handler_registrations.append((khm, sink.handler.element_id))
                sink.init(definition, opts, mapper, self.app_context)
                if self.app_context.breaker_threshold:
                    # per-endpoint breakers: a distributed sink breaks
                    # each destination independently, never the fan-out
                    targets = (sink.children
                               if isinstance(sink, DistributedSink)
                               else [sink])
                    for di, child in enumerate(targets):
                        suffix = f"#{di}" if child is not sink else ""
                        child.attach_breaker(self._make_breaker(
                            f"sink:{definition.id}:{len(self.sinks)}"
                            f"{suffix}"))
                # publish failures follow the stream's @OnError contract
                # (reference: Sink.onError:354 routing into '!stream')
                sink.stream_junction = junction
                cb = SinkStreamCallback(sink)
                if self.app_context.input_journal is not None:
                    # output-ledger identity for replay dedup: stream id
                    # + ordinal keeps multiple sinks on one stream apart
                    cb.ledger_key = ("sink", definition.id, len(self.sinks))
                junction.subscribe(cb)
                self.sinks.append(sink)

    def get_or_create_junction(
        self, stream_id: str, fallback_def: StreamDefinition, is_inner=False, is_fault=False
    ) -> StreamJunction:
        key = self._key(stream_id, is_inner, is_fault)
        if key in self.junctions:
            return self.junctions[key]
        d = StreamDefinition(id=stream_id, attributes=list(fallback_def.attributes))
        return self.define_stream(d, key=key)

    def resolve_stream_definition(self, s) -> StreamDefinition:
        if isinstance(s, SingleInputStream):
            key = self._key(s.stream_id, s.is_inner, s.is_fault)
            if key in self.definitions:
                return self.definitions[key]
            raise DefinitionNotExistError(
                f"stream '{key}' is not defined in app '{self.name}'"
            )
        raise SiddhiAppCreationError(f"cannot resolve definition for {s!r}")

    def junction_for_input(self, s: SingleInputStream) -> StreamJunction:
        key = self._key(s.stream_id, s.is_inner, s.is_fault)
        if key not in self.junctions:
            raise DefinitionNotExistError(f"stream '{key}' is not defined")
        return self.junctions[key]

    def table_resolver(self, table_name: str, obj: bool = False):
        """Membership-test provider for `expr IN Table` conditions
        (``obj=True`` hands back the table itself for condition-form
        membership — see ExpressionCompiler._c_InOp)."""
        table = self.tables.get(table_name)
        if table is None:
            raise SiddhiAppCreationError(f"'IN {table_name}': table is not defined")
        return table if obj else table.contains_fn()

    # -- build --------------------------------------------------------------

    def _build_functions(self):
        """name -> expression-builder map: function extensions plus
        script-defined UDFs (``define function f[lang] ...``)."""
        from siddhi_tpu.extension.function import (
            builder_for_extension,
            make_scalar_function_builder,
        )

        fns = {}
        for full_name, factory in self.extensions.items("function"):
            fns[full_name] = builder_for_extension(factory)
        for fd in self.siddhi_app.function_definitions.values():
            engine_factory = self.extensions.lookup("script", fd.language.lower())
            if engine_factory is None:
                raise SiddhiAppCreationError(
                    f"function '{fd.id}': unknown script language '{fd.language}'")
            scalar = engine_factory().compile(fd.id, fd.body, fd.return_type)
            fns[fd.id] = make_scalar_function_builder(scalar, fd.return_type)
        return fns

    def _build_table(self, td):
        """@store tables become record-table runtimes over a store
        extension (reference: DefinitionParserHelper table wiring);
        plain tables are columnar in-memory tables."""
        from siddhi_tpu.query_api.annotation import find_annotation
        from siddhi_tpu.table import InMemoryTable, RecordTableRuntime, TableCache

        store_ann = find_annotation(td.annotations, "store")
        if store_ann is None:
            if self.app_context.devtables:
                import logging

                from siddhi_tpu.devtable import DeviceTable

                sm = self.app_context.statistics_manager
                try:
                    table = DeviceTable(
                        td, capacity=self.app_context.devtable_capacity,
                        faults=self.app_context.fault_injector,
                        tracer=self.app_context.tracer,
                        statistics_manager=sm)
                    if sm is not None:
                        sm.register_devtable(td.id, table)
                    return table
                except SiddhiAppCreationError as e:
                    logging.getLogger("siddhi_tpu").warning(
                        "table '%s': @app:devtables requested but the "
                        "table stays host-resident (%s)", td.id, e)
                    if sm is not None:
                        sm.record_devtable_fallback(f"table:{td.id}", str(e))
            return InMemoryTable(td)
        stype, options = self._transport_config(store_ann, "store")
        factory = self.extensions.lookup("store", stype)
        if factory is None:
            raise SiddhiAppCreationError(
                f"table '{td.id}': unknown store type '{stype}'")
        store = factory()
        reader = self.siddhi_context.config_manager.generate_config_reader("store", stype)
        store.init(td, options, reader)
        handler = None
        rthm = self.siddhi_context.record_table_handler_manager
        if rthm is not None:
            handler = rthm.generate(self.name, td.id)
            self.handler_registrations.append((rthm, handler.element_id))
        cache = None
        cache_ann = store_ann.nested("cache")
        if cache_ann is not None:
            size = int(cache_ann.element("size") or cache_ann.element("max.size") or "50")
            policy = (cache_ann.element("cache.policy")
                      or cache_ann.element("policy") or "FIFO")
            retention = cache_ann.element("retention.period")
            if retention:
                from siddhi_tpu.compiler.parser import parse_time_string

                retention_ms = parse_time_string(retention)
            else:
                retention_ms = None
            cache = TableCache(size, policy, retention_ms=retention_ms)
        return RecordTableRuntime(td, store, cache=cache, handler=handler)

    def _note_fused_conflicts(self, qname: str):
        """A query the fusion pre-pass claimed while another fast-path
        annotation was also pinned on the app: the documented precedence
        (fuse > shard > multiplex > hotkeys) resolved it — count the
        losing pin so the resolution is visible, not implicit."""
        sm = self.app_context.statistics_manager
        if sm is None:
            return
        if self.app_context.multiplex:
            sm.record_planner_conflict(
                qname, "@app:multiplex pinned but the query fused "
                "(precedence: fuse > multiplex)")
        if self.app_context.hotkeys:
            sm.record_planner_conflict(
                qname, "@app:hotkeys pinned but the query fused "
                "(precedence: fuse > hotkeys)")

    def build(self):
        from siddhi_tpu.core.app_runtime import SiddhiAppRuntime
        from siddhi_tpu.planner.query_planner import QueryPlanner

        self.functions = self._build_functions()

        for d in self.siddhi_app.stream_definitions.values():
            self.define_stream(d)

        from siddhi_tpu.table import InMemoryTable

        for td in self.siddhi_app.table_definitions.values():
            self.tables[td.id] = self._build_table(td)

        from siddhi_tpu.core.trigger import TriggerRuntime
        from siddhi_tpu.core.window import NamedWindowRuntime
        from siddhi_tpu.planner.expr import ExpressionCompiler, Scope

        for wd in self.siddhi_app.window_definitions.values():
            fn = wd.window_function
            if fn is None:
                raise SiddhiAppCreationError(
                    f"window '{wd.id}': missing window function"
                )
            factory = self.extensions.lookup("window", fn.name, fn.namespace)
            if factory is None:
                raise SiddhiAppCreationError(
                    f"window '{wd.id}': unknown window '{fn.name}()'"
                )
            wscope = Scope()
            for a in wd.attributes:
                wscope.add(wd.id, a.name, a.name, a.type)
            wcompiler = ExpressionCompiler(wscope, functions=self.functions)
            args = [wcompiler.compile(a) for a in fn.args]
            validate_extension_args(
                factory, fn.name, [a.type for a in args],
                where=f"named window '{wd.id}'")
            w = factory(args, wd.attribute_names)
            junction = self.define_stream(
                StreamDefinition(id=wd.id, attributes=list(wd.attributes)),
            )
            nwr = NamedWindowRuntime(wd, w, junction, self.app_context)
            self.named_windows[wd.id] = nwr
            self.scheduler.register_task(nwr)

        for td in self.siddhi_app.trigger_definitions.values():
            junction = self.junctions[td.id]  # trigger defines its stream
            tr = TriggerRuntime(td, junction, self.app_context)
            self.trigger_runtimes[td.id] = tr
            self.scheduler.register_task(tr)

        from siddhi_tpu.aggregation import AggregationRuntime

        self.aggregations: Dict[str, AggregationRuntime] = {}
        for ad in self.siddhi_app.aggregation_definitions.values():
            ar = AggregationRuntime(ad, self)
            self.aggregations[ad.id] = ar
            junction = self.junction_for_input(ad.input_stream)
            junction.subscribe(_AggregationReceiver(ar, self.app_context))

        from siddhi_tpu.core.partition import PartitionRuntime

        qp = QueryPlanner(self)
        # @app:fuse pre-pass: detect chains of device-eligible queries
        # linked by exclusive `insert into` streams and lower each chain
        # to ONE fused engine (planner/fusion.py).  Chain members come
        # back pre-planned, keyed by query identity; everything else
        # takes the ordinary per-query path below.
        fused: Dict[int, object] = {}
        # in auto (cost-model) mode the pre-pass also runs for
        # un-annotated apps — a fused chain beats any per-query lowering
        # whenever one exists (it deletes the junction hops), so the
        # model treats chain membership as the cheapest candidate; a
        # replan pin naming 'fuse' forces the pass too
        want_fuse = (self.app_context.fuse or self.app_context.plan_auto
                     or any("fuse" in str(p).split("+")
                            for p in self.app_context.plan_pins.values()))
        if want_fuse:
            from siddhi_tpu.planner.fusion import plan_fused_chains

            fused = plan_fused_chains(self, qp)
        qi = 0
        pi = 0
        self.partition_runtimes: Dict[str, object] = {}
        for element in self.siddhi_app.execution_elements:
            if isinstance(element, Query):
                qr = fused.pop(id(element), None)
                if qr is not None:
                    self._note_fused_conflicts(qr.name)
                else:
                    qr = qp.plan_query(element, qi)
                qi += 1
                if qr.name in self.query_runtimes:
                    raise SiddhiAppCreationError(f"duplicate query name '{qr.name}'")
                self.query_runtimes[qr.name] = qr
            elif isinstance(element, Partition):
                pr = PartitionRuntime(element, self, pi)
                pi += 1
                self.partition_runtimes[pr.name] = pr

        input_manager = InputManager(self.app_context)
        for key, j in self.junctions.items():
            if not key.startswith("#") and key not in self.named_windows:
                input_manager.register(j)

        runtime = SiddhiAppRuntime(
            name=self.name,
            siddhi_app=self.siddhi_app,
            app_context=self.app_context,
            junctions=self.junctions,
            query_runtimes=self.query_runtimes,
            input_manager=input_manager,
            scheduler=self.scheduler,
            tables=self.tables,
            named_windows=self.named_windows,
            partitions=self.partition_runtimes,
            aggregations=self.aggregations,
            sources=self.sources,
            sinks=self.sinks,
            functions=self.functions,
            handler_registrations=self.handler_registrations,
        )
        # the raw source rides along so a live re-plan
        # (core/app_runtime.py replan) can rebuild from a fresh parse
        runtime._app_string = self.app_string
        return runtime


class _AggregationReceiver:
    """Junction subscriber feeding an AggregationRuntime."""

    def __init__(self, aggregation_runtime, app_context):
        self.aggregation_runtime = aggregation_runtime
        self.app_context = app_context

    def receive(self, batch):
        now = self.app_context.timestamp_generator.current_time()
        self.aggregation_runtime.on_event(batch, now)
