"""Cost-based unified lowering: one static cost model over every path.

The four fast planner paths (shard / multiplex / fuse / hotkeys) grew up
as opt-in annotations with hand-written, mutually exclusive gates.  This
module turns them into *candidates*: for each query it enumerates the
eligible lowerings — including compositions the annotation gates forbid
— scores each with static shape/arity costs (batch width, window size,
partition cardinality, automaton node count, mesh size), and picks the
cheapest.  Explicit annotations act as pins that override the model;
`@app:plan(auto='true')` turns the model on for un-annotated apps.

The scores are per-batch, in arbitrary dispatch-microsecond-like units.
They only ever pick WHICH bit-identical lowering runs — a mis-scored
constant costs throughput, never correctness: every candidate the model
selects still has to pass the real eligibility gate of its path, and the
per-path fallback discipline (log.warning + counted reason) covers any
gap between the model's static view and the gate's exact one.

Composition precedence when several pinned annotations apply to one
query (the implemented build order, now documented and counted):

    fuse > shard > multiplex > hotkeys

i.e. the fusion pre-pass claims chain members before the per-query loop
runs; mesh-sharded state does not multiplex; the hotkey router only
wraps single-device dense state.  A pinned path losing to another pin is
counted on the statistics feed (plannerConflicts / plannerConflictReason).
"""

from __future__ import annotations

import logging
import math
from typing import Dict, List, Optional

from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.query_api import (
    JoinInputStream,
    Query,
    SingleInputStream,
    StateInputStream,
    WindowHandler,
)

log = logging.getLogger("siddhi_tpu")

# -- static cost constants (per batch, arbitrary units) ----------------------
# Calibrated against the relative magnitudes the bench suite observes:
# dispatch/junction overheads dominate small batches, per-event terms
# dominate large ones.  BATCH_HINT is the planning-time batch width; the
# PlanMonitor re-scores with the OBSERVED batch width at runtime.

DISPATCH = 60.0          # per-batch host dispatch + callback overhead
JUNCTION_HOP = 90.0      # EventBatch build + junction publish between queries
H2D = 25.0               # host->device staging setup per batch
HOST_PER_EVENT = 0.5     # host engine per-event cost
DEVICE_PER_EVENT = 0.004  # jitted device engine per-event cost
DENSE_NODE_PER_EVENT = 0.002  # dense NFA per-event per-automaton-node cost
SHARD_COLLECTIVE = 18.0  # per-batch collective cost, scaled by log2(mesh)
HOTKEY_ROUTER = 8.0      # sketch update + batch split per batch
HOTKEY_SKEW = 0.6        # prior: traffic share the scan slots absorb
WINDOW_LEN_HINT = 256    # window width assumed when not statically known
BATCH_HINT = 4096        # planning-time batch width


class QueryTraits:
    """Static shape facts the scorer reads — extracted from the AST only
    (no engines built), so classification can never fail an app build."""

    __slots__ = ("kind", "tumbling_batch", "aggregating", "window_len",
                 "n_nodes", "n_stages", "output_rate")

    def __init__(self, kind: str):
        self.kind = kind                # 'single' | 'state' | 'join' | 'other'
        self.tumbling_batch = False     # lengthBatch/timeBatch window
        self.aggregating = False        # group by / having / aggregators
        self.window_len = WINDOW_LEN_HINT
        self.n_nodes = 2                # automaton node count (state kind)
        self.n_stages = 1               # fused-chain stage count
        self.output_rate = False


class PlanCandidate:
    __slots__ = ("path", "cost", "feasible", "reason")

    def __init__(self, path: str, cost: float, feasible: bool = True,
                 reason: str = ""):
        self.path = path
        self.cost = cost
        self.feasible = feasible
        self.reason = reason

    def to_dict(self) -> Dict[str, object]:
        return {"path": self.path, "cost": round(self.cost, 3),
                "feasible": self.feasible, "reason": self.reason}


class PlanRecord:
    """The chosen plan for one query: candidates with costs, the pick,
    the pin that forced it (if any), the realized lowering, and the
    re-plan history — the `/siddhi-plan/<app>` payload."""

    __slots__ = ("name", "mode", "candidates", "chosen", "predicted_cost",
                 "pinned", "actual", "replans", "traits")

    def __init__(self, name: str, mode: str = "legacy"):
        self.name = name
        self.mode = mode            # 'auto' | 'pinned' | 'legacy'
        self.candidates: List[PlanCandidate] = []
        self.chosen = "host"
        self.predicted_cost = 0.0
        self.pinned: Optional[str] = None
        self.actual: Optional[str] = None
        self.replans: List[Dict[str, object]] = []
        self.traits: Optional[QueryTraits] = None

    def candidate(self, path: str) -> Optional[PlanCandidate]:
        for c in self.candidates:
            if c.path == path:
                return c
        return None

    def components(self) -> List[str]:
        return self.chosen.split("+")

    def note_replan(self, old: str, new: str, forced: bool, reason: str):
        self.replans.append({"from": old, "to": new, "forced": forced,
                             "reason": reason})

    def to_dict(self) -> Dict[str, object]:
        return {
            "mode": self.mode,
            "chosen": self.chosen,
            "predictedCost": round(self.predicted_cost, 3),
            "pinned": self.pinned,
            "actual": self.actual,
            "candidates": [c.to_dict() for c in self.candidates],
            "rejected": [c.to_dict() for c in self.candidates
                         if not c.feasible],
            "replans": list(self.replans),
        }


# -- trait extraction --------------------------------------------------------


def _window_traits(handlers, traits: QueryTraits):
    for h in handlers:
        if not isinstance(h, WindowHandler):
            continue
        if h.name in ("lengthBatch", "timeBatch"):
            traits.tumbling_batch = True
        for a in h.args:
            v = getattr(a, "value", None)
            if isinstance(v, int) and v > 0:
                traits.window_len = v
                break


def classify_query(app_planner, query: Query) -> QueryTraits:
    """AST-only classification; defensive — never raises."""
    try:
        from siddhi_tpu.planner.query_planner import QueryPlanner

        s = query.input_stream
        if isinstance(s, SingleInputStream):
            traits = QueryTraits("single")
            _window_traits(s.handlers, traits)
        elif isinstance(s, StateInputStream):
            traits = QueryTraits("state")
            traits.n_nodes = max(2, _count_state_nodes(s))
        elif isinstance(s, JoinInputStream):
            traits = QueryTraits("join")
        else:
            traits = QueryTraits("other")
        sel = query.selector
        traits.aggregating = bool(sel.group_by) or sel.having is not None \
            or QueryPlanner._has_aggregators(sel)
        traits.output_rate = query.output_rate is not None
        return traits
    except Exception:  # noqa: BLE001 — classification must never fail a build
        log.debug("cost model: classification failed; host-only traits",
                  exc_info=True)
        return QueryTraits("other")


def _count_state_nodes(st) -> int:
    """Approximate automaton node count: stream leaves of the state tree."""
    n = 0
    stack = [getattr(st, "state_element", None) or st]
    seen = set()
    while stack:
        el = stack.pop()
        if el is None or id(el) in seen:
            continue
        seen.add(id(el))
        if isinstance(getattr(el, "stream", None), SingleInputStream):
            n += 1
        for attr in ("element", "left", "right", "first", "second",
                     "elements", "state_element", "stream_elements"):
            child = getattr(el, attr, None)
            if isinstance(child, (list, tuple)):
                stack.extend(child)
            elif child is not None:
                stack.append(child)
    return n


# -- scoring -----------------------------------------------------------------


def score_path(path: str, traits: QueryTraits, ctx, batch: float) -> float:
    """Per-batch cost of ``path`` under the static model.  ``batch`` is
    the assumed batch width (BATCH_HINT at plan time; the PlanMonitor
    passes the observed width when re-scoring)."""
    nd = ctx.tpu_devices or 1
    collective = SHARD_COLLECTIVE * max(1.0, math.log2(nd)) if nd > 1 else 0.0
    slots = max(2, ctx.multiplex_slots)
    dense_ev = DENSE_NODE_PER_EVENT * traits.n_nodes * batch
    cost = 0.0
    for comp in path.split("+"):
        if comp == "host":
            cost += DISPATCH + HOST_PER_EVENT * batch \
                + 0.001 * traits.window_len
        elif comp == "device":
            cost += DISPATCH + H2D + DEVICE_PER_EVENT * batch
        elif comp == "dense":
            cost += DISPATCH + H2D + dense_ev
        elif comp == "multiplex":
            # seat amortization: the shared engine's dispatch + transfer
            # setup is paid once per cycle across every seated tenant
            cost += (DISPATCH + H2D) / slots + DEVICE_PER_EVENT * batch
        elif comp == "fuse":
            # a fused chain replaces per-stage dispatch + junction hops
            # with one dispatch; stages still cost their device step
            cost += DISPATCH + H2D \
                + traits.n_stages * DEVICE_PER_EVENT * batch \
                - (traits.n_stages - 1) * JUNCTION_HOP
        elif comp == "shard":
            # shard divides the per-event work already accumulated and
            # adds the collective
            cost = cost / nd + DISPATCH * (1 - 1 / nd) + collective
        elif comp == "hotkey":
            # the scan slots absorb the skewed share at device-query
            # rates; the dense residual shrinks by the same share
            cost -= dense_ev * HOTKEY_SKEW
            cost += HOTKEY_ROUTER + DEVICE_PER_EVENT * batch * HOTKEY_SKEW
        else:
            cost += DISPATCH
    return max(cost, 0.1)


def _check_composable(path: str, traits: QueryTraits, ctx):
    """Eligibility pre-gate for a candidate path; raises
    SiddhiAppCreationError with the rejection reason.  Mirrors (in the
    static vocabulary) the real per-path gates, plus the compositions
    that are enumerated but not yet lowerable."""
    comps = path.split("+")
    if "multiplex" in comps and "hotkey" in comps:
        raise SiddhiAppCreationError(
            "multiplex+hotkey is not composable yet: the router's state "
            "handoff assumes a dedicated engine's row ownership, shared "
            "seats would interleave promoted rows across tenants")
    if "hotkey" in comps and "shard" in comps:
        raise SiddhiAppCreationError(
            "hotkey+shard is not composable yet: the promote/demote "
            "state handoff assumes single-device partition rows")
    if "multiplex" in comps and "shard" in comps:
        raise SiddhiAppCreationError(
            "mesh-sharded state does not multiplex: seats are packed on "
            "one device engine")
    if "shard" in comps and not ctx.tpu_devices:
        raise SiddhiAppCreationError(
            "no device mesh declared (@app:execution devices='N')")
    if "multiplex" in comps:
        if traits.kind == "single" and not traits.tumbling_batch:
            raise SiddhiAppCreationError(
                "multiplex seats tumbling lengthBatch/timeBatch queries")
        if traits.kind == "state" and traits.aggregating:
            raise SiddhiAppCreationError(
                "aggregating patterns do not multiplex")
        if traits.output_rate:
            raise SiddhiAppCreationError(
                "rate-limited queries do not multiplex")
    if "hotkey" in comps and traits.aggregating:
        raise SiddhiAppCreationError(
            "hotkey scan slots serve passthrough selects only")
    if "fuse" in comps and traits.n_stages < 2:
        raise SiddhiAppCreationError("not part of a fusable chain")


def _paths_for(traits: QueryTraits, ctx) -> List[str]:
    if traits.kind == "single":
        paths = ["host", "device", "multiplex"]
        if ctx.tpu_devices:
            paths += ["device+shard", "multiplex+shard"]
    elif traits.kind == "state":
        paths = ["host", "dense", "multiplex", "dense+hotkey"]
        if ctx.tpu_devices:
            paths += ["dense+shard", "dense+hotkey+shard",
                      "multiplex+hotkey"]
        else:
            paths += ["multiplex+hotkey"]
    else:
        paths = ["host"]
    return paths


def build_plan_record(app_planner, query: Query, name: str) -> PlanRecord:
    """Enumerate + score the candidate lowerings for one query.

    Pins win over the model: a replan override (ctx.plan_pins) pins the
    exact path; legacy annotations pin their path in non-auto mode.  In
    auto mode the cheapest feasible candidate is chosen.  Every
    infeasible candidate is recorded (and — for the not-yet-composable
    compositions — counted as a planner fallback) so `/siddhi-plan`
    shows WHY a path was not taken.
    """
    ctx = app_planner.app_context
    sm = ctx.statistics_manager
    traits = classify_query(app_planner, query)
    pin_override = (getattr(ctx, "plan_pins", None) or {}).get(name)
    mode = ("pinned" if pin_override is not None
            else "auto" if getattr(ctx, "plan_auto", False) else "legacy")
    rec = PlanRecord(name, mode)
    rec.traits = traits

    if ctx.execution_mode != "tpu":
        rec.candidates.append(
            PlanCandidate("host", score_path("host", traits, ctx,
                                             BATCH_HINT)))
        rec.chosen = "host"
        rec.predicted_cost = rec.candidates[0].cost
        return rec

    for path in _paths_for(traits, ctx):
        cost = score_path(path, traits, ctx, BATCH_HINT)
        try:
            _check_composable(path, traits, ctx)
        except SiddhiAppCreationError as e:
            # a cost-gate rejection is a fallback like any other: the
            # user (or the model) wanted the path, the query is not
            # getting it — log + count, never silent
            log.warning(
                "query '%s': cost model rejected candidate '%s': %s",
                name, path, e)
            if sm is not None:
                sm.record_planner_fallback(name, f"{path}: {e}")
            rec.candidates.append(PlanCandidate(path, cost, False, str(e)))
            continue
        rec.candidates.append(PlanCandidate(path, cost))

    feasible = [c for c in rec.candidates if c.feasible]
    best = min(feasible, key=lambda c: c.cost) if feasible \
        else rec.candidates[0]
    if pin_override is not None:
        rec.pinned = pin_override
        rec.chosen = pin_override
        c = rec.candidate(pin_override)
        rec.predicted_cost = c.cost if c is not None else \
            score_path(pin_override, traits, ctx, BATCH_HINT)
    elif mode == "auto":
        rec.chosen = best.path
        rec.predicted_cost = best.cost
    else:
        # legacy: annotations steer the planner directly; record what
        # they pin so the REST dump explains the realized lowering
        pins = [p for p, on in (("fuse", ctx.fuse),
                                ("shard", bool(ctx.tpu_devices)),
                                ("multiplex", ctx.multiplex),
                                ("hotkeys", ctx.hotkeys)) if on]
        rec.pinned = "+".join(pins) if pins else None
        rec.chosen = best.path
        rec.predicted_cost = best.cost
    return rec


def fused_plan_record(name: str, ctx, n_stages: int,
                      sharded: bool = False) -> PlanRecord:
    """PlanRecord for a query the fusion pre-pass claimed (the per-query
    enumeration never sees chain members)."""
    traits = QueryTraits("single")
    traits.n_stages = max(2, n_stages)
    mode = "auto" if getattr(ctx, "plan_auto", False) else "legacy"
    rec = PlanRecord(name, mode)
    rec.traits = traits
    path = "fuse+shard" if sharded else "fuse"
    for p in ("host", "device", "fuse"):
        rec.candidates.append(
            PlanCandidate(p, score_path(p, traits, ctx, BATCH_HINT)))
    if sharded:
        rec.candidates.append(
            PlanCandidate("fuse+shard",
                          score_path("fuse+shard", traits, ctx, BATCH_HINT)))
    rec.chosen = path
    c = rec.candidate(path)
    rec.predicted_cost = c.cost if c is not None else 0.0
    rec.pinned = "fuse" if ctx.fuse and mode == "legacy" else None
    return rec
