"""Eligibility gates + wiring for the hand-written Pallas kernel layer.

``@app:kernels(...)`` asks the planner to swap the hot inner step of
eligible runtimes for a hand-written Pallas kernel
(siddhi_tpu/kernels/), each pinned bit-identical to the XLA
formulation it replaces:

- ``nfa``:  bit-packed dense-NFA step (kernels/dense_step.py) for
  every-headed simple filter chains;
- ``scan``: one fused kernel for the hotkey scan's max-plus + counting
  chains (kernels/scan_chain.py), replacing two associative-scan
  passes;
- ``bank``: collision-free segmented reduce (kernels/bank_scatter.py)
  replacing the aggregation bank's scatter-add.

Mirrors planner/hotkeys.py: every rejection raises
``SiddhiAppCreationError`` with a DISTINCT reason; the ``try_*``
wrappers convert that into a counted ``Queries.<q>.kernelFallbacks`` /
``kernelFallbackReason`` on the stats feed and leave the runtime on
its plain XLA path (graceful: @app:kernels never breaks a running
app).  Each enable ends with a smoke lowering through the real shapes,
so a Mosaic rejection on an exotic TPU generation is also a counted
fallback, not a first-batch crash.
"""

from __future__ import annotations

import logging

from siddhi_tpu.core.exceptions import SiddhiAppCreationError

log = logging.getLogger("siddhi_tpu")


def check_kernels_available() -> None:
    """Process-level gate: Pallas importable + trivial kernel lowers."""
    from siddhi_tpu.kernels import probe

    ok, reason = probe.kernels_available()
    if not ok:
        raise SiddhiAppCreationError(reason)


def check_dense_kernel_eligible(engine) -> None:
    """The bit-packed step kernel covers the every-headed simple-chain
    class only (one candidate plane bit per row, no counting/capture
    machinery).  Raises with a distinct reason outside it."""
    if engine.is_sequence:
        raise SiddhiAppCreationError(
            "nfa kernel: sequence semantics (strict contiguity masks) "
            "are not in the packed-plane step — XLA path kept")
    if not engine.every_start:
        raise SiddhiAppCreationError(
            "nfa kernel: non-every head needs reset-on-emit plane "
            "clears — XLA path kept")
    if engine.group_every:
        raise SiddhiAppCreationError(
            "nfa kernel: grouped-every restart masks are not in the "
            "packed-plane step — XLA path kept")
    if getattr(engine, "has_deadlines", False):
        raise SiddhiAppCreationError(
            "nfa kernel: absent/deadline nodes need per-chain timers — "
            "XLA path kept")
    for node in engine.nodes:
        if not (node.kind == "stream"
                and node.min_count == 1 and node.max_count == 1):
            raise SiddhiAppCreationError(
                "nfa kernel: counting/logical/absent nodes need the "
                "counts/register planes — XLA path kept")
    if engine.alloc.slots:
        raise SiddhiAppCreationError(
            "nfa kernel: captured attributes need the register file — "
            "XLA path kept")


def try_enable_dense_kernel(app, runtime, qname: str) -> bool:
    """Swap a DensePatternRuntime's step for the packed-plane kernel;
    False (counted, logged) when ineligible or the lowering fails."""
    sm = app.app_context.statistics_manager
    engine = runtime.engine
    try:
        check_kernels_available()
        check_dense_kernel_eligible(engine)
        if getattr(runtime, "mesh", None) is not None:
            raise SiddhiAppCreationError(
                "nfa kernel: mesh-sharded runtimes keep the XLA step "
                "(the kernel is single-device)")
        engine.use_kernel = True
        engine._step_cache.clear()
        try:
            from siddhi_tpu.kernels import dense_step

            dense_step.smoke_lower(engine)
        except Exception as e:
            engine.use_kernel = False
            engine._step_cache.clear()
            raise SiddhiAppCreationError(
                f"nfa kernel: lowering failed: {e}")
        runtime.lowered_to = "kernel"
        return True
    except SiddhiAppCreationError as e:
        log.warning(
            "query '%s': @app:kernels(nfa) requested but the packed "
            "step cannot be used, staying on XLA: %s", qname, e)
        if sm is not None:
            sm.record_kernel_fallback(qname, str(e))
        return False


def try_enable_scan_kernel(app, router, qname: str) -> bool:
    """Swap a hotkey router's scan step for the fused chain kernel;
    False (counted, logged) when unavailable or the lowering fails."""
    sm = app.app_context.statistics_manager
    scan = router._scan
    try:
        check_kernels_available()
        scan.use_kernel = True
        scan._step_fn = None
        try:
            from siddhi_tpu.kernels import scan_chain
            from siddhi_tpu.ops.nfa_scan import NEG

            scan_chain.smoke_lower(scan.n_nodes, scan.n_slots, NEG)
        except Exception as e:
            scan.use_kernel = False
            scan._step_fn = None
            raise SiddhiAppCreationError(
                f"scan kernel: lowering failed: {e}")
        return True
    except SiddhiAppCreationError as e:
        log.warning(
            "query '%s': @app:kernels(scan) requested but the fused "
            "chain kernel cannot be used, staying on XLA: %s", qname, e)
        if sm is not None:
            sm.record_kernel_fallback(qname, str(e))
        return False


def try_enable_bank_kernel(ctx, agg_name: str) -> bool:
    """Decide whether a DeviceBucketBank should route its scatter
    through the segmented-reduce kernel; False (counted, logged) when
    unavailable or the lowering fails."""
    sm = ctx.statistics_manager
    try:
        check_kernels_available()
        try:
            from siddhi_tpu.kernels import bank_scatter

            bank_scatter.smoke_lower()
        except Exception as e:
            raise SiddhiAppCreationError(
                f"bank kernel: lowering failed: {e}")
        return True
    except SiddhiAppCreationError as e:
        log.warning(
            "aggregation '%s': @app:kernels(bank) requested but the "
            "segmented-reduce kernel cannot be used, staying on the "
            "XLA scatter: %s", agg_name, e)
        if sm is not None:
            sm.record_kernel_fallback(agg_name, str(e))
        return False


def try_enable_query_kernels(app, runtime, qname: str) -> None:
    """The planner hook for pattern queries: enable every requested
    kernel kind the runtime can host.  Works on both plain
    DensePatternRuntime and a HotKeyRouterRuntime wrapper (whose dense
    half and scan half are gated independently)."""
    from siddhi_tpu.core.hotkey_router import HotKeyRouterRuntime

    kinds = app.app_context.kernel_kinds
    if isinstance(runtime, HotKeyRouterRuntime):
        scan_ok = ("scan" in kinds
                   and try_enable_scan_kernel(app, runtime, qname))
        dense_ok = ("nfa" in kinds
                    and try_enable_dense_kernel(app, runtime._dense, qname))
        if scan_ok or dense_ok:
            runtime.lowered_to = "hotkey+kernel"
    elif "nfa" in kinds:
        try_enable_dense_kernel(app, runtime, qname)
