"""Expression compiler: query_api expression tree -> columnar evaluator.

Replaces the reference's typed executor-tree construction
(util/parser/ExpressionParser.java:207 and the ~155 per-type×op executor
classes under core/executor/) with a single compile pass producing a
vectorized closure: ``fn(env) -> array`` where ``env`` maps column keys to
arrays.  The closure uses operator overloading only, so the same compiled
tree evaluates on numpy (host) and on jax.numpy under jit (device) for
numeric expressions.

Java arithmetic semantics are preserved where they differ from numpy:
integer division truncates toward zero and integer remainder takes the
dividend's sign (the reference executes on JVM ints —
executor/math/{Divide,Mod}ExpressionExecutor*).
"""

from __future__ import annotations

import uuid as _uuid
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core.exceptions import (
    SiddhiAppCreationError,
    SiddhiAppRuntimeError,
)
from siddhi_tpu.query_api import (
    AndOp,
    ArithmeticOp,
    AttrType,
    CompareOp,
    Constant,
    Expression,
    FunctionCall,
    InOp,
    IsNull,
    IsNullStream,
    NotOp,
    OrOp,
    TimeConstant,
    Variable,
)
from siddhi_tpu.query_api.attribute import promote

# env keys for batch metadata
TS_KEY = "__ts"
N_KEY = "__n"


@dataclass
class CompiledExpression:
    fn: Callable[[Dict[str, np.ndarray]], np.ndarray]
    type: AttrType

    def __call__(self, env: Dict[str, np.ndarray]) -> np.ndarray:
        return self.fn(env)


class Scope:
    """Resolves a Variable to (env column key, AttrType).

    For single-stream queries keys are bare attribute names; for
    joins/patterns the planner registers qualified keys like ``e1.price``
    or ``left.symbol`` as well.
    """

    def __init__(self):
        # attr name -> (key, type); ambiguous bare names map to None
        self._bare: Dict[str, Optional[Tuple[str, AttrType]]] = {}
        # (stream_ref, attr) -> (key, type)
        self._qualified: Dict[Tuple[str, str], Tuple[str, AttrType]] = {}
        # stream refs known to the scope (e.g. pattern event refs)
        self.stream_refs: set = set()

    def add(self, stream_ref: str, attr: str, key: str, attr_type: AttrType):
        self.stream_refs.add(stream_ref)
        self._qualified[(stream_ref, attr)] = (key, attr_type)
        if attr in self._bare:
            existing = self._bare[attr]
            if existing is not None and existing[0] != key:
                self._bare[attr] = None  # ambiguous — stays ambiguous
        else:
            self._bare[attr] = (key, attr_type)

    def add_bare(self, name: str, attr_type: AttrType):
        """Register an unqualified name (synthetic aggregation outputs,
        select aliases referencable from having/order-by)."""
        self._bare[name] = (name, attr_type)

    def add_bare_key(self, name: str, key: str, attr_type: AttrType):
        """Register an unqualified name bound to an explicit env key."""
        self._bare[name] = (key, attr_type)

    def clone(self) -> "Scope":
        s = Scope()
        s._bare = dict(self._bare)
        s._qualified = dict(self._qualified)
        s.stream_refs = set(self.stream_refs)
        return s

    def add_alias(self, alias: str, stream_ref: str):
        """Make `alias.attr` resolve like `stream_ref.attr`."""
        self.stream_refs.add(alias)
        for (ref, attr), v in list(self._qualified.items()):
            if ref == stream_ref:
                self._qualified[(alias, attr)] = v

    def resolve(self, var: Variable) -> Tuple[str, AttrType]:
        if var.stream_id is not None:
            hit = self._qualified.get((var.stream_id, var.attribute))
            if hit is None:
                raise SiddhiAppCreationError(
                    f"cannot resolve attribute '{var.stream_id}.{var.attribute}'"
                )
            return hit
        hit = self._bare.get(var.attribute)
        if hit is None:
            if var.attribute in self._bare:
                raise SiddhiAppCreationError(
                    f"attribute '{var.attribute}' is ambiguous; qualify with stream name"
                )
            raise SiddhiAppCreationError(f"cannot resolve attribute '{var.attribute}'")
        return hit


def _refs_stream(expr, sid: str) -> bool:
    """True when the expression tree references a Variable qualified by
    ``sid`` (used to pick condition-membership for `... in Table`)."""
    if isinstance(expr, Variable):
        return expr.stream_id == sid
    if expr is None or isinstance(expr, (str, int, float, bool)):
        return False
    for f in getattr(expr, "__dataclass_fields__", {}):
        v = getattr(expr, f)
        if isinstance(v, (list, tuple)):
            if any(_refs_stream(x, sid) for x in v):
                return True
        elif _refs_stream(v, sid):
            return True
    return False


_CMP = {
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}


def _null_safe_compare(a, b, op: str):
    """Comparison where null (None in object lanes — e.g. unmatched
    outer-join fills) compares false instead of raising, matching the
    reference's null-comparison semantics.  Engages ONLY for numpy
    object-dtype operands — jax tracers (the dense NFA jit path) and
    typed arrays take the plain vectorized comparison."""
    if getattr(a, "dtype", None) != object and getattr(b, "dtype", None) != object:
        return _CMP[op](a, b)
    a_arr, b_arr = np.broadcast_arrays(
        np.atleast_1d(np.asarray(a, dtype=object)), np.atleast_1d(np.asarray(b, dtype=object)))
    # cheap None scan (elementwise __eq__ against None); string filters —
    # the common object-lane compare — skip the masked path entirely
    none_mask = (a_arr == None) | (b_arr == None)  # noqa: E711 — elementwise
    if not none_mask.any():
        return _CMP[op](a_arr, b_arr)
    out = np.zeros(a_arr.shape, dtype=bool)
    ok = ~none_mask
    if ok.any():
        cmp = np.frompyfunc(_CMP[op], 2, 1)(a_arr[ok], b_arr[ok]).astype(bool)
        out[ok] = cmp
    return out


def _null_safe_arith(a, b, op):
    """Arithmetic where null (None in object lanes — e.g. unmatched
    outer-join fills) propagates to a null result instead of raising
    TypeError, matching the reference's arithmetic executors
    (MultiplyExpressionExecutorDouble.java:43-45 returns null when an
    operand is null).  Engages ONLY for numpy object-dtype operands —
    jax tracers and typed arrays take the plain vectorized op."""
    if getattr(a, "dtype", None) != object and getattr(b, "dtype", None) != object:
        return op(a, b)
    a_arr, b_arr = np.broadcast_arrays(
        np.atleast_1d(np.asarray(a, dtype=object)),
        np.atleast_1d(np.asarray(b, dtype=object)))
    none_mask = (a_arr == None) | (b_arr == None)  # noqa: E711 — elementwise
    if not none_mask.any():
        return np.frompyfunc(op, 2, 1)(a_arr, b_arr)
    out = np.empty(a_arr.shape, dtype=object)
    out[none_mask] = None
    ok = ~none_mask
    if ok.any():
        out[ok] = np.frompyfunc(op, 2, 1)(a_arr[ok], b_arr[ok])
    return out


def _java_int_div(a, b):
    q = a // b
    r = a - q * b
    # adjust floor division to truncation when signs differ and remainder != 0
    adjust = (r != 0) & ((a < 0) != (b < 0))
    return q + adjust


def _java_int_mod(a, b):
    r = a % b
    adjust = (r != 0) & ((a < 0) != (b < 0))
    return r - b * adjust


_NUMERIC_NP = {
    AttrType.INT: np.int32,
    AttrType.LONG: np.int64,
    AttrType.FLOAT: np.float32,
    AttrType.DOUBLE: np.float64,
}


class ExpressionCompiler:
    """Compiles expression trees against a Scope.

    ``table_resolver(name)`` supplies membership-test callables for
    ``expr IN Table`` (wired by the planner once tables exist).
    """

    def __init__(self, scope: Scope, functions: Optional[Dict] = None, table_resolver=None):
        self.scope = scope
        self.functions = dict(BUILTIN_FUNCTIONS)
        if functions:
            self.functions.update(functions)
        self.table_resolver = table_resolver

    def compile(self, expr: Expression) -> CompiledExpression:
        m = getattr(self, "_c_" + type(expr).__name__, None)
        if m is None:
            raise SiddhiAppCreationError(f"cannot compile expression node {type(expr).__name__}")
        return m(expr)

    # ---- leaves -----------------------------------------------------------

    def _c_Constant(self, e: Constant) -> CompiledExpression:
        v = e.value
        if e.type.is_numeric:
            v = _NUMERIC_NP[e.type](v)
        return CompiledExpression(lambda env: v, e.type)

    def _c_TimeConstant(self, e: TimeConstant) -> CompiledExpression:
        v = np.int64(e.value)
        return CompiledExpression(lambda env: v, AttrType.LONG)

    def _c_Variable(self, e: Variable) -> CompiledExpression:
        key, t = self.scope.resolve(e)
        return CompiledExpression(lambda env: env[key], t)

    # ---- boolean ----------------------------------------------------------

    def _c_AndOp(self, e: AndOp) -> CompiledExpression:
        l, r = self.compile(e.left), self.compile(e.right)
        return CompiledExpression(lambda env: l.fn(env) & r.fn(env), AttrType.BOOL)

    def _c_OrOp(self, e: OrOp) -> CompiledExpression:
        l, r = self.compile(e.left), self.compile(e.right)
        return CompiledExpression(lambda env: l.fn(env) | r.fn(env), AttrType.BOOL)

    def _c_NotOp(self, e: NotOp) -> CompiledExpression:
        c = self.compile(e.expr)
        return CompiledExpression(lambda env: ~c.fn(env), AttrType.BOOL)

    def _c_CompareOp(self, e: CompareOp) -> CompiledExpression:
        l, r = self.compile(e.left), self.compile(e.right)
        op = e.op

        def fn(env):
            return _null_safe_compare(l.fn(env), r.fn(env), op)

        return CompiledExpression(fn, AttrType.BOOL)

    # ---- arithmetic -------------------------------------------------------

    def _c_ArithmeticOp(self, e: ArithmeticOp) -> CompiledExpression:
        l, r = self.compile(e.left), self.compile(e.right)
        if not (l.type.is_numeric and r.type.is_numeric):
            raise SiddhiAppCreationError(
                f"arithmetic '{e.op}' on non-numeric types {l.type}/{r.type}"
            )
        out_t = promote(l.type, r.type)
        is_int = out_t in (AttrType.INT, AttrType.LONG)
        op = e.op
        if op == "+":
            raw = lambda a, b: a + b
        elif op == "-":
            raw = lambda a, b: a - b
        elif op == "*":
            raw = lambda a, b: a * b
        elif op == "/":
            raw = _java_int_div if is_int else (lambda a, b: a / b)
        elif op == "%":
            raw = _java_int_mod if is_int else (lambda a, b: a % b)
        else:
            raise SiddhiAppCreationError(f"unknown arithmetic op {op!r}")
        fn = lambda env: _null_safe_arith(l.fn(env), r.fn(env), raw)
        return CompiledExpression(fn, out_t)

    # ---- null / membership ------------------------------------------------

    def _c_IsNull(self, e: IsNull) -> CompiledExpression:
        c = self.compile(e.expr)

        # dispatch on the RUNTIME dtype, not the declared type: nulls
        # from outer joins / partial upserts ride object-dtype columns
        # regardless of the attribute's declared type (e.g. a LONG rv
        # column carrying None after a left outer join)
        def fn(env):
            v = np.asarray(c.fn(env))
            if v.dtype == object:
                return np.frompyfunc(
                    lambda x: (x is None
                               or (isinstance(x, float) and np.isnan(x))),
                    1, 1)(v).astype(bool)
            if v.dtype.kind == "f":
                return np.isnan(v)
            # native int/bool lanes have no null representation
            return np.zeros(v.shape, dtype=bool)

        return CompiledExpression(fn, AttrType.BOOL)

    def _c_IsNullStream(self, e: IsNullStream) -> CompiledExpression:
        # `e1[1] is null` — presence mask supplied by the pattern engine as
        # a column `__present.<ref>[<idx>]`
        idx = e.stream_index if e.stream_index is not None else 0
        key = f"__present.{e.stream_id}[{idx}]"
        return CompiledExpression(lambda env: ~env[key], AttrType.BOOL)

    def _c_InOp(self, e: InOp) -> CompiledExpression:
        if self.table_resolver is None:
            raise SiddhiAppCreationError(f"'IN {e.source_id}': no table resolver in this context")
        # general form: `(cond) in Table` where cond references Table.attr
        # columns — membership holds when SOME table row satisfies the
        # condition against the event (reference: the on-condition
        # compiled against the store, e.g.
        # UpdateFromTableTestCase.updateFromTableTest3's
        # `(symbol==StockTable.symbol and volume==StockTable.volume) in
        # StockTable`).  The legacy value-membership (`attr in Table`,
        # primary-key probe) stays for non-table-referencing scalars.
        if _refs_stream(e.expr, e.source_id):
            table = None
            try:
                table = self.table_resolver(e.source_id, obj=True)
            except TypeError:
                pass  # resolver without an object channel
            if table is not None:
                from siddhi_tpu.table.table import CompiledTableCondition

                cond = CompiledTableCondition(
                    table, e.expr, self.scope,
                    extra_functions=self.functions,
                    table_resolver=self.table_resolver)

                def member_cond(env):
                    n = env.get(N_KEY, 1)
                    if not isinstance(n, (int, np.integer)):
                        n = 1
                    n = max(int(n), 1)
                    out = np.zeros(n, dtype=bool)
                    # split env once per batch: array columns must be
                    # row-aligned with the batch (a short column is a
                    # planner bug — fail loudly, don't repeat v[-1])
                    arrays = {}
                    scalars = {}
                    for k, v in env.items():
                        if k == N_KEY:
                            continue
                        if isinstance(v, np.ndarray) and v.ndim >= 1:
                            if len(v) < n:
                                raise SiddhiAppRuntimeError(
                                    f"'IN {e.source_id}': env column '{k}' "
                                    f"has {len(v)} rows for a {n}-row batch")
                            arrays[k] = v
                        else:
                            scalars[k] = v
                    scalars[N_KEY] = 1
                    for i in range(n):
                        ev = dict(scalars)
                        for k, v in arrays.items():
                            ev[k] = v[i]
                        out[i] = len(cond.slots_matching(ev)) > 0
                    return out if n > 1 else out[0]

                return CompiledExpression(member_cond, AttrType.BOOL)
        member_fn = self.table_resolver(e.source_id)
        c = self.compile(e.expr)
        return CompiledExpression(lambda env: member_fn(c.fn(env)), AttrType.BOOL)

    # ---- functions --------------------------------------------------------

    def _c_FunctionCall(self, e: FunctionCall) -> CompiledExpression:
        name = (e.namespace + ":" if e.namespace else "") + e.name
        builder = self.functions.get(name)
        if builder is None:
            raise SiddhiAppCreationError(f"unknown function '{name}()'")
        args = [self.compile(a) for a in e.args]
        return builder(args)


# ---------------------------------------------------------------------------
# Builtin scalar functions (reference: core/executor/function/*)
# ---------------------------------------------------------------------------


_CAST_TARGETS = {
    "string": AttrType.STRING,
    "int": AttrType.INT,
    "long": AttrType.LONG,
    "float": AttrType.FLOAT,
    "double": AttrType.DOUBLE,
    "bool": AttrType.BOOL,
}


def _to_type(arr, t: AttrType):
    if t == AttrType.STRING:
        a = np.asarray(arr)
        out = np.frompyfunc(lambda x: None if x is None else str(x), 1, 1)(a)
        return out
    if t == AttrType.BOOL:
        a = np.asarray(arr)
        if a.dtype == object:
            out = np.frompyfunc(
                lambda x: (None if x is None
                           else x if isinstance(x, bool)
                           else str(x).lower() == "true"), 1, 1
            )(a)
            if any(x is None for x in out.reshape(-1).tolist()):
                return out
            return out.astype(bool)
        return a.astype(bool)
    dt = _NUMERIC_NP[t]
    a = np.asarray(arr)
    if a.dtype == object:
        # null-safe: None converts to None (reference per-type convert
        # executors return null for null input); the column stays
        # object-dtype when any null is present
        out = np.frompyfunc(
            lambda x: None if x is None else dt(float(x)), 1, 1)(a)
        if any(x is None for x in out.reshape(-1).tolist()):
            return out
        return out.astype(dt)
    return a.astype(dt)


def _fn_cast(args: List[CompiledExpression]) -> CompiledExpression:
    if len(args) != 2:
        raise SiddhiAppCreationError("cast(value, 'type') needs 2 args")
    # target type must be a constant string
    target = args[1].fn({})
    t = _CAST_TARGETS.get(str(target).lower())
    if t is None:
        raise SiddhiAppCreationError(f"cast: unknown target type {target!r}")
    v = args[0]
    return CompiledExpression(lambda env: _to_type(v.fn(env), t), t)


def _fn_convert(args: List[CompiledExpression]) -> CompiledExpression:
    return _fn_cast(args)


def _fn_coalesce(args: List[CompiledExpression]) -> CompiledExpression:
    if not args:
        raise SiddhiAppCreationError("coalesce() needs at least 1 arg")
    t = args[0].type

    def fn(env):
        out = np.asarray(args[0].fn(env))
        if out.dtype == object:
            out = out.copy()
            for a in args[1:]:
                nulls = np.frompyfunc(lambda x: x is None, 1, 1)(out).astype(bool)
                if not nulls.any():
                    break
                out[nulls] = np.broadcast_to(np.asarray(a.fn(env), dtype=object), out.shape)[nulls]
            return out
        if np.issubdtype(out.dtype, np.floating):
            for a in args[1:]:
                nulls = np.isnan(out)
                if not nulls.any():
                    break
                out = np.where(nulls, a.fn(env), out)
            return out
        return out

    return CompiledExpression(fn, t)


def _fn_if_then_else(args: List[CompiledExpression]) -> CompiledExpression:
    if len(args) != 3:
        raise SiddhiAppCreationError("ifThenElse(cond, then, else) needs 3 args")
    cond, then_e, else_e = args
    t = then_e.type if then_e.type != AttrType.OBJECT else else_e.type

    def fn(env):
        c = cond.fn(env)
        a = then_e.fn(env)
        b = else_e.fn(env)
        if getattr(a, "dtype", None) == object or getattr(b, "dtype", None) == object:
            a = np.asarray(a, dtype=object)
            b = np.asarray(b, dtype=object)
            c_arr = np.asarray(c)
            out = np.where(c_arr, a, b)
            return out
        return np.where(c, a, b)

    return CompiledExpression(fn, t)


def _fn_uuid(args: List[CompiledExpression]) -> CompiledExpression:
    def fn(env):
        n = env[N_KEY]
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = str(_uuid.uuid4())
        return out

    return CompiledExpression(fn, AttrType.STRING)


def _fn_event_timestamp(args: List[CompiledExpression]) -> CompiledExpression:
    return CompiledExpression(lambda env: env[TS_KEY], AttrType.LONG)


def _fn_current_time_millis(args: List[CompiledExpression]) -> CompiledExpression:
    import time as _time

    return CompiledExpression(
        lambda env: np.int64(int(_time.time() * 1000)), AttrType.LONG
    )


def _minmax(args: List[CompiledExpression], is_max: bool) -> CompiledExpression:
    if not args:
        raise SiddhiAppCreationError("maximum()/minimum() need args")
    t = args[0].type
    for a in args[1:]:
        t = promote(t, a.type)

    def fn(env):
        vals = [a.fn(env) for a in args]
        out = vals[0]
        for v in vals[1:]:
            out = np.maximum(out, v) if is_max else np.minimum(out, v)
        return out

    return CompiledExpression(fn, t)


def _fn_default(args: List[CompiledExpression]) -> CompiledExpression:
    # default(attr, fallback): replace nulls with fallback
    return _fn_coalesce(args)


def _instance_of(py_check) -> Callable:
    def builder(args: List[CompiledExpression]) -> CompiledExpression:
        v = args[0]

        def fn(env):
            a = np.asarray(v.fn(env))
            if a.dtype == object:
                return np.frompyfunc(py_check, 1, 1)(a).astype(bool)
            ok = py_check(a.dtype.type(0))
            n = a.shape[0] if a.ndim else 1
            return np.full(n, ok, dtype=bool)

        return CompiledExpression(fn, AttrType.BOOL)

    return builder


def _fn_sqrt(args: List[CompiledExpression]) -> CompiledExpression:
    if len(args) != 1:
        raise SiddhiAppCreationError("sqrt(value) needs 1 arg")
    v = args[0]

    def fn(env):
        with np.errstate(invalid="ignore"):
            return np.sqrt(np.asarray(v.fn(env), dtype=np.float64))

    return CompiledExpression(fn, AttrType.DOUBLE)


BUILTIN_FUNCTIONS: Dict[str, Callable] = {
    "sqrt": _fn_sqrt,
    "cast": _fn_cast,
    "convert": _fn_convert,
    "coalesce": _fn_coalesce,
    "ifThenElse": _fn_if_then_else,
    "UUID": _fn_uuid,
    "eventTimestamp": _fn_event_timestamp,
    "currentTimeMillis": _fn_current_time_millis,
    "maximum": lambda args: _minmax(args, True),
    "minimum": lambda args: _minmax(args, False),
    "default": _fn_default,
    "instanceOfString": _instance_of(lambda x: isinstance(x, str)),
    "instanceOfBoolean": _instance_of(lambda x: isinstance(x, (bool, np.bool_))),
    "instanceOfInteger": _instance_of(
        lambda x: isinstance(x, (int, np.int32)) and not isinstance(x, bool)
    ),
    "instanceOfLong": _instance_of(lambda x: isinstance(x, (int, np.int64)) and not isinstance(x, bool)),
    "instanceOfFloat": _instance_of(lambda x: isinstance(x, (float, np.float32))),
    "instanceOfDouble": _instance_of(lambda x: isinstance(x, (float, np.float64))),
}

# aggregator names handled by the selector, NOT scalar functions
AGGREGATOR_NAMES = {
    "sum", "avg", "count", "min", "max", "minForever", "maxForever",
    "stdDev", "distinctCount", "and", "or", "unionSet",
}
