"""Eligibility gate + wiring for the skew-aware hot-key router.

``@app:hotkeys(...)`` asks the planner to wrap eligible partitioned
dense pattern queries in a ``HotKeyRouterRuntime``
(core/hotkey_router.py): a space-saving sketch watches the junction's
key histogram and promotes heavy keys onto a batched associative-scan
engine (ops/hotkey_scan.py) while cold keys stay on the dense path.

The gate is strictly narrower than the dense gate — the scan's
exactness contract (events of one node interchangeable, state = per
-lane youngest start + count) only holds for every-headed linear
filter chains selecting final-node attributes.  Every rejection raises
``SiddhiAppCreationError`` with a DISTINCT reason; ``try_wrap_hotkey``
converts that into a counted ``Queries.<q>.hotkeyFallbacks`` /
``hotkeyFallbackReason`` on the stats feed and leaves the query on the
plain dense path (graceful: @app:hotkeys never breaks a running app).
"""

from __future__ import annotations

import logging
from typing import Optional

from siddhi_tpu.core.exceptions import SiddhiAppCreationError

log = logging.getLogger("siddhi_tpu")


def check_hotkey_eligible(st, dense_engine) -> None:
    """Gates BEYOND what the scan engine's own constructor enforces
    (linear every-headed chain, single stream, boolean device-evaluable
    filters, 2..32 nodes, no counts/logical/absent — see
    ops/nfa_scan._chain_nodes).  Raises with a distinct reason."""
    if len(dense_engine.stream_keys) != 1:
        raise SiddhiAppCreationError(
            "hotkey routing: multi-stream chains have per-stream steps "
            "the scan cannot interleave — dense path kept")
    if getattr(dense_engine, "has_deadlines", False):
        raise SiddhiAppCreationError(
            "hotkey routing: absent/deadline nodes need per-chain "
            "timers; the scan holds only youngest-start per lane — "
            "dense path kept")
    if dense_engine.alloc.slots:
        raise SiddhiAppCreationError(
            "hotkey routing: captured attributes from non-final nodes "
            "are not representable in youngest-start/count state — "
            "dense path kept")
    for _name, src in dense_engine.out_spec:
        if not (isinstance(src, tuple) and src[0] == "cand"):
            raise SiddhiAppCreationError(
                "hotkey routing: select references a non-final-node "
                "attribute — dense path kept")


def build_hotkey_router(app, st, dense_runtime, query_name: str):
    """Construct the scan engine + router for an eligible query; raises
    SiddhiAppCreationError (with the reason) when ineligible."""
    from siddhi_tpu.core.hotkey_router import HotKeyRouterRuntime
    from siddhi_tpu.ops.hotkey_scan import HotKeyScanEngine

    ctx = app.app_context
    check_hotkey_eligible(st, dense_runtime.engine)
    sid = dense_runtime.engine.stream_keys[0]
    stream_def = app.definitions.get(sid)
    if stream_def is None:
        raise SiddhiAppCreationError(
            f"hotkey routing: stream '{sid}' has no definition")
    # the scan ctor re-runs the chain walk + filter trace and raises
    # its own distinct reasons (sequence, within, non-filter handlers,
    # non-device-evaluable filters, ...)
    scan = HotKeyScanEngine(st, stream_def, n_slots=ctx.hotkey_k)
    return HotKeyRouterRuntime(
        dense_runtime, scan,
        promote=ctx.hotkey_promote, demote=ctx.hotkey_demote,
        app_context=ctx, query_name=query_name)


def try_wrap_hotkey(app, st, dense_runtime, query_name: str
                    ) -> Optional[object]:
    """The planner hook: router on success, None (with a counted,
    logged fallback reason) when the query is outside the scan class."""
    sm = app.app_context.statistics_manager
    try:
        router = build_hotkey_router(app, st, dense_runtime, query_name)
        if sm is not None:
            sm.register_hotkey_router(query_name, router)
        return router
    except SiddhiAppCreationError as e:
        log.warning(
            "query '%s': @app:hotkeys requested but query is outside "
            "the scan class, staying dense: %s", query_name, e)
        if sm is not None:
            sm.record_hotkey_fallback(query_name, str(e))
        return None
