"""@app:fuse pre-pass: lower `insert into` chains to fused device graphs.

The junction path plans every query into its own engine and routes each
inter-query hop host-side through `StreamJunction`: the producer builds
an EventBatch, the junction dispatches it, the consumer re-pads and
re-uploads it.  This pre-pass runs before the per-query planning loop
(planner/app_planner.py build) and finds chains of device-eligible
queries linked by EXCLUSIVE intermediate streams — each intermediate has
exactly one producer and one consumer, both in the chain, and no other
observer anywhere in the app — then lowers the whole chain to ONE
FusedGraphEngine (ops/fused_graph.py): one jitted program per batch
cycle, intermediate event columns resident in HBM, zero EventBatch
builds and zero junction dispatches between stages.

Anything that would make an intermediate stream observable or that the
fused engine cannot reproduce bit-identically drops back to the junction
path per chain (or per truncated chain suffix), with the reason logged
at WARNING and counted as ``Queries.<q>.fusedFallbacks`` /
``fusedFallbackReason`` on the statistics feed — the downgrade is never
silent, same contract as the sharded/multiplex planners.

Hop gates (the intermediate stream): exactly one top-level device
producer and one consumer; not a table / named window / aggregation /
trigger; not consumed by partitions, aggregations, joins, or extra
queries; declared with NO annotations (@async buffering, @sink,
@OnError, @source all need real junction dispatch); attribute types
INT / FLOAT / BOOL / DOUBLE (LONG and STRING have no device-resident
lane between stages).

Stage gates: non-tail stages are single-input device queries (kind
filter / running / sliding, no group-by, CURRENT output) with no output
rate / order-by / limit — an intermediate limiter or slice would need a
host decision mid-chain.  The tail keeps all of those (they ride the
tail QueryRuntime's host-side selector/limiter exactly like the junction
path) and may instead be an unpartitioned dense pattern over the last
intermediate stream.  A DOUBLE attribute may ride a passthrough into the
final output only if it was COMPUTED on-device somewhere in the chain
(f32 on both paths); forwarding an original f64 input column through the
whole chain would round it, so that falls back.

Direct injection into a fused intermediate stream (its InputHandler
still exists when the stream is declared) cannot enter the middle of the
fused program; a tap subscriber raises into the junction's error route
so the misuse is loud instead of silently dropped.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Set, Tuple

from siddhi_tpu.core.exceptions import SiddhiAppRuntimeError, SiddhiAppCreationError
from siddhi_tpu.core.query import QueryRuntime
from siddhi_tpu.query_api import (
    Attribute,
    AttrType,
    InsertIntoStream,
    Query,
    SingleInputStream,
    StreamDefinition,
)
from siddhi_tpu.query_api.annotation import find_annotation
from siddhi_tpu.query_api.execution import (
    AnonymousInputStream,
    JoinInputStream,
    Partition,
    StateInputStream,
)

log = logging.getLogger("siddhi_tpu")

# intermediate lanes: int32 / float32 / bool device columns (DOUBLE
# rides the f32 lane both paths compute in — see module docstring)
_LANE_TYPES = (AttrType.INT, AttrType.FLOAT, AttrType.BOOL, AttrType.DOUBLE)
_EXACT_TAIL_TYPES = (AttrType.INT, AttrType.FLOAT, AttrType.BOOL)


class _FusedIntermediateTap:
    """Loud guard on a fused intermediate stream's junction: events sent
    directly (InputHandler / another app element added later) cannot
    enter the middle of a fused device program."""

    def __init__(self, stream_id: str, chain: str):
        self.stream_id = stream_id
        self.chain = chain

    def receive(self, batch):
        raise SiddhiAppRuntimeError(
            f"stream '{self.stream_id}' is fused device-resident inside "
            f"chain '{self.chain}'; direct injection is not supported — "
            "remove @app:fuse to restore junction dispatch")


def _query_inputs(q: Query) -> List[str]:
    """Stream ids a query consumes (with multiplicity); inner/fault
    streams come back with their junction-key prefix so they can never
    collide with a fusable hop target."""
    out: List[str] = []

    def _single(s: SingleInputStream):
        if s.is_fault:
            out.append("!" + s.stream_id)
        elif s.is_inner:
            out.append("#" + s.stream_id)
        else:
            out.append(s.stream_id)

    def walk(ins):
        if isinstance(ins, SingleInputStream):
            _single(ins)
        elif isinstance(ins, JoinInputStream):
            for side in (ins.left, ins.right):
                if isinstance(side, SingleInputStream):
                    _single(side)
                else:
                    walk(side)
        elif isinstance(ins, StateInputStream):
            out.extend(ins.stream_ids())
        elif isinstance(ins, AnonymousInputStream):
            walk(ins.query.input_stream)

    walk(q.input_stream)
    return out


def _insert_target(q: Query) -> Optional[str]:
    """The query's `insert into` target when it is a plain (non-inner,
    non-fault) CURRENT-event stream insert; None otherwise."""
    out = q.output_stream
    if not isinstance(out, InsertIntoStream):
        return None
    if out.is_inner or out.is_fault:
        return None
    if getattr(out, "event_type", "current") != "current":
        return None
    return out.target


def plan_fused_chains(app, qp) -> Dict[int, QueryRuntime]:
    """Detect and lower fused chains; returns pre-planned runtimes keyed
    by ``id(query_ast)`` for the build loop to consume.  Queries absent
    from the map plan normally."""
    sa = app.siddhi_app
    ctx = app.app_context
    sm = ctx.statistics_manager

    def fallback(qname: str, reason: str):
        log.warning("query '%s': fused chain unavailable (%s); using "
                    "junction dispatch", qname, reason)
        if sm is not None:
            sm.record_fused_fallback(qname, reason)

    # -- top-level queries with their plan() names ---------------------------
    entries: List[Tuple[Query, str]] = []
    qi = 0
    for element in sa.execution_elements:
        if isinstance(element, Query):
            info = find_annotation(element.annotations, "info")
            name = (info.element("name") if info else None) or f"query_{qi}"
            entries.append((element, name))
            qi += 1

    # -- producer / consumer maps --------------------------------------------
    producers: Dict[str, List[int]] = {}
    consumers: Dict[str, List[int]] = {}
    for i, (q, _name) in enumerate(entries):
        t = _insert_target(q)
        if t is not None:
            producers.setdefault(t, []).append(i)
        for sid in _query_inputs(q):
            consumers.setdefault(sid, []).append(i)
    # streams observed outside the top-level query set: partitions,
    # aggregations — any such observer pins the stream to its junction
    other: Set[str] = set()
    for element in sa.execution_elements:
        if isinstance(element, Partition):
            for pq in element.queries:
                other.update(_query_inputs(pq))
            for pt in element.partition_types:
                other.add(getattr(pt, "stream_id", ""))
    for ad in sa.aggregation_definitions.values():
        other.add(ad.input_stream.stream_id)

    def hop_reason(t: str) -> Optional[str]:
        """None when stream ``t`` may fuse away; else why not."""
        if t in sa.table_definitions:
            return f"'{t}' is a table — table hops stay host-side"
        if t in sa.window_definitions:
            return f"'{t}' is a named window — CURRENT+EXPIRED semantics"
        if t in sa.aggregation_definitions:
            return f"'{t}' feeds an aggregation"
        if t in sa.trigger_definitions:
            return f"'{t}' is a trigger stream"
        if len(producers.get(t, [])) != 1:
            return f"stream '{t}' has multiple producers"
        if t in other:
            return (f"stream '{t}' is consumed by a partition or "
                    "aggregation")
        # multiplicity within ONE consumer is fine (a pattern tail may
        # reference its input stream at several automaton nodes); the
        # stage gates validate that shape
        cons = sorted(set(consumers.get(t, [])))
        if len(cons) != 1:
            return (f"stream '{t}' needs exactly one consumer query "
                    f"(has {len(cons)})")
        d = sa.stream_definitions.get(t)
        if d is not None:
            ann = [a.name for a in getattr(d, "annotations", [])]
            if ann:
                # @async buffering, @sink publication, @OnError routing,
                # @source all require real junction dispatch
                return (f"stream '{t}' is annotated "
                        f"({', '.join('@' + a for a in sorted(ann))}) — "
                        "junction semantics required")
        return None

    # -- chain edges ---------------------------------------------------------
    nxt: Dict[int, Tuple[int, str]] = {}
    prev: Dict[int, int] = {}
    for t, prods in producers.items():
        reason = hop_reason(t)
        if reason is not None:
            # only a would-be hop is a fallback; a terminal output
            # stream with no consumers is just the chain's end
            if (consumers.get(t) or t in sa.table_definitions
                    or t in sa.window_definitions
                    or t in sa.aggregation_definitions):
                fallback(entries[prods[0]][1], reason)
            continue
        p, c = prods[0], consumers[t][0]
        if p == c:
            fallback(entries[p][1], f"stream '{t}' forms a self-loop")
            continue
        nxt[p] = (c, t)
        prev[c] = p

    # -- maximal chains (in-degree/out-degree <= 1 => simple paths) ----------
    fused: Dict[int, QueryRuntime] = {}
    seen: Set[int] = set()
    for start in sorted(nxt):
        if start in seen or start in prev:
            continue
        run: List[int] = [start]
        hops: List[str] = []
        node = start
        while node in nxt:
            node, t = nxt[node]
            if node in run:  # cycle guard (unreachable with in-deg <= 1)
                break
            run.append(node)
            hops.append(t)
        seen.update(run)
        while len(run) >= 2:
            planned = _try_lower_chain(app, qp, entries, run, hops,
                                       fallback)
            if planned is not None:
                fused.update(planned)
                break
            # _try_lower_chain recorded the failing stage; retry the
            # prefix without it (the truncated tail's junction output is
            # re-planned normally by the build loop)
            run = run[:-1]
            hops = hops[:-1]
    return fused


def _stage_gate(q: Query, name: str, is_tail: bool):
    """Cheap AST-level eligibility for a chain member; raises with the
    fallback reason."""
    out = q.output_stream
    if out is not None and getattr(out, "event_type", "current") != "current":
        raise SiddhiAppCreationError("device path emits CURRENT events only")
    if not is_tail:
        if not isinstance(q.input_stream, SingleInputStream):
            raise SiddhiAppCreationError(
                "interior stages must be single-input queries")
        if q.output_rate is not None:
            raise SiddhiAppCreationError(
                "an intermediate output rate limit needs a host decision "
                "mid-chain")
        sel = q.selector
        if sel.order_by or sel.limit is not None or sel.offset is not None:
            raise SiddhiAppCreationError(
                "an intermediate order by/limit slices rows mid-chain")
    elif not isinstance(q.input_stream,
                        (SingleInputStream, StateInputStream)):
        raise SiddhiAppCreationError(
            "join tails need the host join planner")
    if q.selector.group_by:
        raise SiddhiAppCreationError(
            "group-by stages keep per-group emission state host-side")


def _try_lower_chain(app, qp, entries, run: List[int], hops: List[str],
                     fallback) -> Optional[Dict[int, QueryRuntime]]:
    """Build engines + runtime wiring for one chain; returns the planned
    runtimes or None after recording the failing stage's reason (caller
    retries the prefix)."""
    from siddhi_tpu.ops.device_query import DeviceQueryEngine
    from siddhi_tpu.ops.fused_graph import FusedGraphEngine

    sa = app.siddhi_app
    ctx = app.app_context
    chain_names = [entries[i][1] for i in run]
    chain_label = "->".join(chain_names)

    # a replan pin is an EXACT path override: a member pinned away from
    # 'fuse' (e.g. {'q1': 'device'}) un-claims the whole chain and the
    # per-query loop lowers each member under its own pin
    pins = getattr(ctx, "plan_pins", None) or {}
    for nm in chain_names:
        p = pins.get(nm)
        if p is not None and "fuse" not in str(p).split("+"):
            log.info("chain %s: member '%s' pinned to '%s' — chain left "
                     "to per-query planning", chain_label, nm, p)
            return {}

    # synthesize undeclared intermediate defs from producer schemas as
    # we go; declared defs must match the producer's output exactly
    # (the junction path's insert-into contract)
    stages: List = []
    # DOUBLE attrs of the CURRENT hop def that are f32-exact (computed
    # on-device, not forwarded from an original f64 input column)
    exact_f64: Set[str] = set()
    dense_tail = None
    dense_key: Optional[str] = None
    inter_defs: List[StreamDefinition] = []

    for pos, idx in enumerate(run):
        q, name = entries[idx]
        is_tail = pos == len(run) - 1
        try:
            _stage_gate(q, name, is_tail)
            if is_tail and isinstance(q.input_stream, StateInputStream):
                dense_tail, dense_key = _build_dense_tail(
                    app, qp, q, hops[pos - 1], inter_defs)
                break
            s = q.input_stream
            if pos == 0:
                definition = app.resolve_stream_definition(s)
                if not (s.is_inner or s.is_fault):
                    if (s.stream_id in app.named_windows
                            or s.stream_id in app.tables
                            or s.stream_id in getattr(
                                app, "aggregations", {})):
                        raise SiddhiAppCreationError(
                            "named-window/table/aggregation inputs need "
                            "the host planner")
            else:
                definition = inter_defs[pos - 1]
            engine = DeviceQueryEngine(
                q, definition,
                n_groups=ctx.tpu_partitions,
                partition_mode=False,
                defer_order_by=True,
            )
            if not is_tail:
                exact_f64 = _check_hop_def(
                    sa, hops[pos], engine, exact_f64, inter_defs)
            else:
                for kind, v, _nm in engine.out_spec:
                    if kind != "passthrough":
                        continue
                    at = definition.attribute_type(v)
                    if at in _EXACT_TAIL_TYPES:
                        continue
                    if at == AttrType.DOUBLE and v in exact_f64:
                        continue
                    raise SiddhiAppCreationError(
                        f"tail passthrough of {at.name} attribute '{v}' "
                        "would lose precision on the device lane")
            stages.append(engine)
        except SiddhiAppCreationError as e:
            fallback(name, f"chain {chain_label}: {e}")
            return None

    graph = None
    tail_name = chain_names[-1]
    nd = ctx.tpu_devices
    pin = str(ctx.plan_pins.get(tail_name, "") or "")
    want_shard = bool(nd) and dense_tail is None and (
        ctx.plan_auto or "shard" in pin.split("+"))
    if want_shard and "shard" not in pin.split("+") and pin:
        # an explicit replan pin without 'shard' stays single-device
        want_shard = False
    if want_shard:
        from siddhi_tpu.parallel.fused_shard import ShardedFusedGraphEngine

        sm = ctx.statistics_manager
        try:
            graph = ShardedFusedGraphEngine(stages, qp._get_mesh(nd))
            log.info("fused chain %s: batch axis sharded over %d devices",
                     chain_label, nd)
        except SiddhiAppCreationError as e:
            # NOT silent: the mesh stays idle for this chain, so log the
            # reason and count it on the statistics feed before falling
            # back to the single-device fused engine
            log.warning("query '%s': fuse+shard unavailable (%s); "
                        "single-device fused engine used", tail_name, e)
            if sm is not None:
                sm.record_sharded_fallback(tail_name, str(e))
    if graph is None:
        try:
            graph = FusedGraphEngine(stages, dense_tail, dense_key)
        except SiddhiAppCreationError as e:
            fallback(tail_name, f"chain {chain_label}: {e}")
            return None
    return _wire_chain(app, qp, entries, run, hops, graph, chain_label)


def _check_hop_def(sa, t: str, engine, exact_f64: Set[str],
                   inter_defs: List[StreamDefinition]) -> Set[str]:
    """Validate (or synthesize) the intermediate stream def for hop
    ``t`` against the producer engine's output schema; appends the def
    used and returns the next hop's f32-exact DOUBLE attr set."""
    out_names = list(engine.output_names)
    out_types = list(engine.out_types)
    for nm, at in zip(out_names, out_types):
        if at not in _LANE_TYPES:
            raise SiddhiAppCreationError(
                f"intermediate attribute '{nm}' is {at.name} — no "
                "device-resident lane between stages")
    d = sa.stream_definitions.get(t)
    if d is not None:
        if (d.attribute_names != out_names
                or [a.type for a in d.attributes] != out_types):
            raise SiddhiAppCreationError(
                f"stream '{t}' schema differs from the producer's "
                "output — junction coercion required")
    else:
        d = StreamDefinition(id=t, attributes=[
            Attribute(nm, at) for nm, at in zip(out_names, out_types)])
    inter_defs.append(d)
    # a DOUBLE stays f32-exact through an expr (computed in f32 on both
    # paths) and through a passthrough of an already-exact value
    nxt: Set[str] = set()
    for kind, v, nm in engine.out_spec:
        if kind == "expr":
            nxt.add(nm)
        elif kind == "passthrough" and v in exact_f64:
            nxt.add(nm)
    return nxt


def _build_dense_tail(app, qp, q: Query, in_stream: str,
                      inter_defs: List[StreamDefinition]):
    """Dense-pattern tail over the last intermediate stream.  The fused
    form covers the unpartitioned passthrough-selector subset; the
    engine itself re-raises for everything deeper."""
    from siddhi_tpu.core.dense_pattern import build_dense_engine

    st = q.input_stream
    sids = st.stream_ids()
    if len(set(sids)) != 1 or sids[0] != in_stream:
        raise SiddhiAppCreationError(
            "pattern tails must read the chain's intermediate stream "
            "only")
    sel = q.selector
    if sel.group_by or sel.having is not None or qp._has_aggregators(sel):
        raise SiddhiAppCreationError(
            "aggregating pattern selectors need host match rows")

    # the intermediate defs may be synthesized (undeclared `insert into`
    # targets) — resolve those ahead of the app registry
    by_id = {d.id: d for d in inter_defs}

    def resolver(s):
        if (isinstance(s, SingleInputStream)
                and not (s.is_inner or s.is_fault)
                and s.stream_id in by_id):
            return by_id[s.stream_id]
        return app.resolve_stream_definition(s)

    engine = build_dense_engine(
        q, st, resolver, 1, n_instances=app.app_context.tpu_instances)
    return engine, engine.stream_keys[0]


def _wire_chain(app, qp, entries, run: List[int], hops: List[str],
                graph, chain_label: str) -> Dict[int, QueryRuntime]:
    """Plan the chain's QueryRuntimes around one FusedChainRuntime: the
    tail query owns the runtime (selector/output/rate-limiter exactly as
    its standalone device form), interior queries get inert runtimes so
    names, persistence layout, and the stats feed stay uniform."""
    from siddhi_tpu.core.dense_pattern import output_attr_types
    from siddhi_tpu.core.fused_graph import (
        FusedChainRuntime,
        _FusedChainReceiver,
    )
    from siddhi_tpu.planner.query_planner import (
        PassThroughRateLimiter,
        _RateLimiterTask,
    )

    ctx = app.app_context
    tail_q, tail_name = entries[run[-1]]
    if graph.dense is not None:
        out_types = output_attr_types(graph.dense)
    else:
        out_types = graph.stages[-1].out_types
    out_target = (getattr(tail_q.output_stream, "target", None)
                  or f"__ret_{tail_name}")
    out_attrs = [Attribute(nm, t)
                 for nm, t in zip(graph.output_names, out_types)]
    selector = qp._passthrough_selector(
        tail_q.selector, graph.output_names, out_target)
    out_def = StreamDefinition(id=out_target, attributes=out_attrs)
    output = qp._plan_output(tail_q, out_def)
    rate_limiter = qp._plan_rate_limiter(tail_q)
    qr = QueryRuntime(tail_name, [[]], selector, rate_limiter, output, ctx)

    runtime = FusedChainRuntime(
        graph, f"#fused_{tail_name}", emit=lambda b: qr.process(b, 0),
        emit_depth=ctx.tpu_emit_depth,
        clock=ctx.timestamp_generator.current_time,
        faults=ctx.fault_injector,
        ingest_depth=ctx.tpu_ingest_depth,
        tracer=ctx.tracer)
    qr.device_runtime = runtime

    head_q, _hn = entries[run[0]]
    junction = app.junction_for_input(head_q.input_stream)
    junction.subscribe(_FusedChainReceiver(runtime))
    app.scheduler.register_task(runtime)
    if rate_limiter.needs_scheduler_task:
        task = _RateLimiterTask(qr, rate_limiter, device_runtime=runtime)
        qr._rate_task = task
        app.scheduler.register_task(task)
    lowered = ("fuse+shard"
               if getattr(graph, "engine_kind", "") == "fused_shard"
               else "fused")
    qr.lowered_to = lowered

    planned: Dict[int, QueryRuntime] = {id(tail_q): qr}

    # interior queries: the junction path would register one runtime per
    # name — keep that registry (and its duplicate-name check) intact
    # with inert placeholders whose work lives inside the fused program.
    # Their intermediate junctions stay registered (when declared) with
    # a loud tap against direct injection.
    for pos, idx in enumerate(run[:-1]):
        q, name = entries[idx]
        iqr = QueryRuntime(
            name, [[]],
            qp._passthrough_selector(
                q.selector, graph.stages[pos].output_names, hops[pos]),
            PassThroughRateLimiter(),
            _InertOutput(), ctx)
        iqr.lowered_to = lowered
        planned[id(q)] = iqr
        if hops[pos] in app.junctions:
            app.junctions[hops[pos]].subscribe(
                _FusedIntermediateTap(hops[pos], chain_label))
    # per-member plan records: the per-query cost enumeration never sees
    # chain members (the pre-pass claims them), so register theirs here
    sm = ctx.statistics_manager
    if sm is not None and hasattr(sm, "register_plan"):
        from siddhi_tpu.planner.costmodel import fused_plan_record

        n_total = len(graph.stages) + (1 if graph.dense is not None else 0)
        for idx in run:
            _q, nm = entries[idx]
            rec = fused_plan_record(nm, ctx, n_total,
                                    sharded=(lowered == "fuse+shard"))
            rec.actual = lowered
            sm.register_plan(nm, rec)
    log.info("fused chain %s: %d stages lowered to one device program",
             chain_label, len(graph.stages)
             + (1 if graph.dense is not None else 0))
    return planned


class _InertOutput:
    """Output slot of an interior chain query: its emission happens
    inside the fused program, so nothing ever flows through here."""

    def send(self, batch, now):  # pragma: no cover - unreachable by design
        raise SiddhiAppRuntimeError(
            "interior fused-chain queries emit inside the fused device "
            "program")
