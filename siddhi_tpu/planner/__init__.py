"""Planner: query_api AST -> executable runtime graph.

The analog of the reference's ``core/util/parser`` package
(SiddhiAppParser/QueryParser/ExpressionParser — SURVEY.md §3.1), but the
product is different: instead of an object graph of per-event processors,
queries lower to columnar step functions (numpy host path, jax device
path) wired between stream junctions.
"""
