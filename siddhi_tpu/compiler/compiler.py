"""SiddhiCompiler: public compile entry points.

Mirrors ``io.siddhi.query.compiler.SiddhiCompiler`` (SiddhiCompiler.java:63
``parse``, :193 ``parseOnDemandQuery``, :233 ``updateVariables``).
"""

from __future__ import annotations

import os
import re
from typing import Dict, Optional

from siddhi_tpu.compiler.parser import Parser, SiddhiParserError
from siddhi_tpu.compiler.tokenizer import TokenizeError, tokenize
from siddhi_tpu.query_api import (
    OnDemandQuery,
    Query,
    SiddhiApp,
    StreamDefinition,
    TableDefinition,
    AggregationDefinition,
    Partition,
)

_VAR_PATTERN = re.compile(r"\$\{(\w+)\}")


def _tokenize(src: str):
    """Tokenize, normalizing lexer failures to SiddhiParserError so every
    compile entry point has one error contract."""
    try:
        return tokenize(src)
    except TokenizeError as e:
        raise SiddhiParserError(str(e)) from e


class SiddhiCompiler:
    @staticmethod
    def update_variables(app_str: str, env: Optional[Dict[str, str]] = None) -> str:
        """Substitute ``${var}`` with environment/system values pre-parse
        (reference: SiddhiCompiler.updateVariables:233)."""

        def repl(m: re.Match) -> str:
            name = m.group(1)
            if env and name in env:
                return env[name]
            if name in os.environ:
                return os.environ[name]
            raise SiddhiParserError(f"no system or environment variable found for '${{{name}}}'")

        return _VAR_PATTERN.sub(repl, app_str)

    @staticmethod
    def parse(app_str: str) -> SiddhiApp:
        return Parser(_tokenize(app_str)).parse_app()

    @staticmethod
    def parse_query(query_str: str) -> Query:
        p = Parser(_tokenize(query_str))
        anns = p.parse_annotations()
        return p.parse_query(anns)

    @staticmethod
    def parse_stream_definition(s: str) -> StreamDefinition:
        app = SiddhiCompiler.parse(s)
        return next(iter(app.stream_definitions.values()))

    @staticmethod
    def parse_table_definition(s: str) -> TableDefinition:
        app = SiddhiCompiler.parse(s)
        return next(iter(app.table_definitions.values()))

    @staticmethod
    def parse_partition(s: str) -> Partition:
        p = Parser(_tokenize(s))
        anns = p.parse_annotations()
        return p.parse_partition(anns)

    @staticmethod
    def parse_aggregation_definition(s: str) -> AggregationDefinition:
        app = SiddhiCompiler.parse(s)
        return next(iter(app.aggregation_definitions.values()))

    @staticmethod
    def parse_on_demand_query(s: str) -> OnDemandQuery:
        p = Parser(_tokenize(s))
        return p.parse_on_demand_query()

    # alias matching the deprecated reference API name
    parse_store_query = parse_on_demand_query
