"""SiddhiQL compiler: text -> query_api AST.

TPU-native replacement for the reference's ANTLR4 pipeline
(``modules/siddhi-query-compiler``, grammar ``SiddhiQL.g4``): a hand-rolled
tokenizer + recursive-descent parser covering the same rule set, entry
points mirroring ``SiddhiCompiler`` (SiddhiCompiler.java:63,:193,:233).
"""

from siddhi_tpu.compiler.compiler import SiddhiCompiler, SiddhiParserError
