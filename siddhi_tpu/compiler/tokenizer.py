"""SiddhiQL tokenizer.

Covers the lexer rules of the reference grammar
(``siddhi-query-compiler/src/main/antlr4/.../SiddhiQL.g4:720-918``):
case-insensitive keywords, quoted identifiers, numeric literals with
L/F/D suffixes, single/double/triple-quoted strings, ``--`` and ``/* */``
comments, ``{ ... }`` script bodies, and the operator/symbol set.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional


class TokenizeError(Exception):
    def __init__(self, msg: str, line: int, col: int):
        super().__init__(f"{msg} at line {line}:{col}")
        self.line = line
        self.col = col


# token kinds
ID = "ID"
INT = "INT"
LONG = "LONG"
FLOAT = "FLOAT"
DOUBLE = "DOUBLE"
STRING = "STRING"
SCRIPT = "SCRIPT"
SYM = "SYM"
KW = "KW"
EOF = "EOF"

KEYWORDS = {
    "define", "stream", "table", "app", "from", "partition", "window", "select",
    "group", "by", "order", "asc", "desc", "limit", "offset", "having", "insert",
    "delete", "update", "return", "events", "into", "output", "expired", "current",
    "snapshot", "for", "raw", "of", "as", "at", "or", "and", "in", "is", "not", "on",
    "within", "with", "begin", "end", "null", "every", "last", "all", "first",
    "join", "inner", "outer", "right", "left", "full", "unidirectional", "aggregation",
    "aggregate", "per", "set", "trigger", "function", "string", "int", "long",
    "float", "double", "bool", "object", "true", "false",
}

# time-unit lexemes -> milliseconds multiplier (grammar SiddhiQL.g4:829-836;
# month = 30 days, year = 365 days as in the reference TimeConstant builders)
TIME_UNITS = {}
for _names, _ms in [
    (("millisecond", "milliseconds", "millisec", "ms"), 1),
    (("sec", "second", "seconds"), 1000),
    (("min", "minute", "minutes"), 60_000),
    (("hour", "hours"), 3_600_000),
    (("day", "days"), 86_400_000),
    (("week", "weeks"), 604_800_000),
    (("month", "months"), 2_592_000_000),
    (("year", "years"), 31_536_000_000),
]:
    for _n in _names:
        TIME_UNITS[_n] = _ms

MULTI_SYMS = ["...", "->", "==", "!=", "<=", ">="]
SINGLE_SYMS = set("@()[]{}:;,.#!=<>+-*/%?")


@dataclass
class Token:
    kind: str
    text: str  # for KW: lowercased; for ID/STRING: literal text
    line: int
    col: int
    value: object = None  # parsed numeric value

    def __repr__(self):
        return f"{self.kind}({self.text!r})"


def tokenize(src: str, script_mode_hint: bool = True) -> List[Token]:
    """Tokenize SiddhiQL source.

    ``{ ... }`` blocks are lexed as single SCRIPT tokens (function bodies),
    matching the reference lexer's SCRIPT rule.
    """
    toks: List[Token] = []
    i, n = 0, len(src)
    line, col = 1, 1

    def advance(k: int):
        nonlocal i, line, col
        for _ in range(k):
            if i < n and src[i] == "\n":
                line += 1
                col = 1
            else:
                col += 1
            i += 1

    while i < n:
        c = src[i]
        # whitespace
        if c in " \t\r\n\x0b":
            advance(1)
            continue
        # comments
        if src.startswith("--", i):
            j = src.find("\n", i)
            advance((j - i) if j != -1 else (n - i))
            continue
        if src.startswith("/*", i):
            j = src.find("*/", i + 2)
            advance(((j + 2) - i) if j != -1 else (n - i))
            continue
        tl, tc = line, col
        # script block { ... } with nesting
        if c == "{":
            depth = 0
            j = i
            while j < n:
                if src[j] == "{":
                    depth += 1
                elif src[j] == "}":
                    depth -= 1
                    if depth == 0:
                        break
                elif src[j] in "\"'":
                    quote = src[j]
                    j += 1
                    while j < n and src[j] != quote:
                        j += 1
                elif src.startswith("//", j):
                    # script-internal line comment: braces inside don't count
                    # (reference SCRIPT_ATOM rule, SiddhiQL.g4:883-887)
                    while j < n and src[j] != "\n":
                        j += 1
                j += 1
            if j >= n:
                raise TokenizeError("unterminated '{' script block", tl, tc)
            text = src[i : j + 1]
            toks.append(Token(SCRIPT, text, tl, tc, value=text[1:-1]))
            advance(j + 1 - i)
            continue
        # strings
        if src.startswith('"""', i):
            j = src.find('"""', i + 3)
            if j == -1:
                raise TokenizeError("unterminated triple-quoted string", tl, tc)
            toks.append(Token(STRING, src[i + 3 : j], tl, tc, value=src[i + 3 : j]))
            advance(j + 3 - i)
            continue
        if c in "'\"":
            j = i + 1
            while j < n and src[j] != c:
                if src[j] == "\n":
                    raise TokenizeError("unterminated string literal", tl, tc)
                j += 1
            if j >= n:
                raise TokenizeError("unterminated string literal", tl, tc)
            toks.append(Token(STRING, src[i + 1 : j], tl, tc, value=src[i + 1 : j]))
            advance(j + 1 - i)
            continue
        # quoted identifier
        if c == "`":
            j = src.find("`", i + 1)
            if j == -1:
                raise TokenizeError("unterminated quoted identifier", tl, tc)
            toks.append(Token(ID, src[i + 1 : j], tl, tc))
            advance(j + 1 - i)
            continue
        # numbers
        if c.isdigit() or (c == "." and i + 1 < n and src[i + 1].isdigit()):
            j = i
            seen_dot = False
            seen_exp = False
            while j < n:
                ch = src[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_exp and j + 1 < n and src[j + 1].isdigit():
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_exp and j + 1 < n and (
                    src[j + 1].isdigit() or (src[j + 1] in "+-" and j + 2 < n and src[j + 2].isdigit())
                ):
                    seen_exp = True
                    j += 1
                    if src[j] in "+-":
                        j += 1
                else:
                    break
            text = src[i:j]
            kind = None
            if j < n and src[j] in "lL" and not seen_dot and not seen_exp:
                kind, j = LONG, j + 1
                val = int(text)
            elif j < n and src[j] in "fF":
                kind, j = FLOAT, j + 1
                val = float(text)
            elif j < n and src[j] in "dD":
                kind, j = DOUBLE, j + 1
                val = float(text)
            elif seen_dot or seen_exp:
                kind, val = DOUBLE, float(text)
            else:
                kind, val = INT, int(text)
            toks.append(Token(kind, text, tl, tc, value=val))
            advance(j - i)
            continue
        # identifiers / keywords
        if c.isalpha() or c == "_":
            j = i
            while j < n and (src[j].isalnum() or src[j] == "_"):
                j += 1
            text = src[i:j]
            low = text.lower()
            if low in KEYWORDS or low in TIME_UNITS:
                toks.append(Token(KW, low, tl, tc, value=text))
            else:
                toks.append(Token(ID, text, tl, tc))
            advance(j - i)
            continue
        # symbols
        matched = False
        for ms in MULTI_SYMS:
            if src.startswith(ms, i):
                toks.append(Token(SYM, ms, tl, tc))
                advance(len(ms))
                matched = True
                break
        if matched:
            continue
        if c in SINGLE_SYMS:
            toks.append(Token(SYM, c, tl, tc))
            advance(1)
            continue
        raise TokenizeError(f"unexpected character {c!r}", tl, tc)

    toks.append(Token(EOF, "", line, col))
    return toks
