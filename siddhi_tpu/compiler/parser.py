"""Recursive-descent SiddhiQL parser: tokens -> query_api AST.

Covers the reference grammar's rule set (SiddhiQL.g4): definitions
(stream/table/window/trigger/function/aggregation), annotations, queries
(standard/join/pattern/sequence inputs), selection/group-by/having/
order-by/limit/offset, output rate limiting, query outputs (insert/
delete/update/update-or-insert/return), partitions, and on-demand (store)
queries.  Expression precedence mirrors the ANTLR alternative order:
NOT > */% > +- > relational > equality > IN > AND > OR.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from siddhi_tpu.compiler import tokenizer as T
from siddhi_tpu.compiler.tokenizer import Token, tokenize
from siddhi_tpu.query_api import (
    Annotation,
    Attribute,
    AttrType,
    SiddhiApp,
    # expressions
    Expression,
    Constant,
    TimeConstant,
    Variable,
    FunctionCall,
    ArithmeticOp,
    CompareOp,
    AndOp,
    OrOp,
    NotOp,
    InOp,
    IsNull,
    # definitions
    StreamDefinition,
    TableDefinition,
    WindowDefinition,
    TriggerDefinition,
    FunctionDefinition,
    AggregationDefinition,
    # execution
    Query,
    Selector,
    OutputAttribute,
    OrderByAttribute,
    SingleInputStream,
    JoinInputStream,
    StateInputStream,
    Filter,
    StreamFunction,
    WindowHandler,
    StreamStateElement,
    AbsentStreamStateElement,
    CountStateElement,
    LogicalStateElement,
    NextStateElement,
    EveryStateElement,
    InsertIntoStream,
    ReturnStream,
    DeleteStream,
    UpdateStream,
    UpdateOrInsertStream,
    SetAttribute,
    EventOutputRate,
    TimeOutputRate,
    SnapshotOutputRate,
    Partition,
    ValuePartitionType,
    RangePartitionType,
    OnDemandQuery,
)

ATTR_TYPES = {
    "string": AttrType.STRING,
    "int": AttrType.INT,
    "long": AttrType.LONG,
    "float": AttrType.FLOAT,
    "double": AttrType.DOUBLE,
    "bool": AttrType.BOOL,
    "object": AttrType.OBJECT,
}


class SiddhiParserError(Exception):
    def __init__(self, msg: str, tok: Optional[Token] = None):
        if tok is not None:
            msg = f"{msg} (at line {tok.line}:{tok.col}, near {tok.text!r})"
        super().__init__(msg)


# Keywords that may double as identifiers (grammar rule `name : id|keyword`).
# Structural keywords that would make parsing ambiguous are excluded.
SAFE_NAME_KWS = (
    T.KEYWORDS | set(T.TIME_UNITS)
) - {
    "select", "insert", "delete", "update", "return", "from", "define",
    "partition", "begin", "end", "join", "on", "within", "per", "output",
    "group", "having", "order", "limit", "offset", "not", "and", "or", "in",
    "is", "as", "for", "every", "unidirectional", "aggregate", "set", "into",
}


class Parser:
    def __init__(self, tokens: List[Token]):
        self.toks = tokens
        self.pos = 0

    # -- token helpers ------------------------------------------------------

    def peek(self, off: int = 0) -> Token:
        i = min(self.pos + off, len(self.toks) - 1)
        return self.toks[i]

    def at(self, kind: str, text: Optional[str] = None, off: int = 0) -> bool:
        t = self.peek(off)
        return t.kind == kind and (text is None or t.text == text)

    def at_kw(self, *words: str, off: int = 0) -> bool:
        t = self.peek(off)
        return t.kind == T.KW and t.text in words

    def at_sym(self, *syms: str, off: int = 0) -> bool:
        t = self.peek(off)
        return t.kind == T.SYM and t.text in syms

    def next(self) -> Token:
        t = self.toks[self.pos]
        if t.kind != T.EOF:
            self.pos += 1
        return t

    def accept_kw(self, *words: str) -> Optional[Token]:
        if self.at_kw(*words):
            return self.next()
        return None

    def accept_sym(self, *syms: str) -> Optional[Token]:
        if self.at_sym(*syms):
            return self.next()
        return None

    def expect_kw(self, word: str) -> Token:
        if not self.at_kw(word):
            raise SiddhiParserError(f"expected '{word}'", self.peek())
        return self.next()

    def expect_sym(self, sym: str) -> Token:
        if not self.at_sym(sym):
            raise SiddhiParserError(f"expected '{sym}'", self.peek())
        return self.next()

    def expect_name(self, allow_keywords: bool = False) -> str:
        t = self.peek()
        if t.kind == T.ID:
            return self.next().text
        if t.kind == T.KW and (allow_keywords or t.text in SAFE_NAME_KWS):
            return str(self.next().value)  # original-case text
        raise SiddhiParserError("expected identifier", t)

    # -- entry points -------------------------------------------------------

    def parse_app(self) -> SiddhiApp:
        app = SiddhiApp()
        # leading @app:... annotations (plain @annotations belong to the next
        # definition/query and are handled inside those parsers)
        while self.at_sym("@") and self.at_kw("app", off=1) and self.at_sym(":", off=2):
            app.annotations.append(self.parse_app_annotation())
        while not self.at(T.EOF):
            if self.accept_sym(";"):
                continue
            if self.at_sym("@") and self.at_kw("app", off=1) and self.at_sym(":", off=2):
                app.annotations.append(self.parse_app_annotation())
                continue
            annotations = self.parse_annotations()
            if self.at_kw("define"):
                self.parse_definition(app, annotations)
            elif self.at_kw("partition"):
                app.add_partition(self.parse_partition(annotations))
            elif self.at_kw("from"):
                app.add_query(self.parse_query(annotations))
            else:
                raise SiddhiParserError(
                    "expected 'define', 'from', 'partition' or annotation", self.peek()
                )
        if not any(
            (
                app.stream_definitions, app.table_definitions, app.window_definitions,
                app.trigger_definitions, app.function_definitions,
                app.aggregation_definitions, app.execution_elements,
            )
        ):
            raise SiddhiParserError("empty siddhi app: no definitions found")
        return app

    # -- annotations --------------------------------------------------------

    def parse_app_annotation(self) -> Annotation:
        self.expect_sym("@")
        self.expect_kw("app")
        self.expect_sym(":")
        name = self.expect_name(allow_keywords=True)
        ann = Annotation(name="app:" + name)
        if self.accept_sym("("):
            self._parse_annotation_body(ann)
        return ann

    def parse_annotations(self) -> List[Annotation]:
        anns = []
        while self.at_sym("@") and not (self.at_kw("app", off=1) and self.at_sym(":", off=2)):
            anns.append(self.parse_annotation())
        return anns

    def parse_annotation(self) -> Annotation:
        self.expect_sym("@")
        name = self.expect_name(allow_keywords=True)
        ann = Annotation(name=name)
        if self.accept_sym("("):
            self._parse_annotation_body(ann)
        return ann

    def _parse_annotation_body(self, ann: Annotation):
        if self.accept_sym(")"):
            return
        while True:
            if self.at_sym("@"):
                ann.annotations.append(self.parse_annotation())
            else:
                key, value = self._parse_annotation_element()
                ann.elements.append((key, value))
            if self.accept_sym(","):
                continue
            self.expect_sym(")")
            return

    def _parse_annotation_element(self) -> Tuple[Optional[str], str]:
        # (property_name '=')? property_value ; property_name may be dotted
        # (`buffer.size`), dashed, or colon-separated; value is a string
        # literal (we also leniently accept bare numbers/ids/bools).
        start = self.pos
        if self.at(T.ID) or self.at(T.KW):
            key = self.expect_name(allow_keywords=True)
            while self.at_sym(".", "-", ":") and (self.at(T.ID, off=1) or self.at(T.KW, off=1)):
                sep = self.next().text
                key += sep + self.expect_name(allow_keywords=True)
            if self.accept_sym("="):
                return key, self._parse_annotation_value()
            # not a key=value pair; rewind and treat as bare value
            self.pos = start
        return None, self._parse_annotation_value()

    def _parse_annotation_value(self) -> str:
        t = self.peek()
        if t.kind == T.STRING:
            return str(self.next().value)
        if t.kind in (T.INT, T.LONG, T.FLOAT, T.DOUBLE):
            return self.next().text
        if t.kind in (T.ID, T.KW):
            return self.expect_name(allow_keywords=True)
        raise SiddhiParserError("expected annotation value", t)

    # -- definitions --------------------------------------------------------

    def parse_definition(self, app: SiddhiApp, annotations: List[Annotation]):
        self.expect_kw("define")
        if self.accept_kw("stream"):
            app.define_stream(self._finish_stream_def(StreamDefinition, annotations))
        elif self.accept_kw("table"):
            app.define_table(self._finish_stream_def(TableDefinition, annotations))
        elif self.accept_kw("window"):
            app.define_window(self._parse_window_def(annotations))
        elif self.accept_kw("trigger"):
            app.define_trigger(self._parse_trigger_def(annotations))
        elif self.accept_kw("function"):
            app.define_function(self._parse_function_def(annotations))
        elif self.accept_kw("aggregation"):
            app.define_aggregation(self._parse_aggregation_def(annotations))
        else:
            raise SiddhiParserError("unknown definition kind", self.peek())

    def _parse_source_name(self) -> Tuple[str, bool, bool]:
        inner = fault = False
        if self.accept_sym("#"):
            inner = True
        elif self.accept_sym("!"):
            fault = True
        return self.expect_name(), inner, fault

    def _parse_attr_list(self) -> List[Attribute]:
        self.expect_sym("(")
        attrs = []
        while True:
            name = self.expect_name()
            t = self.peek()
            if t.kind != T.KW or t.text not in ATTR_TYPES:
                raise SiddhiParserError("expected attribute type", t)
            self.next()
            attrs.append(Attribute(name, ATTR_TYPES[t.text]))
            if self.accept_sym(","):
                continue
            self.expect_sym(")")
            return attrs

    def _finish_stream_def(self, cls, annotations):
        name = self.expect_name()
        return cls(id=name, attributes=self._parse_attr_list(), annotations=annotations)

    def _parse_window_def(self, annotations) -> WindowDefinition:
        name = self.expect_name()
        attrs = self._parse_attr_list()
        fn = self._parse_function_operation()
        # reference default: ALL events (WindowDefinition.java:40) so
        # queries reading the window see expiries and can retract
        out_type = "all"
        if self.accept_kw("output"):
            out_type = self._parse_output_event_type()
        return WindowDefinition(
            id=name,
            attributes=attrs,
            annotations=annotations,
            window_function=fn,
            output_event_type=out_type,
        )

    def _parse_output_event_type(self) -> str:
        if self.accept_kw("all"):
            self.expect_kw("events")
            return "all"
        if self.accept_kw("expired"):
            self.expect_kw("events")
            return "expired"
        self.accept_kw("current")
        self.expect_kw("events")
        return "current"

    def _parse_trigger_def(self, annotations) -> TriggerDefinition:
        name = self.expect_name()
        self.expect_kw("at")
        if self.accept_kw("every"):
            ms = self._parse_time_value()
            return TriggerDefinition(id=name, annotations=annotations, at_every_ms=ms)
        t = self.peek()
        if t.kind != T.STRING:
            raise SiddhiParserError("expected time value or string after 'at'", t)
        self.next()
        val = str(t.value)
        if val.lower() == "start":
            return TriggerDefinition(id=name, annotations=annotations, at_start=True)
        return TriggerDefinition(id=name, annotations=annotations, at_cron=val)

    def _parse_function_def(self, annotations) -> FunctionDefinition:
        name = self.expect_name()
        self.expect_sym("[")
        lang = self.expect_name(allow_keywords=True)
        self.expect_sym("]")
        self.expect_kw("return")
        t = self.peek()
        if t.kind != T.KW or t.text not in ATTR_TYPES:
            raise SiddhiParserError("expected return type", t)
        self.next()
        rt = ATTR_TYPES[t.text]
        body_tok = self.peek()
        if body_tok.kind != T.SCRIPT:
            raise SiddhiParserError("expected '{ script }' function body", body_tok)
        self.next()
        return FunctionDefinition(
            id=name, annotations=annotations, language=lang, return_type=rt, body=str(body_tok.value)
        )

    DURATIONS = ["sec", "min", "hour", "day", "week", "month", "year"]
    _DUR_CANON = {
        "sec": "seconds", "second": "seconds", "seconds": "seconds",
        "min": "minutes", "minute": "minutes", "minutes": "minutes",
        "hour": "hours", "hours": "hours",
        "day": "days", "days": "days",
        "week": "weeks", "weeks": "weeks",
        "month": "months", "months": "months",
        "year": "years", "years": "years",
    }
    _DUR_ORDER = ["seconds", "minutes", "hours", "days", "weeks", "months", "years"]

    def _parse_duration_name(self) -> str:
        t = self.peek()
        if t.kind == T.KW and t.text in self._DUR_CANON:
            self.next()
            return self._DUR_CANON[t.text]
        raise SiddhiParserError("expected aggregation duration (sec..year)", t)

    def _parse_aggregation_def(self, annotations) -> AggregationDefinition:
        name = self.expect_name()
        self.expect_kw("from")
        stream = self._parse_standard_stream()
        selector = self._parse_query_section(require_select=True)
        self.expect_kw("aggregate")
        aggregate_by = None
        if self.accept_kw("by"):
            var = self._parse_attribute_reference()
            aggregate_by = var.attribute
        self.expect_kw("every")
        first = self._parse_duration_name()
        durations = [first]
        if self.accept_sym("..."):
            last = self._parse_duration_name()
            i0, i1 = self._DUR_ORDER.index(first), self._DUR_ORDER.index(last)
            if i1 < i0:
                raise SiddhiParserError(f"invalid duration range {first}...{last}")
            durations = self._DUR_ORDER[i0 : i1 + 1]
        else:
            while self.accept_sym(","):
                durations.append(self._parse_duration_name())
        return AggregationDefinition(
            id=name,
            annotations=annotations,
            input_stream=stream,
            selector=selector,
            aggregate_by=aggregate_by,
            durations=durations,
        )

    # -- partition ----------------------------------------------------------

    def parse_partition(self, annotations) -> Partition:
        self.expect_kw("partition")
        self.expect_kw("with")
        self.expect_sym("(")
        ptypes = []
        while True:
            ptypes.append(self._parse_partition_with_stream())
            if self.accept_sym(","):
                continue
            self.expect_sym(")")
            break
        self.expect_kw("begin")
        queries = []
        while True:
            if self.accept_sym(";"):
                continue
            if self.accept_kw("end"):
                break
            anns = self.parse_annotations()
            queries.append(self.parse_query(anns))
        return Partition(partition_types=ptypes, queries=queries, annotations=annotations)

    def _parse_partition_with_stream(self):
        # `expr of Stream` (value) or `expr as 'label' or ... of Stream` (range)
        expr = self.parse_expression()
        if self.at_kw("as"):
            ranges = []
            self.expect_kw("as")
            label = self._expect_string()
            ranges.append((expr, label))
            while self.accept_kw("or"):
                cond = self.parse_expression()
                self.expect_kw("as")
                ranges.append((cond, self._expect_string()))
            self.expect_kw("of")
            stream = self.expect_name()
            return RangePartitionType(stream_id=stream, ranges=ranges)
        self.expect_kw("of")
        stream = self.expect_name()
        return ValuePartitionType(stream_id=stream, expression=expr)

    def _expect_string(self) -> str:
        t = self.peek()
        if t.kind != T.STRING:
            raise SiddhiParserError("expected string literal", t)
        self.next()
        return str(t.value)

    # -- query --------------------------------------------------------------

    def parse_query(self, annotations) -> Query:
        self.expect_kw("from")
        input_stream = self._parse_query_input()
        selector = self._parse_query_section(require_select=False)
        output_rate = self._parse_output_rate()
        output_stream = self._parse_query_output()
        return Query(
            input_stream=input_stream,
            selector=selector,
            output_stream=output_stream,
            output_rate=output_rate,
            annotations=annotations,
        )

    # ---- input classification --------------------------------------------

    _QUERY_BOUNDARY = {"select", "insert", "delete", "update", "return", "output", "group", "having", "order", "limit", "offset"}

    def _classify_input(self) -> str:
        """Look ahead from current position to classify the from-clause:
        'pattern' | 'sequence' | 'join' | 'standard'."""
        depth = 0
        i = self.pos
        toks = self.toks
        has_arrow = has_comma = has_join = has_logical = False
        has_every = has_not = has_binding = has_collect = False
        while i < len(toks):
            t = toks[i]
            if t.kind == T.SYM and t.text in "([":
                depth += 1
            elif t.kind == T.SYM and t.text in ")]":
                depth -= 1
                if depth < 0:
                    break
            elif depth > 0:
                # markers that cannot occur inside expression parentheses
                # still classify a parenthesized whole pattern, e.g.
                # `from (every e1=A -> e2=B) within 1 sec` (reference
                # WithinPatternTestCase.testQuery2's shape)
                if t.kind == T.SYM and t.text == "->":
                    has_arrow = True
                elif t.kind == T.SYM and t.text == "=":
                    has_binding = True
                elif t.kind == T.KW and t.text == "every":
                    has_every = True
            elif depth == 0:
                if t.kind == T.SYM and t.text == "->":
                    has_arrow = True
                elif t.kind == T.SYM and t.text == ",":
                    has_comma = True
                elif t.kind == T.SYM and t.text == "=":
                    # event-ref binding `e1=Stream` ('==' lexes as one token)
                    has_binding = True
                elif t.kind == T.SYM and t.text == "<":
                    # count collection `<n>`, `<n:m>`, `<n:>`, `<:m>` — only
                    # INT/':' tokens up to a closing '>' (distinguishes from a
                    # comparison like `on A.x < 5` in a join on-condition)
                    k = i + 1
                    inner_ok = False
                    while k < len(toks) and k <= i + 4:
                        tk = toks[k]
                        if tk.kind == T.SYM and tk.text == ">":
                            has_collect = has_collect or inner_ok
                            break
                        if tk.kind == T.INT or (tk.kind == T.SYM and tk.text == ":"):
                            inner_ok = True
                            k += 1
                            continue
                        break
                elif t.kind == T.SYM and t.text == ";":
                    break
                elif t.kind == T.KW:
                    prev = toks[i - 1] if i > 0 else None
                    if prev is not None and prev.kind == T.SYM and prev.text in "#!.:@":
                        pass  # name position (`#Inner`, `.length`, `@info`)
                    elif t.text in ("join", "inner", "outer", "left", "right", "full", "unidirectional"):
                        has_join = True
                    elif t.text in ("and", "or"):
                        has_logical = True
                    elif t.text == "every":
                        has_every = True
                    elif t.text == "not":
                        has_not = True
                    elif t.text in self._QUERY_BOUNDARY:
                        break
            i += 1
        # Markers that can only occur in pattern/sequence inputs take priority;
        # 'not'/'and'/'or' also occur inside a join's on-condition, so a join
        # keyword wins over those.
        if has_arrow or has_every or has_binding or has_collect:
            return "sequence" if (has_comma and not has_arrow) else "pattern"
        if has_join:
            return "join"
        if has_not or has_logical:
            return "pattern"
        if has_comma:
            return "sequence"
        return "standard"

    def _parse_query_input(self):
        kind = self._classify_input()
        if kind == "standard":
            return self._parse_standard_stream()
        if kind == "join":
            return self._parse_join_stream()
        if kind == "pattern":
            return self._parse_pattern_stream()
        return self._parse_sequence_stream()

    # ---- standard & join streams ------------------------------------------

    def _parse_stream_handlers(self) -> List:
        """filters `[expr]`, stream functions `#ns:fn(..)`, window `#window.fn(..)`."""
        handlers = []
        while True:
            if self.at_sym("["):
                self.next()
                expr = self.parse_expression()
                self.expect_sym("]")
                handlers.append(Filter(expr))
                continue
            if self.at_sym("#"):
                if self.at_kw("window", off=1) and self.at_sym(".", off=2):
                    self.next()  # '#'
                    self.next()  # 'window'
                    self.next()  # '.'
                    fn = self._parse_function_operation()
                    handlers.append(WindowHandler(fn.namespace, fn.name, fn.args))
                    continue
                # '#ns:fn(...)' or '#fn(...)'
                if self.at(T.ID, off=1) or self.at(T.KW, off=1):
                    self.next()  # '#'
                    fn = self._parse_function_operation()
                    handlers.append(StreamFunction(fn.namespace, fn.name, fn.args))
                    continue
            break
        return handlers

    def _parse_standard_stream(self) -> SingleInputStream:
        name, inner, fault = self._parse_source_name()
        handlers = self._parse_stream_handlers()
        return SingleInputStream(stream_id=name, is_inner=inner, is_fault=fault, handlers=handlers)

    def _parse_join_source(self) -> SingleInputStream:
        s = self._parse_standard_stream()
        if self.accept_kw("as"):
            s.alias = self.expect_name()
        return s

    def _parse_join_stream(self) -> JoinInputStream:
        left = self._parse_join_source()
        trigger = None
        if self.accept_kw("unidirectional"):
            trigger = "left"
        join_type = self._parse_join_kind()
        right = self._parse_join_source()
        if trigger is None and self.accept_kw("unidirectional"):
            trigger = "right"
        on_cond = None
        if self.accept_kw("on"):
            on_cond = self.parse_expression()
        within = per = None
        if self.accept_kw("within"):
            within = self.parse_expression()
            if self.accept_sym(","):
                # within start, end — keep as tuple via per slot below
                end = self.parse_expression()
                within = (within, end)
        if self.accept_kw("per"):
            per = self.parse_expression()
        return JoinInputStream(
            left=left, join_type=join_type, right=right, on_condition=on_cond,
            trigger=trigger, within=within, per=per,
        )

    def _parse_join_kind(self) -> str:
        if self.accept_kw("left"):
            self.expect_kw("outer")
            self.expect_kw("join")
            return JoinInputStream.LEFT_OUTER
        if self.accept_kw("right"):
            self.expect_kw("outer")
            self.expect_kw("join")
            return JoinInputStream.RIGHT_OUTER
        if self.accept_kw("full"):
            self.expect_kw("outer")
            self.expect_kw("join")
            return JoinInputStream.FULL_OUTER
        if self.accept_kw("outer"):
            self.expect_kw("join")
            return JoinInputStream.FULL_OUTER
        if self.accept_kw("inner"):
            self.expect_kw("join")
            return JoinInputStream.INNER_JOIN
        self.expect_kw("join")
        return JoinInputStream.JOIN

    # ---- patterns & sequences ---------------------------------------------

    def _parse_pattern_stream(self) -> StateInputStream:
        state = self._parse_pattern_chain()
        within = None
        if self.accept_kw("within"):
            within = self._parse_time_value()
        return StateInputStream(type=StateInputStream.PATTERN, state=state, within_ms=within)

    def _parse_pattern_chain(self):
        """Chain of pattern elements separated by '->'."""
        elem = self._parse_pattern_chain_element()
        while self.accept_sym("->"):
            nxt = self._parse_pattern_chain_element()
            elem = NextStateElement(element=elem, next=nxt)
        return elem

    def _parse_pattern_chain_element(self):
        if self.accept_kw("every"):
            if self.accept_sym("("):
                inner = self._parse_pattern_chain()
                self.expect_sym(")")
                return EveryStateElement(element=inner)
            return EveryStateElement(element=self._parse_pattern_source())
        if self.at_sym("("):
            self.next()
            inner = self._parse_pattern_chain()
            self.expect_sym(")")
            return inner
        return self._parse_pattern_source()

    def _parse_pattern_source(self):
        """logical / collection / absent / standard stateful source."""
        first = self._parse_stateful_source_atom()
        if self.at_kw("and", "or"):
            op = self.next().text
            second = self._parse_stateful_source_atom()
            return LogicalStateElement(element1=first, operator=op, element2=second)
        return first

    def _parse_stateful_source_atom(self):
        if self.accept_kw("not"):
            stream = self._parse_basic_source()
            wait = None
            if self.accept_kw("for"):
                wait = self._parse_time_value()
            return AbsentStreamStateElement(stream=stream, waiting_time_ms=wait)
        sse = self._parse_standard_stateful_source()
        # pattern count collection <min:max>
        if self.at_sym("<"):
            save = self.pos
            self.next()
            ok, mn, mx = self._try_parse_collect()
            if ok:
                return CountStateElement(stream_state=sse, min_count=mn, max_count=mx)
            self.pos = save
        return sse

    def _try_parse_collect(self):
        ANY = CountStateElement.ANY
        mn = mx = None
        if self.at(T.INT):
            mn = int(self.next().value)
            if self.accept_sym(":"):
                if self.at(T.INT):
                    mx = int(self.next().value)
                else:
                    mx = ANY
            else:
                mx = mn
        elif self.at_sym(":"):
            self.next()
            if not self.at(T.INT):
                return False, 0, 0
            mn = 0
            mx = int(self.next().value)
        else:
            return False, 0, 0
        if not self.at_sym(">"):
            return False, 0, 0
        self.next()
        return True, mn, mx

    def _parse_standard_stateful_source(self) -> StreamStateElement:
        event_ref = None
        if (self.at(T.ID) and self.at_sym("=", off=1)) and not self.at_sym("==", off=1):
            event_ref = self.next().text
            self.next()  # '='
        stream = self._parse_basic_source()
        return StreamStateElement(stream=stream, event_ref=event_ref)

    def _parse_basic_source(self) -> SingleInputStream:
        name, inner, fault = self._parse_source_name()
        handlers = []
        while True:
            if self.at_sym("["):
                self.next()
                expr = self.parse_expression()
                self.expect_sym("]")
                handlers.append(Filter(expr))
                continue
            if self.at_sym("#") and (self.at(T.ID, off=1) or self.at(T.KW, off=1)) and not (
                self.at_kw("window", off=1) and self.at_sym(".", off=2)
            ):
                self.next()
                fn = self._parse_function_operation()
                handlers.append(StreamFunction(fn.namespace, fn.name, fn.args))
                continue
            break
        return SingleInputStream(stream_id=name, is_inner=inner, is_fault=fault, handlers=handlers)

    def _parse_sequence_stream(self) -> StateInputStream:
        every_first = bool(self.accept_kw("every"))
        first = self._parse_sequence_source()
        if every_first:
            first = EveryStateElement(element=first)
        elems = [first]
        while self.accept_sym(","):
            elems.append(self._parse_sequence_source())
        # right-nested Next chain; associativity does not matter for lowering
        state = elems[-1]
        for e in reversed(elems[:-1]):
            state = NextStateElement(element=e, next=state)
        within = None
        if self.accept_kw("within"):
            within = self._parse_time_value()
        return StateInputStream(type=StateInputStream.SEQUENCE, state=state, within_ms=within)

    def _parse_sequence_source(self):
        if self.at_sym("("):
            self.next()
            inner = self._parse_sequence_chain_parenthesized()
            self.expect_sym(")")
            return inner
        first = self._parse_sequence_atom()
        if self.at_kw("and", "or"):
            op = self.next().text
            second = self._parse_sequence_atom()
            return LogicalStateElement(element1=first, operator=op, element2=second)
        return first

    def _parse_sequence_chain_parenthesized(self):
        elems = [self._parse_sequence_source()]
        while self.accept_sym(","):
            elems.append(self._parse_sequence_source())
        state = elems[-1]
        for e in reversed(elems[:-1]):
            state = NextStateElement(element=e, next=state)
        return state

    def _parse_sequence_atom(self):
        if self.accept_kw("not"):
            stream = self._parse_basic_source()
            wait = None
            if self.accept_kw("for"):
                wait = self._parse_time_value()
            return AbsentStreamStateElement(stream=stream, waiting_time_ms=wait)
        sse = self._parse_standard_stateful_source()
        ANY = CountStateElement.ANY
        if self.at_sym("*"):
            self.next()
            return CountStateElement(stream_state=sse, min_count=0, max_count=ANY)
        if self.at_sym("+"):
            self.next()
            return CountStateElement(stream_state=sse, min_count=1, max_count=ANY)
        if self.at_sym("?"):
            self.next()
            return CountStateElement(stream_state=sse, min_count=0, max_count=1)
        if self.at_sym("<"):
            save = self.pos
            self.next()
            ok, mn, mx = self._try_parse_collect()
            if ok:
                return CountStateElement(stream_state=sse, min_count=mn, max_count=mx)
            self.pos = save
        return sse

    # ---- selection section -------------------------------------------------

    def _parse_query_section(self, require_select: bool) -> Selector:
        sel = Selector()
        if self.accept_kw("select"):
            if self.accept_sym("*"):
                sel.selection = None
            else:
                items = [self._parse_output_attribute()]
                while self.accept_sym(","):
                    items.append(self._parse_output_attribute())
                sel.selection = items
        elif require_select:
            raise SiddhiParserError("expected 'select'", self.peek())
        else:
            # no select clause == select *
            sel.selection = None
        if self.accept_kw("group"):
            self.expect_kw("by")
            sel.group_by = [self._parse_attribute_reference()]
            while self.accept_sym(","):
                sel.group_by.append(self._parse_attribute_reference())
        if self.accept_kw("having"):
            sel.having = self.parse_expression()
        if self.accept_kw("order"):
            self.expect_kw("by")
            sel.order_by = [self._parse_order_by_ref()]
            while self.accept_sym(","):
                sel.order_by.append(self._parse_order_by_ref())
        if self.accept_kw("limit"):
            sel.limit = self.parse_expression()
        if self.accept_kw("offset"):
            sel.offset = self.parse_expression()
        return sel

    def _parse_order_by_ref(self) -> OrderByAttribute:
        var = self._parse_attribute_reference()
        asc = True
        if self.accept_kw("desc"):
            asc = False
        elif self.accept_kw("asc"):
            asc = True
        return OrderByAttribute(variable=var, ascending=asc)

    def _parse_output_attribute(self) -> OutputAttribute:
        expr = self.parse_expression()
        rename = None
        if self.accept_kw("as"):
            rename = self.expect_name()
        return OutputAttribute(expression=expr, rename=rename)

    # ---- output rate -------------------------------------------------------

    def _parse_output_rate(self):
        if not self.at_kw("output"):
            return None
        # distinguish `output every ...` / `output snapshot every` / `output
        # first every` from query outputs — 'output' only begins a rate here.
        self.next()
        if self.accept_kw("snapshot"):
            self.expect_kw("every")
            return SnapshotOutputRate(value_ms=self._parse_time_value())
        rtype = "all"
        if self.accept_kw("all"):
            rtype = "all"
        elif self.accept_kw("first"):
            rtype = "first"
        elif self.accept_kw("last"):
            rtype = "last"
        self.expect_kw("every")
        if self.at(T.INT) and self.at_kw("events", off=1):
            n = int(self.next().value)
            self.next()
            return EventOutputRate(events=n, type=rtype)
        return TimeOutputRate(value_ms=self._parse_time_value(), type=rtype)

    # ---- query output ------------------------------------------------------

    def _parse_query_output(self):
        if self.accept_kw("insert"):
            event_type = "current"
            if self.at_kw("all", "expired", "current"):
                event_type = self._parse_output_event_type()
            self.expect_kw("into")
            name, inner, fault = self._parse_source_name()
            return InsertIntoStream(target=name, event_type=event_type, is_inner=inner, is_fault=fault)
        if self.accept_kw("delete"):
            name, _, _ = self._parse_source_name()
            event_type = "current"
            if self.accept_kw("for"):
                event_type = self._parse_output_event_type()
            on = None
            if self.accept_kw("on"):
                on = self.parse_expression()
            return DeleteStream(target=name, event_type=event_type, on_condition=on)
        if self.accept_kw("update"):
            if self.accept_kw("or"):
                self.expect_kw("insert")
                self.expect_kw("into")
                name, _, _ = self._parse_source_name()
                event_type = "current"
                if self.accept_kw("for"):
                    event_type = self._parse_output_event_type()
                set_clause = self._parse_set_clause()
                self.expect_kw("on")
                on = self.parse_expression()
                return UpdateOrInsertStream(
                    target=name, event_type=event_type, set_clause=set_clause, on_condition=on
                )
            name, _, _ = self._parse_source_name()
            event_type = "current"
            if self.accept_kw("for"):
                event_type = self._parse_output_event_type()
            set_clause = self._parse_set_clause()
            self.expect_kw("on")
            on = self.parse_expression()
            return UpdateStream(target=name, event_type=event_type, set_clause=set_clause, on_condition=on)
        if self.accept_kw("return"):
            event_type = "current"
            if self.at_kw("all", "expired", "current"):
                event_type = self._parse_output_event_type()
            return ReturnStream(event_type=event_type)
        raise SiddhiParserError(
            "expected 'insert'/'delete'/'update'/'return' query output", self.peek()
        )

    def _parse_set_clause(self):
        if not self.accept_kw("set"):
            return None
        items = []
        while True:
            var = self._parse_attribute_reference()
            self.expect_sym("=")
            expr = self.parse_expression()
            items.append(SetAttribute(variable=var, expression=expr))
            if self.accept_sym(","):
                continue
            return items

    # -- on-demand (store) queries ------------------------------------------

    def parse_on_demand_query(self) -> OnDemandQuery:
        if self.at_kw("from"):
            self.next()
            store = self.expect_name()
            alias = None
            if self.accept_kw("as"):
                alias = self.expect_name()
            on = None
            if self.accept_kw("on"):
                on = self.parse_expression()
            within = per = None
            if self.accept_kw("within"):
                start = self.parse_expression()
                end = None
                if self.accept_sym(","):
                    end = self.parse_expression()
                within = (start, end)
            if self.accept_kw("per"):
                per = self.parse_expression()
            selector = self._parse_query_section(require_select=False)
            out = None
            qtype = "find"
            if self.at_kw("delete"):
                out = self._parse_query_output()
                qtype = "delete"
            elif self.at_kw("update"):
                out = self._parse_query_output()
                qtype = "update_or_insert" if isinstance(out, UpdateOrInsertStream) else "update"
            return OnDemandQuery(
                type=qtype, input_store=store, input_alias=alias, on_condition=on,
                within=within, per=per, selector=selector, output_stream=out,
            )
        # `select ... insert into T` / `select ... update ...` forms
        selector = self._parse_query_section(require_select=True)
        out = self._parse_query_output()
        if isinstance(out, InsertIntoStream):
            qtype = "insert"
        elif isinstance(out, DeleteStream):
            qtype = "delete"
        elif isinstance(out, UpdateOrInsertStream):
            qtype = "update_or_insert"
        else:
            qtype = "update"
        return OnDemandQuery(type=qtype, selector=selector, output_stream=out)

    # -- expressions ---------------------------------------------------------

    def parse_expression(self) -> Expression:
        return self._parse_or()

    def _parse_or(self) -> Expression:
        left = self._parse_and()
        while self.at_kw("or"):
            self.next()
            left = OrOp(left, self._parse_and())
        return left

    def _parse_and(self) -> Expression:
        left = self._parse_in()
        while self.at_kw("and"):
            self.next()
            left = AndOp(left, self._parse_in())
        return left

    def _parse_in(self) -> Expression:
        left = self._parse_equality()
        while self.at_kw("in"):
            self.next()
            left = InOp(left, self.expect_name())
        return left

    def _parse_equality(self) -> Expression:
        left = self._parse_relational()
        while self.at_sym("==", "!="):
            op = self.next().text
            left = CompareOp(op, left, self._parse_relational())
        return left

    def _parse_relational(self) -> Expression:
        left = self._parse_additive()
        while self.at_sym("<", "<=", ">", ">="):
            op = self.next().text
            left = CompareOp(op, left, self._parse_additive())
        return left

    def _parse_additive(self) -> Expression:
        left = self._parse_multiplicative()
        while self.at_sym("+", "-"):
            op = self.next().text
            left = ArithmeticOp(op, left, self._parse_multiplicative())
        return left

    def _parse_multiplicative(self) -> Expression:
        left = self._parse_unary()
        while self.at_sym("*", "/", "%"):
            op = self.next().text
            left = ArithmeticOp(op, left, self._parse_unary())
        return left

    def _parse_unary(self) -> Expression:
        if self.at_kw("not"):
            self.next()
            return NotOp(self._parse_unary())
        if self.at_sym("-", "+"):
            sign = self.next().text
            expr = self._parse_unary()
            if sign == "-":
                if isinstance(expr, Constant) and expr.type.is_numeric:
                    return Constant(-expr.value, expr.type)
                return ArithmeticOp("-", Constant(0, AttrType.INT), expr)
            return expr
        return self._parse_postfix()

    def _parse_postfix(self) -> Expression:
        expr = self._parse_primary()
        # null check: `<primary> is null`
        if self.at_kw("is") and self.at_kw("null", off=1):
            self.next()
            self.next()
            return IsNull(expr)
        return expr

    def _parse_primary(self) -> Expression:
        t = self.peek()
        if self.at_sym("("):
            self.next()
            expr = self.parse_expression()
            self.expect_sym(")")
            return expr
        # literals
        if t.kind == T.STRING:
            self.next()
            return Constant(str(t.value), AttrType.STRING)
        if t.kind in (T.INT, T.LONG, T.FLOAT, T.DOUBLE):
            return self._parse_numeric_or_time()
        if t.kind == T.KW:
            if t.text == "true":
                self.next()
                return Constant(True, AttrType.BOOL)
            if t.text == "false":
                self.next()
                return Constant(False, AttrType.BOOL)
            if t.text == "null":
                self.next()
                return Constant(None, AttrType.OBJECT)
        # attribute reference or function call (possibly '#'/'!' prefixed)
        if self.at_sym("#", "!") or t.kind == T.ID or t.kind == T.KW:
            return self._parse_ref_or_call()
        raise SiddhiParserError("expected expression", t)

    def _parse_numeric_or_time(self) -> Expression:
        t = self.peek()
        # time constant: INT followed by a time unit keyword
        if t.kind == T.INT and self.peek(1).kind == T.KW and self.peek(1).text in T.TIME_UNITS:
            return TimeConstant(self._parse_time_value())
        self.next()
        if t.kind == T.INT:
            v = int(t.value)
            # un-suffixed literals beyond int32 widen to LONG (Java would
            # reject them outright; widening keeps 64-bit ids writable
            # without the 'L' suffix)
            if -(2**31) <= v < 2**31:
                return Constant(v, AttrType.INT)
            return Constant(v, AttrType.LONG)
        if t.kind == T.LONG:
            return Constant(int(t.value), AttrType.LONG)
        if t.kind == T.FLOAT:
            return Constant(float(t.value), AttrType.FLOAT)
        return Constant(float(t.value), AttrType.DOUBLE)

    def _parse_time_value(self) -> int:
        """`1 hour 30 min` -> milliseconds."""
        total = 0
        matched = False
        while self.at(T.INT) and self.peek(1).kind == T.KW and self.peek(1).text in T.TIME_UNITS:
            n = int(self.next().value)
            unit = self.next().text
            total += n * T.TIME_UNITS[unit]
            matched = True
        if not matched:
            raise SiddhiParserError("expected time value", self.peek())
        return total

    def _parse_function_operation(self) -> FunctionCall:
        ns = None
        name = self.expect_name(allow_keywords=True)
        if self.accept_sym(":"):
            ns = name
            name = self.expect_name(allow_keywords=True)
        self.expect_sym("(")
        args: List[Expression] = []
        star = False
        if self.accept_sym(")"):
            return FunctionCall(namespace=ns, name=name, args=tuple(args))
        if self.at_sym("*") and self.at_sym(")", off=1):
            self.next()
            star = True
        else:
            args.append(self.parse_expression())
            while self.accept_sym(","):
                args.append(self.parse_expression())
        self.expect_sym(")")
        return FunctionCall(namespace=ns, name=name, args=tuple(args), star=star)

    def _parse_ref_or_call(self) -> Expression:
        inner = fault = False
        if self.accept_sym("#"):
            inner = True
        elif self.accept_sym("!"):
            fault = True
        t = self.peek()
        if t.kind not in (T.ID, T.KW):
            raise SiddhiParserError("expected identifier", t)
        # function call? name '(' or ns ':' name '('
        if not inner and not fault:
            if self.at_sym("(", off=1):
                return self._parse_function_operation()
            if self.at_sym(":", off=1) and (self.at(T.ID, off=2) or self.at(T.KW, off=2)) and self.at_sym("(", off=3):
                return self._parse_function_operation()
        return self._parse_attribute_reference(inner=inner, fault=fault)

    def _parse_attribute_reference(self, inner: bool = False, fault: bool = False) -> Variable:
        """`attr` | `Stream.attr` | `e[1].attr` | `e[last].attr` |
        `e[last-1].attr` | `#inner.attr` | `name1#name2.attr`."""
        if not inner and not fault:
            if self.accept_sym("#"):
                inner = True
            elif self.accept_sym("!"):
                fault = True
        name1 = self.expect_name(allow_keywords=False)
        idx: Optional[int] = None
        fn_id: Optional[str] = None
        if self.at_sym("["):
            idx = self._parse_attribute_index()
        if self.accept_sym("#"):
            fn_id = self.expect_name()
            if self.at_sym("["):
                self._parse_attribute_index()  # second index (rare) — ignored
        if self.accept_sym("."):
            attr = self.expect_name()
            return Variable(
                attribute=attr, stream_id=name1, stream_index=idx,
                is_inner=inner, is_fault=fault, function_id=fn_id,
            )
        if idx is not None or fn_id is not None:
            # `e1[1] is null` — a stream-slot null check, not an attribute ref
            # (reference grammar null_check over stream_reference)
            if self.at_kw("is") and self.at_kw("null", off=1):
                from siddhi_tpu.query_api import IsNullStream

                self.next()
                self.next()
                return IsNullStream(
                    stream_id=name1, stream_index=idx, is_inner=inner, is_fault=fault
                )
            raise SiddhiParserError("expected '.attribute' after indexed reference", self.peek())
        return Variable(attribute=name1, is_inner=inner, is_fault=fault)

    def _parse_attribute_index(self) -> int:
        self.expect_sym("[")
        if self.accept_kw("last"):
            k = 0
            if self.accept_sym("-"):
                t = self.peek()
                if t.kind != T.INT:
                    raise SiddhiParserError("expected integer after 'last -'", t)
                self.next()
                k = int(t.value)
            self.expect_sym("]")
            return -(k + 1)  # last == -1, last-1 == -2
        t = self.peek()
        if t.kind != T.INT:
            raise SiddhiParserError("expected index", t)
        self.next()
        self.expect_sym("]")
        return int(t.value)


def parse_time_string(s: str) -> int:
    """Annotation time value ('10 sec', '1 hour 30 min') -> milliseconds.
    The whole string must be consumed — partial matches ('1.5 min') are
    errors, not silent misparses."""
    import re

    from siddhi_tpu.compiler.tokenizer import TIME_UNITS

    pattern = re.compile(r"\s*(\d+)\s*([a-zA-Z]+)")
    total = 0
    pos = 0
    matched = False
    while m := pattern.match(s, pos):
        ms = TIME_UNITS.get(m.group(2).lower())
        if ms is None:
            raise SiddhiParserError(f"unknown time unit '{m.group(2)}' in '{s}'")
        total += int(m.group(1)) * ms
        pos = m.end()
        matched = True
    if not matched or s[pos:].strip():
        raise SiddhiParserError(f"expected a time value, got '{s}'")
    return total
