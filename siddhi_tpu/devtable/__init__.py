"""Device-resident tables: columnar HBM storage, jitted scatter
mutations, snapshot-consistent stream-table joins.

Enabled per app by ``@app:devtables(capacity='N')`` under
``@app:execution('tpu')``.  Eligible tables build as ``DeviceTable``
(columnar ``[C]`` device arrays + validity lane + host slot map);
ineligible ones fall back to ``InMemoryTable`` — logged and counted,
never an error.
"""

from .join import DevTableJoinReceiver, DevTableJoinRuntime
from .planner import plan_devtable_mutation, try_plan_devtable_join
from .storage import DeviceTable

__all__ = [
    "DeviceTable",
    "DevTableJoinReceiver",
    "DevTableJoinRuntime",
    "plan_devtable_mutation",
    "try_plan_devtable_join",
]
