"""Stream-table joins against device-resident tables.

``DevTableJoinRuntime`` replaces the host ``JoinRuntime`` +
``JoinStreamReceiver`` pair for eligible queries (inner join, one
``DeviceTable`` side, windowless/filterless stream side, a primary-key
equality conjunct): the arriving micro-batch ships its key lane and the
condition-referenced attribute lanes to the device once, a jitted
``[B, C]`` masked probe gathers the matched table row per event and
evaluates the FULL join condition on device lanes, and matched pairs
ride the existing async emit pipeline — zero host materialization
between ingest and emit.

Snapshot consistency: the probe closes over the table's CURRENT column
references at dispatch (``DeviceTable.device_state`` under the table
lock).  JAX arrays are immutable, so scatter mutations landing while
the probe is in flight produce NEW arrays and never tear the probed
view — the probe reads exactly the revision-in-progress it dispatched
against, the device analog of the host path's lock-ordered probe.

Because the eligibility gate requires a primary-key equality conjunct,
at most ONE table row matches each event, so output shapes are fixed
``[B]`` lanes and matched pairs emit in arrival order — bit-identical
to the host ``JoinRuntime._join``'s row-major ``np.nonzero`` order.

The runtime mirrors ``DeviceQueryRuntime``'s pipeline discipline:
``IngestStage`` staging for the count gate, ``EmitQueue`` for deferred
materialization, per-batch fault isolation through ``on_fault``, cycle
tokens and ``table.probe`` spans for observability.  A demoted table
(or a null-carrying batch) falls back per batch to the exact host
cross-product semantics — after a pipeline drain, so emit order holds.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core import event as ev
from siddhi_tpu.core.emit_queue import EmitQueue, EmitStats, PendingEmit, fetch_coalesced
from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.ingest_stage import IngestStage, IngestStats, staged_put
from siddhi_tpu.planner.expr import N_KEY, TS_KEY

log = logging.getLogger("siddhi_tpu")


def _pow2(n: int, floor: int = 16) -> int:
    return max(1 << (max(n, 1) - 1).bit_length(), floor)


class DevTableJoinRuntime:
    """One stream-table join lowered onto the device batch cycle."""

    MAX_CHUNK = 4096  # [B, C] probe work bound; larger batches chunk

    def __init__(self, name: str, stream_side, table_side, stream_is_left: bool,
                 condition, key_expr, cond_stream_lanes: Dict[str, Tuple[str, np.dtype]],
                 out_stream_id: str, emit, emit_depth=1, ingest_depth=1,
                 clock=None, faults=None, tracer=None):
        import jax

        self.name = name
        self.stream_side = stream_side
        self.table_side = table_side
        self.table = table_side.table
        self.stream_is_left = stream_is_left
        self.condition = condition
        self.key_expr = key_expr
        # condition-referenced stream attrs riding device lanes:
        # env key -> (attribute name, lane dtype)
        self._cond_lanes = cond_stream_lanes
        self.out_stream_id = out_stream_id
        self.emit = emit
        self.clock = clock
        self.faults = faults
        self.tracer = tracer
        self.engine_kind = "devtable_join"
        self.step_invocations = 0
        self.probe_invocations = 0
        self.host_fallback_batches = 0
        self.emit_stats = EmitStats()
        self.emit_queue = EmitQueue(depth=emit_depth, stats=self.emit_stats,
                                    faults=faults, on_fault=self._on_fault)
        self.ingest_stats = IngestStats()
        self.ingest_stage = IngestStage(depth=ingest_depth, stats=self.ingest_stats,
                                        faults=faults, on_fault=self._on_fault)
        left, right = ((stream_side, table_side) if stream_is_left
                       else (table_side, stream_side))
        self._out_names = [
            left.qualified_key(a.name) for a in left.definition.attributes
        ] + [right.qualified_key(a.name) for a in right.definition.attributes]
        self._tbl_names = [a.name for a in self.table.definition.attributes]
        tbl_env = {table_side.qualified_key(a.name): a.name
                   for a in self.table.definition.attributes}
        cond_fn = condition.fn

        def probe(keys, ev_mask, ev_lanes, pk_col, tcols, valid):
            import jax.numpy as jnp

            oneh = (keys[:, None] == pk_col[None, :]) & valid[None, :]
            matched = oneh.any(axis=1) & ev_mask
            slot = jnp.argmax(oneh, axis=1)
            gathered = {nm: c[slot] for nm, c in tcols.items()}
            env = dict(ev_lanes)
            for qk, nm in tbl_env.items():
                env[qk] = gathered[nm]
            env[N_KEY] = keys.shape[0]
            ok = jnp.broadcast_to(
                jnp.asarray(cond_fn(env)).astype(bool), matched.shape)
            mask = matched & ok
            return mask, gathered, jnp.sum(mask.astype(jnp.int32))

        self._probe = jax.jit(probe)

    def _on_fault(self, e):
        if self.tracer is not None:
            self.tracer.dump(f"onerror-isolation:{type(e).__name__}")
        if self.faults is not None:
            self.faults.notify(e)

    # -- batch entry ------------------------------------------------------

    def process_stream_batch(self, batch: EventBatch):
        cur = batch.only(ev.CURRENT)
        n = len(cur)
        if n == 0:
            return
        now = self.clock() if self.clock is not None else 0
        host_reason = self._host_only_reason(cur)
        if host_reason is not None:
            # pipeline barrier first so the synchronous host emit cannot
            # overtake queued device emits from earlier batches
            self.ingest_stage.flush()
            self.emit_queue.drain()
            self.host_fallback_batches += 1
            self._host_join(cur, now)
            return
        tok = (self.tracer.begin_cycle(self.engine_kind, n)
               if self.tracer is not None else None)
        keys = self._event_keys(cur)
        for lo in range(0, n, self.MAX_CHUNK):
            hi = min(n, lo + self.MAX_CHUNK)
            self._dispatch_chunk(cur, keys, lo, hi, now, tok)

    def _host_only_reason(self, cur: EventBatch) -> Optional[str]:
        if self.table.demoted:
            return "table demoted to host"
        for _, (attr, _dt) in self._cond_lanes.items():
            if cur.columns[attr].dtype.kind == "O":
                return f"nulls in condition attribute '{attr}'"
        return None

    def _event_keys(self, cur: EventBatch) -> np.ndarray:
        env = {self.stream_side.qualified_key(a.name): cur.columns[a.name]
               for a in self.stream_side.definition.attributes}
        env[TS_KEY] = cur.timestamps
        env[N_KEY] = len(cur)
        return np.broadcast_to(self.key_expr.fn(env), (len(cur),))

    def _dispatch_chunk(self, cur, keys, lo, hi, now, tok):
        cn = hi - lo
        B = _pow2(cn)
        klane = np.zeros(B, dtype=np.int32)
        klane[:cn] = keys[lo:hi].astype(np.int32, copy=False)
        mlane = np.zeros(B, dtype=bool)
        mlane[:cn] = True
        lanes = {}
        for ek, (attr, dt) in self._cond_lanes.items():
            col = np.zeros(B, dtype=dt)
            col[:cn] = cur.columns[attr][lo:hi].astype(dt, copy=False)
            lanes[ek] = col
        # snapshot-consistent: CURRENT immutable refs, under the table lock
        tcols, tvalid = self.table.device_state()
        t0 = time.perf_counter()
        k_d, m_d, l_d = staged_put((klane, mlane, lanes),
                                   faults=self.faults, stats=self.ingest_stats)
        mask_d, gathered_d, count_d = self._probe(
            k_d, m_d, l_d, tcols[self.table.pk], tcols, tvalid)
        self.step_invocations += 1
        self.probe_invocations += 1
        if self.tracer is not None:
            from siddhi_tpu.observability.trace import STAGE_TABLE_PROBE

            self.tracer.record_span(STAGE_TABLE_PROBE, self.engine_kind,
                                    t0, time.perf_counter(), n_events=cn)

        def finish():
            c = int(fetch_coalesced([count_d])[0])
            if tok is not None:
                tok.step_done(c)
            if c == 0:
                self.emit_queue.skip()
                return
            arrays = [mask_d] + [gathered_d[nm] for nm in self._tbl_names]
            self.emit_queue.push(PendingEmit(
                arrays,
                lambda host: self._materialize(host, cur, lo, now),
                trace=tok))

        self.ingest_stage.submit(count_d, finish, trace=tok)

    # -- deferred materialization (runs on fetched HOST arrays) -----------

    def _materialize(self, host: List[np.ndarray], cur: EventBatch,
                     lo: int, now: int):
        mask = host[0]
        sel = np.flatnonzero(mask)
        rows = sel + lo
        cols: Dict[str, np.ndarray] = {}
        for a in self.stream_side.definition.attributes:
            cols[self.stream_side.qualified_key(a.name)] = \
                cur.columns[a.name][rows]
        for i, nm in enumerate(self._tbl_names):
            cols[self.table_side.qualified_key(nm)] = host[1 + i][sel]
        out = EventBatch(
            self.out_stream_id,
            self._out_names,
            {k: cols[k] for k in self._out_names},
            cur.timestamps[rows],
            np.full(len(rows), ev.CURRENT, dtype=np.int8),
        )
        out.aux["emit_now"] = now
        self.emit(out)

    # -- per-batch host fallback (exact host-join semantics) ---------------

    def _host_join(self, cur: EventBatch, now: int):
        buf = self.table.rows_batch()
        n_a, n_b = len(cur), len(buf)
        if n_b == 0:
            return
        env: Dict[str, np.ndarray] = {}
        for a in self.stream_side.definition.attributes:
            env[self.stream_side.qualified_key(a.name)] = np.repeat(
                cur.columns[a.name], n_b)
        for a in self.table.definition.attributes:
            env[self.table_side.qualified_key(a.name)] = np.tile(
                buf.columns[a.name], n_a)
        env[TS_KEY] = np.repeat(cur.timestamps, n_b)
        env[N_KEY] = n_a * n_b
        mask2 = np.broadcast_to(
            self.condition.fn(env), (n_a * n_b,)).reshape(n_a, n_b)
        ai, bi = np.nonzero(mask2)
        if len(ai) == 0:
            return
        cols: Dict[str, np.ndarray] = {}
        for a in self.stream_side.definition.attributes:
            cols[self.stream_side.qualified_key(a.name)] = \
                cur.columns[a.name][ai]
        for a in self.table.definition.attributes:
            cols[self.table_side.qualified_key(a.name)] = buf.columns[a.name][bi]
        out = EventBatch(
            self.out_stream_id,
            self._out_names,
            {k: cols[k] for k in self._out_names},
            cur.timestamps[ai],
            np.full(len(ai), ev.CURRENT, dtype=np.int8),
        )
        out.aux["emit_now"] = now
        self.emit(out)

    # -- barrier contract ---------------------------------------------------

    def drain(self):
        self.ingest_stage.flush()
        self.emit_queue.drain()

    def snapshot(self) -> Dict:
        self.drain()
        return {}

    def restore(self, state: Dict):
        self.drain()


class DevTableJoinReceiver:
    """Junction subscriber replacing ``JoinStreamReceiver`` for the
    stream side of a devtable-lowered join."""

    def __init__(self, runtime: DevTableJoinRuntime):
        self.runtime = runtime

    def receive(self, batch: EventBatch):
        self.runtime.process_stream_batch(batch)
