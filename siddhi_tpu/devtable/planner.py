"""Eligibility gates and lowering for device-resident tables.

Two planner entry points live here, both raising
``SiddhiAppCreationError`` with a human-readable reason when a query
does not fit the device path — callers catch that, log a WARNING and
count it on the statistics feed (``devtableFallbacks`` /
``devtableFallbackReason``), then fall back to the host table path.
Results never change; only the placement does.

``try_plan_devtable_join``
    Lowers an inner stream-table join onto ``DevTableJoinRuntime``
    when exactly one side is a live ``DeviceTable``, the stream side
    is bare (no window/filters/aggregation, triggering), and the
    condition carries a primary-key equality conjunct whose event
    expression evaluates host-side from stream attributes alone.
    Residual conjuncts are fine — the probe evaluates the FULL
    condition on device lanes — but every attribute the condition
    touches must ride a device lane (INT/FLOAT/BOOL).

``plan_devtable_mutation``
    Lowers delete / update / update-or-insert callbacks to the
    batched ``DeviceTable`` scatter entry points when the ``on``
    condition is a single primary-key equality and the set clause is
    event-only.  The returned callbacks keep the generic host-path
    callback around and delegate whole batches to it when a runtime
    shape the kernel cannot express shows up (primary-key rewrites,
    insert/update interleaving on one slot) — counted, never wrong.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.planner.expr import N_KEY, ExpressionCompiler, Scope
from siddhi_tpu.query_api.attribute import AttrType
from siddhi_tpu.query_api.expression import (
    CompareOp,
    Expression,
    Variable,
)

from .join import DevTableJoinRuntime
from .storage import _LANE_DTYPES, DeviceTable


def _gate(name: str, why: str) -> SiddhiAppCreationError:
    return SiddhiAppCreationError(f"query '{name}': devtable ineligible: {why}")


class _Recorder(dict):
    """Env dict that records which lanes a compiled fn actually reads.
    A read of a key outside the available lane set raises KeyError —
    the caller turns that into an eligibility gate."""

    def __init__(self, avail: Dict):
        super().__init__(avail)
        self.used = set()

    def __getitem__(self, k):
        self.used.add(k)
        return super().__getitem__(k)


def _split_conjuncts(e: Expression) -> List[Expression]:
    from siddhi_tpu.query_api.expression import AndOp

    if isinstance(e, AndOp):
        return _split_conjuncts(e.left) + _split_conjuncts(e.right)
    return [e]


def _refs_side(e: Expression, ids: Tuple) -> bool:
    """Does the expression reference (by qualifier) any of the ids?"""
    if isinstance(e, Variable):
        return e.stream_id in ids
    for f in ("left", "right", "expr"):
        sub = getattr(e, f, None)
        if isinstance(sub, Expression) and _refs_side(sub, ids):
            return True
    for a in getattr(e, "args", ()) or ():
        if isinstance(a, Expression) and _refs_side(a, ids):
            return True
    return False


def _pk_key_expr(name: str, cond: Expression, table: DeviceTable,
                 table_ids: Tuple) -> Expression:
    """Find a ``T.pk == <event expr>`` conjunct; return the event expr."""
    for term in _split_conjuncts(cond):
        if not (isinstance(term, CompareOp) and term.op == "=="):
            continue
        for tv, ot in ((term.left, term.right), (term.right, term.left)):
            if (isinstance(tv, Variable) and tv.attribute == table.pk
                    and tv.stream_id in table_ids
                    and not _refs_side(ot, table_ids)):
                return ot
    raise _gate(name, f"no primary-key equality conjunct on "
                      f"'{table.table_id}.{table.pk}'")


def try_plan_devtable_join(name: str, j, left, right, condition,
                           compiler: ExpressionCompiler, emit,
                           app_context) -> DevTableJoinRuntime:
    """Gate + lower a join to ``DevTableJoinRuntime``; raises
    ``SiddhiAppCreationError`` naming the first failed gate."""
    import jax

    from siddhi_tpu.query_api import JoinInputStream

    dev_left = isinstance(left.table, DeviceTable)
    dev_right = isinstance(right.table, DeviceTable)
    if not (dev_left or dev_right):
        raise _gate(name, "no device-resident table side")
    if dev_left and dev_right:
        raise _gate(name, "both sides are device tables")
    table_side, stream_side = (left, right) if dev_left else (right, left)
    stream_is_left = not dev_left
    table = table_side.table
    if table.demoted:
        raise _gate(name, "table already demoted to host")
    if j.join_type not in (JoinInputStream.JOIN, JoinInputStream.INNER_JOIN):
        raise _gate(name, f"join type '{j.join_type}' (inner only)")
    if condition is None:
        raise _gate(name, "no 'on' condition")
    if (stream_side.table is not None or stream_side.aggregation is not None
            or stream_side.window is not None
            or stream_side.named_window is not None or stream_side.filters):
        raise _gate(name, "stream side carries filters/window")
    if not stream_side.triggers:
        raise _gate(name, "stream side does not trigger")

    table_ids = (table_side.ref, table.table_id)
    key_ast = _pk_key_expr(name, j.on_condition, table, table_ids)
    key_c = compiler.compile(key_ast)
    if key_c.type != AttrType.INT:
        raise _gate(name, f"key expression type {key_c.type} (INT required)")

    # the key evaluates host-side from stream lanes alone
    stream_env = {
        stream_side.qualified_key(a.name): np.zeros(4, dtype=a.type.np_dtype)
        for a in stream_side.definition.attributes
    }
    from siddhi_tpu.planner.expr import TS_KEY

    kenv = _Recorder(stream_env)
    kenv[TS_KEY] = np.zeros(4, dtype=np.int64)
    kenv[N_KEY] = 4
    try:
        np.broadcast_to(key_c.fn(kenv), (4,))
    except Exception as e:
        raise _gate(name, f"key expression not stream-only ({e})")

    # the full condition evaluates on device lanes: INT/FLOAT/BOOL stream
    # attrs + every table attr (DeviceTable admits lane dtypes only)
    avail: Dict[str, np.ndarray] = {}
    stream_lanes: Dict[str, Tuple[str, np.dtype]] = {}
    for a in stream_side.definition.attributes:
        dt = _LANE_DTYPES.get(a.type)
        if dt is None:
            continue
        ek = stream_side.qualified_key(a.name)
        avail[ek] = np.zeros(4, dtype=dt)
        stream_lanes[ek] = (a.name, dt)
    for a in table.definition.attributes:
        avail[table_side.qualified_key(a.name)] = np.zeros(
            4, dtype=table._dtypes[a.name])
    # pass 1 (numpy): record which lanes the condition actually reads —
    # touching anything outside the lane env (STRING/LONG attrs, the
    # timestamp key) raises KeyError here and keeps the host join
    rec = _Recorder(avail)
    rec[N_KEY] = 4
    try:
        np.broadcast_to(condition.fn(rec), (4,))
    except Exception as e:
        raise _gate(name, f"condition not device-evaluable ({e})")
    # pass 2 (trace): it must ALSO trace through jit over abstract lanes
    # (eval_shape needs a plain-dict pytree, so the recorder stays host-only)
    env = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
           for k, v in avail.items()}
    env[N_KEY] = 4
    try:
        jax.eval_shape(lambda en: condition.fn(en), env)
    except Exception as e:
        raise _gate(name, f"condition not device-traceable ({e})")
    used = {ek: stream_lanes[ek] for ek in rec.used if ek in stream_lanes}

    return DevTableJoinRuntime(
        name, stream_side, table_side, stream_is_left,
        condition, key_c, used,
        out_stream_id=f"#join_{name}", emit=emit,
        emit_depth=app_context.tpu_emit_depth,
        ingest_depth=app_context.tpu_ingest_depth,
        clock=app_context.timestamp_generator.current_time,
        faults=app_context.fault_injector,
        tracer=app_context.tracer,
    )


def plan_devtable_mutation(name: str, out, out_def, out_scope: Scope,
                           table: DeviceTable, generic,
                           functions=None, table_resolver=None):
    """Gate + lower a delete/update/upsert output to the batched
    ``DeviceTable`` entry points; raises ``SiddhiAppCreationError``
    when the host path must keep the query."""
    from siddhi_tpu.query_api import DeleteStream, UpdateOrInsertStream, UpdateStream
    from siddhi_tpu.table.callbacks import (
        DevTableDeleteCallback,
        DevTableUpdateCallback,
        DevTableUpsertCallback,
    )
    from siddhi_tpu.table.table import _equality_terms

    if table.demoted:
        raise _gate(name, "table already demoted to host")
    if out.on_condition is None:
        raise _gate(name, "no 'on' condition")
    terms, only_conj = _equality_terms(out.on_condition, table)
    if not only_conj or len(terms) != 1 or terms[0][0] != table.pk:
        raise _gate(name, "condition is not a single primary-key equality")
    compiler = ExpressionCompiler(out_scope, functions=functions,
                                  table_resolver=table_resolver)
    try:
        key_c = compiler.compile(terms[0][1])
    except SiddhiAppCreationError as e:
        raise _gate(name, f"key expression not event-only ({e})")
    if key_c.type != AttrType.INT:
        raise _gate(name, f"key expression type {key_c.type} (INT required)")

    output_names = [a.name for a in out_def.attributes]
    if isinstance(out, DeleteStream):
        return DevTableDeleteCallback(table, key_c, out.event_type)

    tbl_attrs = set(table.definition.attribute_names)
    set_ops: List[Tuple[str, object]] = []
    if out.set_clause is None:
        shared = [nm for nm in output_names if nm in tbl_attrs]
        if not shared:
            raise _gate(name, "default set clause shares no attributes")
        for nm in shared:
            set_ops.append((nm, compiler.compile(Variable(attribute=nm))))
    else:
        for sa in out.set_clause:
            v = sa.variable
            if v.stream_id not in (None, table.table_id) or \
                    v.attribute not in tbl_attrs:
                raise _gate(name, f"set target '{v.attribute}' is not a "
                                  "table attribute")
            try:
                set_ops.append((v.attribute, compiler.compile(sa.expression)))
            except SiddhiAppCreationError as e:
                raise _gate(name, f"set expression not event-only ({e})")

    if isinstance(out, UpdateStream):
        return DevTableUpdateCallback(table, key_c, set_ops, out.event_type,
                                      generic)
    if isinstance(out, UpdateOrInsertStream):
        missing = tbl_attrs - set(output_names)
        if missing:
            raise _gate(name, "update-or-insert output does not cover table "
                              f"attributes {sorted(missing)}")
        return DevTableUpsertCallback(table, key_c, set_ops, out.event_type,
                                      generic)
    raise _gate(name, f"output type {type(out).__name__}")
