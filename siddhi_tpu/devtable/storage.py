"""Device-resident tables: columnar HBM storage + jitted scatter upserts.

Re-design of ``table/table.py``'s ``InMemoryTable`` with the row storage
moved onto the accelerator: one ``[C]``-capacity device column per
attribute plus a validity lane, while the slot-index map (primary key ->
slot), timestamps and a liveness mirror stay host-side so probes and
eligibility decisions never synchronize.  Mutations lower to ONE jitted
in-place scatter step per callback batch, reusing the collision-free
one-hot discipline of ``kernels/bank_scatter.py``: every write row
scatters through a ``[N, C]`` one-hot plane and an argmax over the row
order resolves duplicate keys last-writer-wins *inside* the kernel, so
duplicate keys within a batch never race.

Consistency is MVCC-ish revision pinning: JAX arrays are immutable, so
each scatter produces NEW column arrays; ``drain()`` — called at the
batch-cycle barrier by ``SiddhiAppRuntime.drain_device_emits`` —
advances the table revision and pins the current array references.
``persist()``/``restore``, on-demand queries and the debugger read the
pinned revision: the PR 9 capture machinery (``durability/capture.py``)
freezes the pinned device references in-barrier and fetches them on the
checkpoint writer thread while the batch loop keeps mutating fresh
arrays.

Capacity is fixed at ``@app:devtables(capacity='N')``.  Deletes
tombstone (validity lane cleared, key unmapped) without recycling the
slot mid-cycle; a counted compaction at the barrier — or on demand when
an insert would overflow — moves tombstones to the free list.  If the
table is still full after compacting, it demotes itself to a host
``InMemoryTable`` mid-run with a WARNING and a counted
``devtableDemotions`` gauge — never a crash.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from siddhi_tpu.core.emit_queue import fetch_coalesced
from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.core.ingest_stage import IngestStats, staged_put
from siddhi_tpu.query_api import AttrType
from siddhi_tpu.query_api.annotation import find_annotation
from siddhi_tpu.table.table import TBL, _scalar

log = logging.getLogger("siddhi_tpu")

# attribute types that ride device lanes BIT-EXACTLY: the host table
# stores these very numpy dtypes, so host/devtable differentials are
# equality, not tolerance (LONG/DOUBLE would narrow on device lanes and
# STRING/OBJECT cannot ride at all — all gate to the host path)
_LANE_DTYPES = {
    AttrType.INT: np.dtype(np.int32),
    AttrType.FLOAT: np.dtype(np.float32),
    AttrType.BOOL: np.dtype(np.bool_),
}


def _pow2(n: int, floor: int = 8) -> int:
    return max(1 << (max(n, 1) - 1).bit_length(), floor)


def _scatter_body(cols, valid, vals, write_slots, kill_slots):
    """One-hot LWW scatter (the bank_scatter discipline): write row j
    lands at ``write_slots[j]`` (-1 inert); duplicate slots within the
    batch resolve to the LAST row via argmax over the row order;
    ``kill_slots`` clear validity and win over same-step writes (a
    displaced row is dead even if the step also wrote it, matching the
    host table's sequential delete-then-update bookkeeping)."""
    import jax.numpy as jnp

    cap = valid.shape[0]
    n = write_slots.shape[0]
    lane = jnp.arange(cap, dtype=jnp.int32)[None, :]
    w1h = write_slots[:, None] == lane  # [N, C]; -1 rows touch nothing
    touched = w1h.any(axis=0)
    order = jnp.arange(1, n + 1, dtype=jnp.int32)[:, None]
    winner = jnp.argmax(jnp.where(w1h, order, 0), axis=0)  # last writer
    out = {}
    for nm, col in cols.items():
        v = vals.get(nm)
        out[nm] = col if v is None else jnp.where(touched, v[winner], col)
    killed = (kill_slots[:, None] == lane).any(axis=0)
    return out, (valid | touched) & ~killed


class _NotDeviceable(Exception):
    """A value this batch cannot ride a typed device lane (null / object
    dtype) — the caller demotes gracefully instead of crashing."""


class DeviceTable:
    """Columnar table resident in device HBM, duck-type compatible with
    ``InMemoryTable`` so every host read path — compiled conditions,
    on-demand queries, generic callbacks, ``IN table`` membership —
    works unchanged (reads fetch through the sanctioned
    ``fetch_coalesced``; the pk probe never leaves the host)."""

    def __init__(self, definition, capacity: int = 1024, faults=None,
                 tracer=None, statistics_manager=None):
        import jax

        self.definition = definition
        self.table_id = definition.id
        self._lock = threading.RLock()
        if capacity < 1:
            raise SiddhiAppCreationError(
                f"devtable '{self.table_id}': capacity must be >= 1")
        self._cap = int(capacity)

        # -- eligibility: raise SiddhiAppCreationError -> host fallback --
        pk_ann = find_annotation(definition.annotations, "PrimaryKey")
        pks = ([v for _, v in pk_ann.elements] or None) if pk_ann is not None else None
        if not pks or len(pks) != 1:
            raise SiddhiAppCreationError(
                f"devtable '{self.table_id}': needs exactly one primary "
                "key attribute (slot-index map is a single-key hash)")
        pk = pks[0]
        if pk not in definition.attribute_names:
            raise SiddhiAppCreationError(
                f"table '{definition.id}': primary key '{pk}' is not an attribute")
        for a in definition.attributes:
            if a.type not in _LANE_DTYPES:
                raise SiddhiAppCreationError(
                    f"devtable '{self.table_id}': attribute '{a.name}' is "
                    f"{a.type.name} — device lanes carry INT/FLOAT/BOOL "
                    "bit-exactly; other types keep the host table")
        if any(a.name.lower() == "index" for a in definition.annotations):
            raise SiddhiAppCreationError(
                f"devtable '{self.table_id}': @Index needs host-side "
                "per-value slot sets; indexed tables keep the host path")
        if next(a for a in definition.attributes if a.name == pk).type != AttrType.INT:
            raise SiddhiAppCreationError(
                f"devtable '{self.table_id}': primary key '{pk}' must be "
                "INT (int32 device key lane)")

        self.primary_keys: List[str] = [pk]
        self.pk = pk
        self.indexes: Dict[str, Dict] = {}
        self._dtypes = {a.name: _LANE_DTYPES[a.type] for a in definition.attributes}

        # -- host-side metadata (no device sync to read any of it) --------
        self._pk_map: Dict[int, int] = {}
        self._slot_key: Dict[int, int] = {}
        self._hlive = np.zeros(self._cap, dtype=bool)
        self._ts = np.zeros(self._cap, dtype=np.int64)
        self._hwm = 0
        self._free: List[int] = []
        self._tombstones: List[int] = []

        # -- device-resident state ----------------------------------------
        self.ingest_stats = IngestStats()
        init = {nm: np.zeros(self._cap, dtype=dt) for nm, dt in self._dtypes.items()}
        init["__valid"] = np.zeros(self._cap, dtype=bool)
        placed = staged_put(init, stats=self.ingest_stats)  # state init: unarmed
        self._dvalid = placed.pop("__valid")
        self._dcols = placed
        self._scatter = jax.jit(_scatter_body)

        # -- MVCC pinning / stats ------------------------------------------
        self.revision = 0
        self._dirty = False
        self._pinned: Optional[Dict] = None
        self.scatter_steps = 0
        self.compactions = 0
        self.demotions = 0
        self._host = None  # set on graceful demotion
        self._faults = faults
        self._tracer = tracer
        self._sm = statistics_manager
        self._pin()

    # -- basics ---------------------------------------------------------

    @property
    def demoted(self) -> bool:
        return self._host is not None

    def __len__(self) -> int:
        if self._host is not None:
            return len(self._host)
        return int(self._hlive.sum())

    @property
    def size(self) -> int:
        return len(self)

    def live_slots(self) -> np.ndarray:
        if self._host is not None:
            return self._host.live_slots()
        return np.flatnonzero(self._hlive)

    # -- demotion / capacity --------------------------------------------

    def _demote(self, reason: str):
        """Rebuild the rows in a host InMemoryTable and route every
        future call there — graceful mid-run demotion, never a crash."""
        from siddhi_tpu.table.table import InMemoryTable

        log.warning(
            "devtable '%s': demoting to the host table path mid-run "
            "(%s); reads/mutations continue host-side", self.table_id, reason)
        host = InMemoryTable(self.definition, capacity=max(self._cap, 64))
        slots = np.flatnonzero(self._hlive)
        names = self.definition.attribute_names
        cols = fetch_coalesced([self._dcols[nm][slots] for nm in names])
        with host._lock:
            for i in range(len(slots)):
                row = {nm: cols[k][i] for k, nm in enumerate(names)}
                host._insert_row(row, int(self._ts[slots[i]]))
        self._host = host
        # the slot-index map is the shared currency of compiled pk
        # probes — rebind so in-flight CompiledTableCondition objects
        # follow the demotion without replanning
        self._pk_map = host._pk_map
        self.demotions += 1
        if self._sm is not None:
            self._sm.record_devtable_fallback(
                f"table:{self.table_id}", f"demoted: {reason}")

    def _compact(self):
        """Counted reclamation of tombstoned slots (their validity lane
        is already False on device) — runs at the barrier and on demand
        when an insert would overflow."""
        if not self._tombstones:
            return
        self._free.extend(self._tombstones)
        self._tombstones = []
        self.compactions += 1

    def _ensure_capacity(self, n_new: int) -> bool:
        avail = len(self._free) + (self._cap - self._hwm)
        if n_new <= avail:
            return True
        self._compact()
        avail = len(self._free) + (self._cap - self._hwm)
        if n_new <= avail:
            return True
        self._demote(
            f"capacity {self._cap} exhausted even after compaction "
            f"({n_new} new keys, {avail} free slots)")
        return False

    def _alloc(self) -> int:
        if self._free:
            return self._free.pop()
        s = self._hwm
        self._hwm += 1
        return s

    # -- lane conversion -------------------------------------------------

    def _lane(self, arr, nm: str, n: int) -> np.ndarray:
        a = arr if isinstance(arr, np.ndarray) else np.empty(0)
        if not isinstance(arr, np.ndarray) or a.dtype.kind == "O":
            raise _NotDeviceable(
                f"attribute '{nm}' carries nulls/objects this batch")
        return a[:n].astype(self._dtypes[nm], copy=False)

    # -- the scatter step -------------------------------------------------

    def _apply_scatter(self, write_slots: List[int],
                       vals: Dict[str, np.ndarray],
                       kill_slots: List[int]):
        """ONE jitted one-hot LWW scatter for this mutation batch; pads
        to pow-2 row counts so retraces stay bounded."""
        t0 = time.perf_counter()
        n = len(write_slots)
        npad = _pow2(n)
        w = np.full(npad, -1, dtype=np.int32)
        if n:
            w[:n] = np.fromiter(write_slots, dtype=np.int32, count=n)
        kpad = _pow2(len(kill_slots))
        k = np.full(kpad, -1, dtype=np.int32)
        if kill_slots:
            k[:len(kill_slots)] = np.fromiter(
                kill_slots, dtype=np.int32, count=len(kill_slots))
        pv = {}
        for nm, v in vals.items():
            col = np.zeros(npad, dtype=self._dtypes[nm])
            col[:n] = v
            pv[nm] = col
        w_d, k_d, v_d = staged_put(
            (w, k, pv), faults=self._faults, stats=self.ingest_stats)
        self._dcols, self._dvalid = self._scatter(
            self._dcols, self._dvalid, v_d, w_d, k_d)
        self.scatter_steps += 1
        self._dirty = True
        if self._tracer is not None:
            from siddhi_tpu.observability.trace import STAGE_TABLE_UPSERT

            self._tracer.record_span(
                STAGE_TABLE_UPSERT, "devtable", t0, time.perf_counter(),
                n_events=n)

    def device_state(self):
        """(cols, valid) CURRENT device references — a probe closing
        over them is snapshot-consistent by array immutability."""
        with self._lock:
            return self._dcols, self._dvalid

    # -- batched lowered mutations ----------------------------------------

    def insert(self, batch: EventBatch):
        """Add rows; duplicate keys replace (LWW) — within the batch the
        duplicates share one slot and the kernel argmax picks the last."""
        with self._lock:
            if self._host is not None:
                self._host.insert(batch)
                return
            names = self.definition.attribute_names
            n = len(batch)
            try:
                cols = {nm: self._lane(batch.columns[nm], nm, n) for nm in names}
            except _NotDeviceable as e:
                self._demote(str(e))
                self._host.insert(batch)
                return
            keys = cols[self.pk]
            n_new = 0
            seen = set()
            for kk in keys.tolist():
                if kk not in self._pk_map and kk not in seen:
                    seen.add(kk)
                    n_new += 1
            if not self._ensure_capacity(n_new):
                self._host.insert(batch)
                return
            write_slots: List[int] = []
            for j in range(n):
                kk = int(keys[j])
                s = self._pk_map.get(kk)
                if s is None:
                    s = self._alloc()
                    self._pk_map[kk] = s
                    self._slot_key[s] = kk
                self._hlive[s] = True
                self._ts[s] = int(batch.timestamps[j])
                write_slots.append(s)
            self._apply_scatter(write_slots, cols, [])

    def _insert_row(self, row: Dict, ts: int) -> int:
        """Single-row generic entry (update-or-insert miss branch of the
        host callback).  A None value cannot ride a typed lane — demote
        gracefully and let the host table hold it."""
        with self._lock:
            if self._host is None and any(row.get(nm) is None
                                          for nm in self.definition.attribute_names):
                self._demote("null value in inserted row (partial projection)")
            if self._host is not None:
                with self._host._lock:
                    return self._host._insert_row(row, ts)
            names = self.definition.attribute_names
            cols = {}
            try:
                for nm in names:
                    a = np.zeros(1, dtype=self._dtypes[nm])
                    a[0] = _scalar(row[nm])
                    cols[nm] = a
            except (TypeError, ValueError):
                self._demote(f"non-device value in inserted row: {row!r}")
                with self._host._lock:
                    return self._host._insert_row(row, ts)
            kk = int(cols[self.pk][0])
            s = self._pk_map.get(kk)
            if s is None:
                if not self._ensure_capacity(1):
                    with self._host._lock:
                        return self._host._insert_row(row, ts)
                s = self._alloc()
                self._pk_map[kk] = s
                self._slot_key[s] = kk
            self._hlive[s] = True
            self._ts[s] = int(ts)
            self._apply_scatter([s], cols, [])
            return s

    def delete_keys(self, keys: np.ndarray):
        """Lowered delete: unmap + tombstone, one kill scatter."""
        with self._lock:
            if self._host is not None:
                slots = [self._pk_map[int(kk)] for kk in keys.tolist()
                         if int(kk) in self._pk_map]
                self._host.delete_slots(slots)
                return
            kills: List[int] = []
            for kk in keys.tolist():
                s = self._pk_map.pop(int(kk), None)
                if s is None or not self._hlive[s]:
                    continue
                self._slot_key.pop(s, None)
                self._hlive[s] = False
                self._tombstones.append(s)
                kills.append(s)
            if kills:
                self._apply_scatter([], {}, kills)

    def delete_slots(self, slots):
        """Generic entry (host DeleteTableCallback probing via compiled
        conditions)."""
        with self._lock:
            if self._host is not None:
                self._host.delete_slots(slots)
                return
            kills: List[int] = []
            for s in slots:
                s = int(s)
                if not self._hlive[s]:
                    continue
                kk = self._slot_key.pop(s, None)
                if kk is not None and self._pk_map.get(kk) == s:
                    del self._pk_map[kk]
                self._hlive[s] = False
                self._tombstones.append(s)
                kills.append(s)
            if kills:
                self._apply_scatter([], {}, kills)

    def update_keys(self, keys: np.ndarray, values: Dict[str, np.ndarray]):
        """Lowered update (no primary-key rewrite — gated at plan time):
        rows whose key misses are dropped, matching the host probe."""
        with self._lock:
            if self._host is not None:
                slots, idx = self._key_slots(keys)
                if slots:
                    self._host.update_slots(
                        slots, {nm: v[idx] for nm, v in values.items()})
                return
            slots, idx = self._key_slots(keys)
            if not slots:
                return
            try:
                vals = {nm: self._lane(v[idx], nm, len(slots))
                        for nm, v in values.items()}
            except _NotDeviceable as e:
                self._demote(str(e))
                self._host.update_slots(
                    slots, {nm: v[idx] for nm, v in values.items()})
                return
            self._apply_scatter(slots, vals, [])

    def _key_slots(self, keys: np.ndarray):
        slots: List[int] = []
        idx: List[int] = []
        for j, kk in enumerate(keys.tolist()):
            s = self._pk_map.get(int(kk))
            if s is not None and (self._host is not None or self._hlive[s]):
                slots.append(s)
                idx.append(j)
        return slots, np.fromiter(idx, dtype=np.int64, count=len(idx))

    def update_slots(self, slots, values: Dict):
        """Generic entry; handles primary-key rewrites with the host
        table's sequential last-writer-wins bookkeeping (a displaced
        row dies even when this very step also wrote it)."""
        with self._lock:
            if self._host is not None:
                self._host.update_slots(slots, values)
                return
            live = [(j, int(s)) for j, s in enumerate(slots) if self._hlive[int(s)]]
            if not live:
                return
            idx = np.fromiter((j for j, _ in live), dtype=np.int64, count=len(live))
            wslots = [s for _, s in live]
            try:
                vals = {nm: self._lane(np.ascontiguousarray(v)[idx], nm, len(live))
                        for nm, v in values.items()}
            except _NotDeviceable as e:
                self._demote(str(e))
                self._host.update_slots(slots, values)
                return
            kills: List[int] = []
            if self.pk in vals:
                new_keys = vals[self.pk]
                for r, (_, s) in enumerate(live):
                    old = self._slot_key.get(s)
                    nk = int(new_keys[r])
                    if old == nk:
                        continue
                    if old is not None and self._pk_map.get(old) == s:
                        del self._pk_map[old]
                    other = self._pk_map.get(nk)
                    if other is not None and other != s:
                        # key collision: the displaced row dies (LWW)
                        self._slot_key.pop(other, None)
                        self._hlive[other] = False
                        self._tombstones.append(other)
                        kills.append(other)
                    self._pk_map[nk] = s
                    self._slot_key[s] = nk
            self._apply_scatter(wslots, vals, kills)

    def upsert(self, keys: np.ndarray, insert_cols: Dict[str, np.ndarray],
               set_cols: Dict[str, np.ndarray], ts: np.ndarray) -> bool:
        """Lowered update-or-insert: rows classify sequentially against a
        speculative key view (a key inserted by an earlier row turns later
        duplicates into updates, matching the host's sequential probe),
        then apply as two scatters — inserts (full rows) before updates
        (set attrs).  The probe key and the inserted row's own primary
        key may differ (``on T.k == S.a`` with a projected ``k``); the
        slot map follows the INSERTED key, like the host ``_insert_row``.

        Returns False — with NOTHING mutated — when the batch needs an
        insert of a slot AFTER an update of the same slot (the two-phase
        scatter order would invert host sequential semantics); the
        caller delegates that batch to the generic host-path callback."""
        with self._lock:
            if self._host is not None:
                self._host_upsert(keys, insert_cols, set_cols, ts)
                return True
            try:
                ins = {nm: self._lane(v, nm, len(keys))
                       for nm, v in insert_cols.items()}
                upd = {nm: self._lane(v, nm, len(keys))
                       for nm, v in set_cols.items()}
            except _NotDeviceable as e:
                self._demote(str(e))
                self._host_upsert(keys, insert_cols, set_cols, ts)
                return True
            ikeys = ins[self.pk]

            # pass A: pure simulation — new-slot count + ordering check
            sim: Dict[int, object] = {}

            def tok_of(kk: int):
                t = sim.get(kk)
                if t is not None:
                    return t
                return self._pk_map.get(kk)

            n_new = 0
            ins_last: Dict[object, int] = {}
            upd_first: Dict[object, int] = {}
            for j, kk in enumerate(keys.tolist()):
                t = tok_of(int(kk))
                if t is not None:
                    upd_first.setdefault(t, j)
                else:
                    ik = int(ikeys[j])
                    t2 = tok_of(ik)
                    if t2 is None:
                        t2 = ("new", ik)
                        n_new += 1
                    sim[ik] = t2
                    ins_last[t2] = j
            for t, jl in ins_last.items():
                if t in upd_first and jl > upd_first[t]:
                    return False  # insert after update of the same slot

            if not self._ensure_capacity(n_new):
                self._host_upsert(keys, insert_cols, set_cols, ts)
                return True

            # pass B: apply
            ins_slots: List[int] = []
            ins_idx: List[int] = []
            upd_slots: List[int] = []
            upd_idx: List[int] = []
            for j, kk in enumerate(keys.tolist()):
                s = self._pk_map.get(int(kk))
                if s is not None:
                    upd_slots.append(s)
                    upd_idx.append(j)
                    continue
                ik = int(ikeys[j])
                s = self._pk_map.get(ik)  # in-place replace on collision
                if s is None:
                    s = self._alloc()
                self._pk_map[ik] = s
                self._slot_key[s] = ik
                self._hlive[s] = True
                self._ts[s] = int(ts[j])
                ins_slots.append(s)
                ins_idx.append(j)
            if ins_slots:
                ii = np.fromiter(ins_idx, dtype=np.int64, count=len(ins_idx))
                self._apply_scatter(
                    ins_slots, {nm: v[ii] for nm, v in ins.items()}, [])
            if upd_slots:
                ui = np.fromiter(upd_idx, dtype=np.int64, count=len(upd_idx))
                self._apply_scatter(
                    upd_slots, {nm: v[ui] for nm, v in upd.items()}, [])
            return True

    def _host_upsert(self, keys, insert_cols, set_cols, ts):
        """Demoted path: sequential per-row emulation of the host
        update-or-insert callback."""
        host = self._host
        for j, kk in enumerate(keys.tolist()):
            s = self._pk_map.get(int(kk))
            if s is not None and host._live[s]:
                host.update_slots([s], {nm: v[j:j + 1]
                                        for nm, v in set_cols.items()})
            else:
                row = {nm: insert_cols[nm][j]
                       for nm in self.definition.attribute_names}
                with host._lock:
                    host._insert_row(row, int(ts[j]))

    # -- reads (sanctioned coalesced fetch; pk probe stays host) ----------

    def rows_batch(self, slots: Optional[np.ndarray] = None) -> EventBatch:
        with self._lock:
            if self._host is not None:
                return self._host.rows_batch(slots)
            if slots is None:
                slots = self.live_slots()
            names = self.definition.attribute_names
            cols_dev = [self._dcols[nm][slots] for nm in names]
            ts = self._ts[slots]
        cols = fetch_coalesced(cols_dev)
        return EventBatch(self.table_id, names,
                          {nm: cols[i] for i, nm in enumerate(names)}, ts)

    def column_env(self, slots: np.ndarray) -> Dict[str, np.ndarray]:
        with self._lock:
            if self._host is not None:
                return self._host.column_env(slots)
            names = self.definition.attribute_names
            cols_dev = [self._dcols[nm][slots] for nm in names]
        cols = fetch_coalesced(cols_dev)
        return {TBL + nm: cols[i] for i, nm in enumerate(names)}

    def contains_fn(self, attr_hint: Optional[str] = None):
        def member(values) -> np.ndarray:
            with self._lock:
                if self._host is not None:
                    return self._host.contains_fn(attr_hint)(values)
                keys = self._pk_map
            vals = np.atleast_1d(np.ascontiguousarray(values))
            return np.frompyfunc(lambda v: _scalar(v) in keys, 1, 1)(
                vals).astype(bool)

        return member

    # -- barrier / MVCC pinning -------------------------------------------

    def _pin(self):
        self._pinned = {
            "cols": dict(self._dcols),
            "slots": np.flatnonzero(self._hlive),
            "ts": self._ts.copy(),
            "revision": self.revision,
        }

    def drain(self):
        """Batch-cycle barrier (SiddhiAppRuntime.drain_device_emits):
        compact tombstones, advance the revision if mutations landed,
        and pin the current immutable column references — the snapshot
        every consistent reader (persist / on-demand / debugger) sees."""
        with self._lock:
            if self._host is not None:
                return
            self._compact()
            if self._dirty:
                self.revision += 1
                self._dirty = False
                self._pin()

    def devtable_metrics(self) -> Dict[str, object]:
        return {
            "devtableLiveRows": len(self),
            "devtableCapacity": self._cap,
            "devtableRevision": self.revision,
            "devtableScatterSteps": self.scatter_steps,
            "devtableCompactions": self.compactions,
            "devtableDemotions": self.demotions,
            "devtableDemoted": self._host is not None,
        }

    # -- snapshot contract (host-format compatible) -----------------------

    def snapshot(self) -> Dict:
        """State of the PINNED revision: device gathers against the
        pinned (immutable) column references — ``durability/capture.py``
        freezes these by reference and the writer thread fetches them,
        so the async checkpoint sees revision R while the batch loop
        mutates R+1."""
        with self._lock:
            if self._host is not None:
                return self._host.snapshot()
            p = self._pinned
            slots = p["slots"]
            return {
                "cols": {nm: p["cols"][nm][slots]
                         for nm in self.definition.attribute_names},
                "ts": p["ts"][slots].copy(),
                "revision": p["revision"],
            }

    def restore(self, state: Dict):
        with self._lock:
            if self._host is not None:
                self._host.restore(state)
                return
            names = self.definition.attribute_names
            ts = np.ascontiguousarray(state["ts"]).astype(np.int64)
            n = len(ts)
            if n > self._cap:
                self._demote(f"restored state has {n} rows > capacity {self._cap}")
                self._host.restore(state)
                return
            cols = fetch_coalesced([state["cols"][nm] for nm in names])
            self._pk_map = {}
            self._slot_key = {}
            self._free = []
            self._tombstones = []
            self._hwm = n
            self._hlive[:] = False
            self._hlive[:n] = True
            self._ts[:] = 0
            self._ts[:n] = ts
            init = {}
            for i, nm in enumerate(names):
                col = np.zeros(self._cap, dtype=self._dtypes[nm])
                col[:n] = np.ascontiguousarray(cols[i]).astype(
                    self._dtypes[nm], copy=False)
                init[nm] = col
            init["__valid"] = self._hlive.copy()
            placed = staged_put(init, stats=self.ingest_stats)  # barrier, unarmed
            self._dvalid = placed.pop("__valid")
            self._dcols = placed
            kcol = init[self.pk]
            for s in range(n):
                kk = int(kcol[s])
                self._pk_map[kk] = s
                self._slot_key[s] = kk
            self.revision = int(state.get("revision", 0))
            self._dirty = False
            self._pin()
