"""Step debugger: breakpoints at query IN/OUT terminals.

Re-design of the reference ``debugger/SiddhiDebugger.java:36``
(acquireBreakPoint:95, checkBreakPoint:133 blocks the event thread on a
lock; next()/play() release it) for batched execution: checkpoints sit
at micro-batch boundaries — a breakpoint delivers the whole batch at the
query terminal to the debugger callback, and the event thread blocks
until ``next()`` (stop at the next checkpoint, acquired or not) or
``play()`` (run to the next acquired breakpoint).  Calling next()/play()
from inside the callback — the SiddhiDebuggerClient pattern — resumes
without blocking.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Set, Tuple

from siddhi_tpu.core.event import Event, EventBatch, events_from_batch


class QueryTerminal:
    IN = "IN"
    OUT = "OUT"


class SiddhiDebugger:
    """One per debugged app runtime (``SiddhiAppRuntime.debug()``)."""

    QueryTerminal = QueryTerminal

    def __init__(self, app_runtime):
        self.app = app_runtime
        self._acquired: Set[Tuple[str, str]] = set()
        self._step = False  # next(): break at the very next checkpoint
        self._callback: Optional[Callable] = None
        self._resume = threading.Event()
        self._resume.set()
        self._lock = threading.Lock()

    # -- breakpoint management ----------------------------------------------

    def acquire_break_point(self, query_name: str, terminal: str):
        """reference: SiddhiDebugger.acquireBreakPoint:95"""
        with self._lock:
            self._acquired.add((query_name, terminal))

    def release_break_point(self, query_name: str, terminal: str):
        with self._lock:
            self._acquired.discard((query_name, terminal))

    def release_all_break_points(self):
        with self._lock:
            self._acquired.clear()

    def set_debugger_callback(self, callback: Callable):
        """``callback(events, query_name, terminal, debugger)`` runs on
        the event thread when a breakpoint hits."""
        self._callback = callback

    # -- stepping ------------------------------------------------------------

    def next(self):
        """Resume and stop at the next checkpoint of any query."""
        self._step = True
        self._resume.set()

    def play(self):
        """Resume and run until the next acquired breakpoint."""
        self._step = False
        self._resume.set()

    # -- state inspection ----------------------------------------------------

    def get_query_state(self, query_name: str):
        qr = self.app.query_runtimes.get(query_name)
        if qr is None or not hasattr(qr, "snapshot_state"):
            return None
        return qr.snapshot_state()

    # Java-style aliases
    acquireBreakPoint = acquire_break_point
    releaseBreakPoint = release_break_point
    releaseAllBreakPoints = release_all_break_points
    setDebuggerCallback = set_debugger_callback
    getQueryState = get_query_state

    # -- engine-facing hook --------------------------------------------------

    def check_breakpoint(self, query_name: str, terminal: str, batch: EventBatch):
        """Called by QueryRuntime at each terminal; blocks the event
        thread while the breakpoint holds (reference:
        SiddhiDebugger.checkBreakPoint:133)."""
        with self._lock:
            hit = self._step or (query_name, terminal) in self._acquired
        if not hit:
            return
        self._step = False
        self._resume.clear()
        cb = self._callback
        if cb is not None:
            cb(events_from_batch(batch), query_name, terminal, self)
        # a callback that called next()/play() has already set the event
        self._resume.wait()
