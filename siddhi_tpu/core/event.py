"""Event model: user-facing Event + columnar EventBatch.

TPU-native replacement for the reference's pooled linked-list event chunks
(``core/event/``: StreamEvent with 3 Object[] segments + next pointer,
ComplexEventChunk cursor — StreamEvent.java:37-56).  Here a chunk of
events is a **columnar micro-batch**: one array per attribute plus
timestamp and event-type lanes.  Numeric columns are numpy arrays that
flow into jit-compiled steps unchanged; STRING/OBJECT columns stay host
side as object arrays (string partition/group-by keys are interned to
int64 ids by the keyed-state machinery).

Event types mirror ComplexEvent.Type: CURRENT, EXPIRED, TIMER, RESET.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

from siddhi_tpu.query_api import AttrType
from siddhi_tpu.query_api.definition import AbstractDefinition

# event type lanes
CURRENT = 0
EXPIRED = 1
TIMER = 2
RESET = 3

_TYPE_NAMES = {CURRENT: "CURRENT", EXPIRED: "EXPIRED", TIMER: "TIMER", RESET: "RESET"}


class Event:
    """User-facing event: timestamp (ms) + data tuple.

    Mirrors ``io.siddhi.core.event.Event``.
    """

    __slots__ = ("timestamp", "data", "is_expired")

    def __init__(self, timestamp: int = -1, data: Optional[Sequence] = None, is_expired: bool = False):
        self.timestamp = timestamp
        self.data = list(data) if data is not None else []
        self.is_expired = is_expired

    def __repr__(self):
        return f"Event{{timestamp={self.timestamp}, data={self.data}, isExpired={self.is_expired}}}"

    def __eq__(self, other):
        return (
            isinstance(other, Event)
            and self.timestamp == other.timestamp
            and self.data == other.data
            and self.is_expired == other.is_expired
        )


class EventBatch:
    """Columnar batch of events on one stream.

    columns: attribute name -> np.ndarray (len n)
    timestamps: int64[n] (ms)
    types: int8[n] of CURRENT/EXPIRED/TIMER/RESET
    """

    __slots__ = ("stream_id", "attribute_names", "columns", "timestamps", "types", "aux")

    def __init__(
        self,
        stream_id: str,
        attribute_names: List[str],
        columns: Dict[str, np.ndarray],
        timestamps: np.ndarray,
        types: Optional[np.ndarray] = None,
    ):
        self.stream_id = stream_id
        self.attribute_names = attribute_names
        self.columns = columns
        self.timestamps = np.asarray(timestamps, dtype=np.int64)
        n = len(self.timestamps)
        if types is None:
            types = np.zeros(n, dtype=np.int8)
        self.types = np.asarray(types, dtype=np.int8)
        # side-channel metadata (e.g. group keys) — row-aligned lists/arrays;
        # NOT propagated by mask/take/concat unless the producer re-attaches
        self.aux: Dict[str, object] = {}

    def __len__(self) -> int:
        return len(self.timestamps)

    @property
    def size(self) -> int:
        return len(self.timestamps)

    # per-row aux side channels that row selections must keep aligned
    _ROW_AUX = ("group_keys", "partition_keys")

    def _carry_group_keys(self, out: "EventBatch", sel) -> "EventBatch":
        for name in self._ROW_AUX:
            gk = self.aux.get(name)
            if gk is not None and len(gk) == len(self):
                if isinstance(sel, np.ndarray) and sel.dtype == bool:
                    out.aux[name] = [k for k, m in zip(gk, sel) if m]
                else:
                    out.aux[name] = [gk[int(i)] for i in sel]
        return out

    def mask(self, m: np.ndarray) -> "EventBatch":
        """Select rows where boolean mask is True."""
        out = EventBatch(
            self.stream_id,
            self.attribute_names,
            {k: v[m] for k, v in self.columns.items()},
            self.timestamps[m],
            self.types[m],
        )
        return self._carry_group_keys(out, m)

    def take(self, idx: np.ndarray) -> "EventBatch":
        out = EventBatch(
            self.stream_id,
            self.attribute_names,
            {k: v[idx] for k, v in self.columns.items()},
            self.timestamps[idx],
            self.types[idx],
        )
        return self._carry_group_keys(out, idx)

    def with_types(self, t: int) -> "EventBatch":
        return EventBatch(
            self.stream_id,
            self.attribute_names,
            dict(self.columns),
            self.timestamps,
            np.full(len(self), t, dtype=np.int8),
        )

    def only(self, *event_types: int) -> "EventBatch":
        m = np.isin(self.types, event_types)
        if m.all():
            return self
        return self.mask(m)

    def copy(self) -> "EventBatch":
        out = EventBatch(
            self.stream_id,
            list(self.attribute_names),
            {k: v.copy() for k, v in self.columns.items()},
            self.timestamps.copy(),
            self.types.copy(),
        )
        for name in self._ROW_AUX:
            a = self.aux.get(name)
            if a is not None:
                out.aux[name] = list(a)
        return out

    @staticmethod
    def concat(batches: List["EventBatch"]) -> "EventBatch":
        assert batches
        if len(batches) == 1:
            return batches[0]
        b0 = batches[0]
        out = EventBatch(
            b0.stream_id,
            b0.attribute_names,
            {
                k: np.concatenate([b.columns[k] for b in batches])
                for k in b0.attribute_names
            },
            np.concatenate([b.timestamps for b in batches]),
            np.concatenate([b.types for b in batches]),
        )
        for name in EventBatch._ROW_AUX:
            if all(
                b.aux.get(name) is not None and len(b.aux[name]) == len(b)
                for b in batches
            ):
                out.aux[name] = [k for b in batches for k in b.aux[name]]
        return out

    def __repr__(self):
        return f"EventBatch({self.stream_id}, n={len(self)})"


def empty_batch(definition: AbstractDefinition, stream_id: Optional[str] = None) -> EventBatch:
    cols = {
        a.name: np.empty(0, dtype=a.type.np_dtype) for a in definition.attributes
    }
    return EventBatch(
        stream_id or definition.id,
        definition.attribute_names,
        cols,
        np.empty(0, dtype=np.int64),
    )


def batch_from_rows(
    definition: AbstractDefinition,
    rows: List[Sequence],
    timestamps: Sequence[int],
    types: Optional[Sequence[int]] = None,
    stream_id: Optional[str] = None,
) -> EventBatch:
    """Build a columnar batch from row-major data (the converter analog —
    reference: event/stream/converter/*)."""
    n = len(rows)
    n_attrs = len(definition.attributes)
    for i, r in enumerate(rows):
        if len(r) != n_attrs:
            raise ValueError(
                f"event data {list(r)!r} has {len(r)} values but stream "
                f"'{definition.id}' expects {n_attrs} attributes"
            )
    cols: Dict[str, np.ndarray] = {}
    for j, attr in enumerate(definition.attributes):
        dt = attr.type.np_dtype
        if dt == np.dtype(object):
            arr = np.empty(n, dtype=object)
            for i in range(n):
                arr[i] = rows[i][j]
        else:
            arr = np.asarray([rows[i][j] for i in range(n)], dtype=dt) if n else np.empty(0, dtype=dt)
        cols[attr.name] = arr
    return EventBatch(
        stream_id or definition.id,
        definition.attribute_names,
        cols,
        np.asarray(timestamps, dtype=np.int64),
        np.asarray(types, dtype=np.int8) if types is not None else None,
    )


def batch_from_events(
    definition: AbstractDefinition, events: List[Event], stream_id: Optional[str] = None
) -> EventBatch:
    return batch_from_rows(
        definition,
        [e.data for e in events],
        [e.timestamp for e in events],
        [EXPIRED if e.is_expired else CURRENT for e in events],
        stream_id,
    )


def events_from_batch(batch: EventBatch) -> List[Event]:
    """Convert back to row-major Events for user callbacks/sinks.

    Columns unbox wholesale via ``ndarray.tolist()`` (one C call per
    column) instead of per-cell ``.item()``."""
    n = len(batch)
    if n == 0:
        return []
    names = batch.attribute_names
    lists = [batch.columns[nm].tolist() for nm in names]
    ts_list = batch.timestamps.tolist()
    expired = (batch.types == EXPIRED).tolist()
    out: List[Event] = []
    for i in range(n):
        e = Event.__new__(Event)
        e.timestamp = ts_list[i]
        e.data = [c[i] for c in lists]
        e.is_expired = expired[i]
        out.append(e)
    return out


def _unbox(v):
    """numpy scalar -> python scalar (keeps callback data plain)."""
    if isinstance(v, np.generic):
        return v.item()
    return v
