"""Named windows: ``define window W (...) <fn>(...) output <type> events``.

Re-design of the reference ``core/window/Window.java:65``: a shared
window processor owned by the app, fed by ``insert into W`` queries
(InsertIntoWindowCallback analog), publishing its CURRENT/EXPIRED flow to
a junction that ``from W`` queries subscribe to, and probe-able by joins
and on-demand queries (the FindableProcessor contract).
"""

from __future__ import annotations

from typing import Dict, Optional

from siddhi_tpu.core import event as ev
from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.query import OutputCallback


class NamedWindowRuntime:
    def __init__(self, definition, window, junction, app_context):
        self.definition = definition
        self.window_id = definition.id
        self.window = window
        self.junction = junction
        self.app_context = app_context
        # reference default: ALL events (WindowDefinition.java:40 —
        # queries reading the window see CURRENT + EXPIRED so windowed
        # aggregates can retract on expiry)
        self.output_event_type = definition.output_event_type or "all"

    # -- ingestion (insert into W) ------------------------------------------

    def add(self, batch: EventBatch, now: int):
        wout = self.window.process(batch, now)
        self._publish(wout)

    def _publish(self, wout: Optional[EventBatch]):
        if wout is None or len(wout) == 0:
            return
        if self.output_event_type == "current":
            out = wout.only(ev.CURRENT)
        elif self.output_event_type == "expired":
            out = wout.only(ev.EXPIRED)
        else:
            out = wout.only(ev.CURRENT, ev.EXPIRED)
        if len(out) == 0:
            return
        out.stream_id = self.junction.stream_id
        self.junction.send(out)

    # -- findable contract (joins / on-demand probes) -----------------------

    def buffered(self) -> Optional[EventBatch]:
        return self.window.buffered()

    def rows_batch(self) -> Optional[EventBatch]:
        return self.window.buffered()

    # -- scheduler task contract -------------------------------------------

    def next_wakeup(self) -> Optional[int]:
        return self.window.next_wakeup()

    def fire(self, now: int):
        self._publish(self.window.on_time(now))

    # -- snapshot contract --------------------------------------------------

    def snapshot(self) -> Dict:
        return self.window.snapshot()

    def restore(self, state: Dict):
        self.window.restore(state)


class InsertIntoWindowCallback(OutputCallback):
    """Routes query output into a named window (reference:
    InsertIntoWindowCallback.java).  Output must cover the window's
    schema by name (validated at plan time, like the table path)."""

    def __init__(
        self,
        window_runtime: NamedWindowRuntime,
        event_type: str,
        output_names: Optional[list] = None,
    ):
        self.window_runtime = window_runtime
        self.event_type = event_type
        if output_names is not None:
            from siddhi_tpu.core.exceptions import SiddhiAppCreationError

            missing = [
                a.name
                for a in window_runtime.definition.attributes
                if a.name not in output_names
            ]
            if missing:
                raise SiddhiAppCreationError(
                    f"insert into window '{window_runtime.window_id}': output "
                    f"is missing window attribute(s) {missing}"
                )

    def send(self, batch: EventBatch, now: int):
        if self.event_type == "current":
            out = batch.only(ev.CURRENT)
        elif self.event_type == "expired":
            out = batch.only(ev.EXPIRED)
        else:
            out = batch.only(ev.CURRENT, ev.EXPIRED)
        if len(out) == 0:
            return
        wdef = self.window_runtime.definition
        if out.attribute_names != wdef.attribute_names:
            out = EventBatch(
                self.window_runtime.window_id,
                wdef.attribute_names,
                {nm: out.columns[nm] for nm in wdef.attribute_names},
                out.timestamps,
                out.types,
            )
        out = out.with_types(ev.CURRENT)
        self.window_runtime.add(out, now)
