"""Async emit pipeline: count-gated, double-buffered device→host emits.

The product path's dominant cost on the tunneled platform is the
device→host fetch of jit outputs (~57 ms sticky RTT per transfer —
bench.py).  This module holds the pieces every device runtime shares:

- ``EmitStats``: per-runtime transfer counters surfaced through
  ``util/statistics.py`` (``emitTransfers`` / ``deferredBatches`` /
  ``zeroMatchSkips`` / ``maxPendingDepth``).
- ``EmitQueue``: a bounded pending-emit queue.  Each entry is one
  junction batch whose match outputs are still resident on the device;
  when the queue reaches its configured depth (``emit.depth`` on
  ``@app:execution``), ALL queued outputs are drained with one
  coalesced transfer.  Depth 1 (the default) drains right after each
  batch — emit timing is then identical to the synchronous path while
  still benefiting from count-gating and the per-batch coalesced fetch.
- ``fetch_coalesced``: groups device arrays by (dtype, trailing shape),
  concatenates each group on device along axis 0, fetches everything in
  a single ``jax.device_get``, and splits back host-side — one transfer
  round trip instead of one per column per batch.

Exactness contract: entries drain strictly FIFO and each entry
materializes into exactly the EventBatch the synchronous path would
have emitted, so callback content AND order are bit-identical; the
runtimes insert explicit ``drain()`` barriers wherever host code could
observe emit timing (snapshot/restore, timer fires, rate-limiter
decisions, pull queries, shutdown, debugger).
"""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from .exceptions import TransferFaultError

log = logging.getLogger("siddhi_tpu.emit")


class EmitStats:
    """Transfer counters for one device runtime (host-side ints; one
    increment per batch, matching the micro-batched tracker style of
    util/statistics.py)."""

    __slots__ = ("emit_transfers", "deferred_batches", "zero_match_skips",
                 "max_pending_depth", "auto_depth")

    def __init__(self):
        self.emit_transfers = 0
        self.deferred_batches = 0
        self.zero_match_skips = 0
        self.max_pending_depth = 0
        # effective depth the 'auto' controller is currently running at
        # (0 = static emit.depth, no controller)
        self.auto_depth = 0

    def note_depth(self, depth: int):
        if depth > self.max_pending_depth:
            self.max_pending_depth = depth

    def as_dict(self) -> dict:
        return {
            "emitTransfers": self.emit_transfers,
            "deferredBatches": self.deferred_batches,
            "zeroMatchSkips": self.zero_match_skips,
            "maxPendingDepth": self.max_pending_depth,
            "autoEffectiveDepth": self.auto_depth,
        }


def _is_device_array(a) -> bool:
    return not isinstance(a, (np.ndarray, np.generic, int, float, bool))


def fetch_coalesced(arrays: Sequence) -> List[np.ndarray]:
    """One device→host round trip for a list of arrays.

    Device arrays are grouped by (dtype, trailing shape), each group is
    concatenated ON DEVICE along axis 0, the concatenated buffers are
    fetched with a single ``jax.device_get``, and the result is split
    back host-side in input order.  Host numpy arrays pass through
    untouched.  Counts as ONE emit transfer.
    """
    if not arrays:
        return []
    out: List[Optional[np.ndarray]] = [None] * len(arrays)
    groups: dict = {}  # (dtype, trailing shape) -> [index]
    for i, a in enumerate(arrays):
        if not _is_device_array(a):
            out[i] = np.asarray(a)
            continue
        shape = getattr(a, "shape", ())
        if len(shape) == 0:
            key = ("scalar", i)  # 0-d: no concat axis; fetch alone
        else:
            key = (str(a.dtype), tuple(shape[1:]))
        groups.setdefault(key, []).append(i)
    if not groups:
        return [a for a in out]  # all host already
    import jax
    import jax.numpy as jnp

    keys = list(groups)
    staged = []
    for key in keys:
        idxs = groups[key]
        if len(idxs) == 1:
            staged.append(arrays[idxs[0]])
        else:
            try:
                staged.append(jnp.concatenate(
                    [arrays[i] for i in idxs], axis=0))
            except Exception as e:
                # heterogeneous placements (e.g. differently-sharded
                # chunks) can refuse to concatenate — fall back to
                # fetching the group members individually in the same
                # device_get call
                log.debug("fetch_coalesced: device concat refused for "
                          "group %s (%s); fetching %d members "
                          "individually", key, e, len(idxs))
                staged.append(None)
    fetch = []
    for key, s in zip(keys, staged):
        if s is None:
            fetch.extend(arrays[i] for i in groups[key])
        else:
            fetch.append(s)
    host = jax.device_get(fetch)
    pos = 0
    for key, s in zip(keys, staged):
        idxs = groups[key]
        if s is None:
            for i in idxs:
                out[i] = host[pos]
                pos += 1
        elif len(idxs) == 1:
            out[idxs[0]] = host[pos]
            pos += 1
        else:
            cat = host[pos]
            pos += 1
            off = 0
            for i in idxs:
                n = arrays[i].shape[0]
                out[i] = cat[off:off + n]
                off += n
    return out  # type: ignore[return-value]


class PendingEmit:
    """One deferred junction batch: device refs + a materializer that
    turns the fetched host arrays into the exact synchronous emit."""

    __slots__ = ("arrays", "materialize", "trace")

    def __init__(self, arrays: Sequence, materialize: Callable, trace=None):
        # materialize(host_arrays) -> None (runs the emit callback);
        # trace is the batch's sampled cycle token (observability/
        # trace.py CycleToken, or None) — the drain stamps its emit span
        self.arrays = list(arrays)
        self.materialize = materialize
        self.trace = trace


class EmitDepthController:
    """Adaptive queue depth for ``emit.depth='auto'``.

    The right static depth is "how many junction batches arrive during
    one device→host drain round trip": deeper coalesces more transfers
    per RTT, but anything past that only delays callbacks.  Both inputs
    drift at runtime (tunnel RTT is load-dependent, batch cadence is the
    workload's), so the controller keeps decaying averages of the
    inter-push gap (sampled at ``note_push``) and the drain fetch time
    (``note_drain``) and re-derives

        effective_depth = clamp(ceil(rtt_ema / gap_ema), 1, AUTO_DEPTH_MAX)

    after every sample.  The EMA weight makes old samples decay with a
    ~1/ALPHA-sample window, so a match-rate or RTT shift re-converges
    within a few drains.  AUTO_DEPTH_MAX bounds the queue exactly like a
    hand-written ``emit.depth`` would — auto can never grow the pending
    window past it.
    """

    AUTO_DEPTH_MAX = 32
    ALPHA = 0.2  # decaying-window weight (newest sample's share)

    __slots__ = ("_gap_ema", "_rtt_ema", "_last_push", "effective_depth")

    def __init__(self):
        self._gap_ema: Optional[float] = None
        self._rtt_ema: Optional[float] = None
        self._last_push: Optional[float] = None
        self.effective_depth = 1

    def _ema(self, old: Optional[float], sample: float) -> float:
        if old is None:
            return sample
        return old + self.ALPHA * (sample - old)

    def note_push(self, t: Optional[float] = None):
        """One queued batch; ``t`` (monotonic seconds) is injectable
        for tests."""
        if t is None:
            t = time.monotonic()
        if self._last_push is not None:
            self._gap_ema = self._ema(self._gap_ema, t - self._last_push)
        self._last_push = t
        self._recompute()

    def note_drain(self, seconds: float):
        """Observed fetch wall time of one coalesced drain."""
        self._rtt_ema = self._ema(self._rtt_ema, seconds)
        self._recompute()

    def _recompute(self):
        if not self._gap_ema or self._rtt_ema is None:
            return  # no cadence yet (first batch) — stay at current depth
        import math

        depth = math.ceil(self._rtt_ema / self._gap_ema)
        self.effective_depth = max(1, min(depth, self.AUTO_DEPTH_MAX))


class EmitQueue:
    """Bounded per-runtime pending-emit queue (FIFO, depth >= 1).

    ``faults`` (a ``util.faults.FaultInjector`` or None) arms the
    ``emit.drain`` injection site and supplies the transfer retry knobs;
    ``on_fault(exc)`` is the owning runtime's isolation hook — a drain or
    materialize failure is routed there (fault stream / error log /
    exception listeners) instead of propagating and killing the runtime.
    """

    def __init__(self, depth=1, stats: Optional[EmitStats] = None,
                 faults=None, on_fault: Optional[Callable] = None):
        # depth 'auto': bounded self-tuning — a controller re-derives
        # the effective depth from observed drain RTT vs push cadence
        # (never past its AUTO_DEPTH_MAX bound).  The debugger disables
        # the controller when it forces depth 1.
        self.controller: Optional[EmitDepthController] = None
        if depth == "auto":
            self.controller = EmitDepthController()
            depth = 1
        self.depth = max(1, int(depth))
        self.stats = stats or EmitStats()
        self.faults = faults
        self.on_fault = on_fault
        self._entries: List[PendingEmit] = []

    def __len__(self) -> int:
        return len(self._entries)

    def push(self, entry: PendingEmit):
        if self.controller is not None:
            self.controller.note_push()
            self.depth = self.controller.effective_depth
            self.stats.auto_depth = self.depth
        self._entries.append(entry)
        self.stats.note_depth(len(self._entries))
        if len(self._entries) >= self.depth:
            self.drain()
        else:
            self.stats.deferred_batches += 1

    def skip(self):
        """Record a zero-match batch that transferred nothing."""
        self.stats.zero_match_skips += 1

    def _fetch(self, arrays: Sequence) -> List[np.ndarray]:
        """``fetch_coalesced`` behind the ``emit.drain`` injection site,
        with bounded retry-with-backoff on transient transfer faults
        (sticky device loss and other errors propagate immediately)."""
        fi = self.faults
        if fi is None:
            return fetch_coalesced(arrays)
        attempts = fi.transfer_retry_attempts
        backoff = None
        attempt = 0
        while True:
            try:
                fi.check("emit.drain")
                host = fetch_coalesced(arrays)
                if attempt:
                    fi.stats.drains_recovered += 1
                return host
            except TransferFaultError:
                if attempt >= attempts:
                    raise
                attempt += 1
                fi.stats.transfer_retries += 1
                if backoff is None:
                    from ..transport.retry import BackoffRetryCounter

                    backoff = BackoffRetryCounter(
                        scale=fi.transfer_retry_scale)
                wait_s = backoff.get_time_interval_ms() / 1000.0
                backoff.increment()
                log.warning("emit drain: transient transfer fault; "
                            "retry %d/%d in %.3fs", attempt, attempts,
                            wait_s)
                if wait_s > 0:
                    time.sleep(wait_s)

    def drain(self):
        """Flush barrier: materialize every pending entry in FIFO order
        with one coalesced transfer.  Re-entrant pushes from emit
        callbacks land in a fresh list and drain after the current
        entries — the same order the synchronous path produces.

        Fault isolation: a failed fetch drops only THIS drain's entries
        (counted in ``FaultStats.drains_failed`` and routed through
        ``on_fault``); a failing materializer drops only its own entry
        (``callback_faults_isolated``).  Either way the queue stays
        usable and the runtime stays alive."""
        while self._entries:
            entries, self._entries = self._entries, []
            arrays: List = []
            spans: List[int] = []
            for e in entries:
                spans.append(len(e.arrays))
                arrays.extend(e.arrays)
            had_device = any(_is_device_array(a) for a in arrays)
            t0 = (time.monotonic()
                  if self.controller is not None and had_device else None)
            # emit-span clock for sampled cycle tokens: one coalesced
            # fetch serves every entry in this round, so they share the
            # fetch start and each stamps its own materialize end
            t_fetch = time.perf_counter()
            try:
                host = self._fetch(arrays)
            except Exception as err:
                fi = self.faults
                if fi is not None:
                    fi.stats.drains_failed += 1
                log.error("emit drain failed; dropping %d pending "
                          "batch(es): %s", len(entries), err)
                for e in entries:
                    if e.trace is not None:
                        e.trace.aborted("emit")
                if self.on_fault is not None:
                    self.on_fault(err)
                continue
            if t0 is not None:
                self.controller.note_drain(time.monotonic() - t0)
            if had_device:
                self.stats.emit_transfers += 1
            off = 0
            for e, n in zip(entries, spans):
                seg = host[off:off + n]
                off += n
                try:
                    e.materialize(seg)
                except Exception as err:
                    fi = self.faults
                    if fi is not None:
                        fi.stats.callback_faults_isolated += 1
                    log.error("emit materialize failed; dropping one "
                              "pending batch: %s", err)
                    if e.trace is not None:
                        e.trace.aborted("emit")
                    if self.on_fault is not None:
                        self.on_fault(err)
                    continue
                if e.trace is not None:
                    e.trace.emitted(t_fetch)
