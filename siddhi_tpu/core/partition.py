"""Partitions: ``partition with (expr of Stream, ...) begin ... end``.

Re-design of the reference ``core/partition/``
(PartitionRuntimeImpl.java:75, PartitionStreamReceiver.java:44,
ValuePartitionExecutor.java:34, RangePartitionExecutor.java): instead of
ThreadLocal flow-routing into lazily cloned per-key state holders, a
partitioned batch is key-grouped **vectorized** (one executor evaluation
per batch) and each key's sub-batch is fed into that key's *instance* —
a lazily planned copy of the inner queries whose junction namespace
overlays per-key local junctions (partitioned inputs + ``#inner``
streams) on the app's global ones.

The 1M-key hot path (pattern queries over partitioned streams) does not
use these instances — it compiles to the dense NFA engine with a
partition axis (ops/dense_nfa.py); these instances are the general-
purpose semantics-complete path, mirroring the reference's design.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core import event as ev
from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.core.stream import StreamJunction
from siddhi_tpu.planner.expr import CompiledExpression, N_KEY, TS_KEY
from siddhi_tpu.query_api import (
    Partition,
    RangePartitionType,
    StreamDefinition,
    ValuePartitionType,
)
from siddhi_tpu.query_api.annotation import find_annotation


def _batch_env(batch: EventBatch) -> Dict:
    env = dict(batch.columns)
    env[TS_KEY] = batch.timestamps
    env[N_KEY] = len(batch)
    return env


class ValuePartitionExecutor:
    """Key = expression value (reference: ValuePartitionExecutor.java:34)."""

    def __init__(self, compiled: CompiledExpression):
        self.compiled = compiled

    def keys(self, batch: EventBatch) -> List:
        return self.keys_array(batch).tolist()

    def keys_array(self, batch: EventBatch) -> np.ndarray:
        """Raw key column (native dtype, no per-element boxing) — the
        dense path interns straight from this."""
        return np.broadcast_to(
            np.asarray(self.compiled.fn(_batch_env(batch))), (len(batch),))


class RangePartitionExecutor:
    """Key = label of the first matching range condition; non-matching
    rows get None and are dropped (reference: RangePartitionExecutor)."""

    def __init__(self, ranges: List[Tuple[CompiledExpression, str]]):
        self.ranges = ranges

    def keys(self, batch: EventBatch) -> List:
        return self.keys_array(batch).tolist()

    def keys_array(self, batch: EventBatch) -> np.ndarray:
        n = len(batch)
        env = _batch_env(batch)
        out = np.full(n, None, dtype=object)
        assigned = np.zeros(n, dtype=bool)
        for cond, label in self.ranges:
            m = np.broadcast_to(np.asarray(cond.fn(env)), (n,)) & ~assigned
            out[m] = label
            assigned |= m
        return out


class _ScopedScheduler:
    """Records an instance's scheduler registrations so a purged/template
    instance can be fully unregistered (no ghost window ticks)."""

    def __init__(self, real):
        self._real = real
        self._items: List[Tuple[str, object]] = []

    def register_window(self, query_runtime, window):
        self._real.register_window(query_runtime, window)
        self._items.append(("window", (query_runtime, window)))

    def register_task(self, task):
        self._real.register_task(task)
        self._items.append(("task", task))

    def unregister_all(self):
        for kind, item in self._items:
            if kind == "window":
                self._real.unregister_window(*item)
            else:
                self._real.unregister_task(item)
        self._items = []


class _InstancePlanner:
    """Planner facade for one partition-key instance: local junctions for
    partitioned inputs and ``#inner`` streams overlay the app's global
    namespace; everything else delegates."""

    # per-key clones must use the host pattern engine — the dense TPU
    # form of a partitioned pattern is ONE engine with interned keys,
    # wired by PartitionRuntime, not one engine per instance
    in_partition_instance = True

    def __init__(self, app_planner, partitioned_defs: Dict[str, StreamDefinition], key):
        self._app = app_planner
        self.key = key
        self._scoped_scheduler = _ScopedScheduler(app_planner.scheduler)
        self.local_junctions: Dict[str, StreamJunction] = {}
        self.local_definitions: Dict[str, StreamDefinition] = {}
        self.query_runtimes: Dict[str, object] = {}
        for sid, definition in partitioned_defs.items():
            j = StreamJunction(definition, app_planner.app_context)
            j.start()
            self.local_junctions[sid] = j
            self.local_definitions[sid] = definition

    # -- delegated surface --------------------------------------------------

    @property
    def functions(self):
        return getattr(self._app, "functions", {})

    @property
    def app_context(self):
        return self._app.app_context

    @property
    def extensions(self):
        return self._app.extensions

    @property
    def scheduler(self):
        return self._scoped_scheduler

    @property
    def tables(self):
        return self._app.tables

    @property
    def named_windows(self):
        return self._app.named_windows

    def table_resolver(self, table_name: str, obj: bool = False):
        return self._app.table_resolver(table_name, obj=obj)

    # -- junction namespace -------------------------------------------------

    @property
    def junctions(self):
        # input namespace is local-only: queries inside a partition may only
        # read partitioned or #inner streams (global reads would make every
        # key instance a duplicate subscriber)
        return self.local_junctions

    @staticmethod
    def _key(stream_id: str, is_inner: bool = False, is_fault: bool = False) -> str:
        if is_inner:
            return "#" + stream_id
        if is_fault:
            return "!" + stream_id
        return stream_id

    def resolve_stream_definition(self, s) -> StreamDefinition:
        key = self._key(s.stream_id, getattr(s, "is_inner", False), getattr(s, "is_fault", False))
        if key in self.local_definitions:
            return self.local_definitions[key]
        return self._app.resolve_stream_definition(s)

    def junction_for_input(self, s) -> StreamJunction:
        key = self._key(s.stream_id, s.is_inner, s.is_fault)
        if key in self.local_junctions:
            return self.local_junctions[key]
        raise SiddhiAppCreationError(
            f"stream '{key}': queries inside a partition can only read "
            "the partitioned streams or '#inner' streams"
        )

    def get_or_create_junction(
        self, stream_id: str, fallback_def: StreamDefinition, is_inner=False, is_fault=False
    ) -> StreamJunction:
        if is_inner:
            key = "#" + stream_id
            if key not in self.local_junctions:
                d = StreamDefinition(id=stream_id, attributes=list(fallback_def.attributes))
                j = StreamJunction(d, self._app.app_context)
                j.start()
                self.local_junctions[key] = j
                self.local_definitions[key] = d
            return self.local_junctions[key]
        return self._app.get_or_create_junction(stream_id, fallback_def, is_fault=is_fault)


class PartitionInstance:
    """One key's planned copy of the inner queries."""

    def __init__(self, key, partition: Partition, app_planner, partitioned_defs):
        from siddhi_tpu.planner.query_planner import QueryPlanner

        self.key = key
        self.planner = _InstancePlanner(app_planner, partitioned_defs, key)
        qp = QueryPlanner(self.planner)
        self.query_runtimes: Dict[str, object] = {}
        for qi, q in enumerate(partition.queries):
            qr = qp.plan(q, qi)
            self.query_runtimes[qr.name] = qr
        self.last_used: int = 0

    def send(self, stream_id: str, batch: EventBatch, now: int):
        self.last_used = now
        self.planner.local_junctions[stream_id].send(batch)

    def close(self):
        """Unregister every scheduler hook this instance planted."""
        self.planner._scoped_scheduler.unregister_all()
        for j in self.planner.local_junctions.values():
            j.stop()


def _pattern_stream_ids(st) -> List[str]:
    """Junction keys of every source stream in a pattern input (AST walk
    — no planning side effects)."""
    from siddhi_tpu.query_api import (
        CountStateElement,
        LogicalStateElement,
        NextStateElement,
        EveryStateElement,
        StreamStateElement,
    )

    out: List[str] = []

    def walk(el):
        if isinstance(el, NextStateElement):
            walk(el.element)
            walk(el.next)
        elif isinstance(el, EveryStateElement):
            walk(el.element)
        elif isinstance(el, CountStateElement):
            walk(el.stream_state)
        elif isinstance(el, LogicalStateElement):
            walk(el.element1)
            walk(el.element2)
        elif isinstance(el, StreamStateElement):
            s = el.stream
            prefix = "#" if s.is_inner else ("!" if s.is_fault else "")
            key = prefix + s.stream_id
            if key not in out:
                out.append(key)

    walk(st.state)
    return out


class DensePartitionReceiver:
    """Subscriber on a partitioned stream's global junction for the
    TPU form: evaluates the partition executor once per batch and
    advances every device-lowered runtime that reads this stream — no
    per-key instances, no per-key routing.  Runtimes are either dense
    NFA pattern runtimes (which intern keys to engine rows themselves)
    or partitioned device-query runtimes (which take the raw key
    column); both kinds advance in query plan order."""

    def __init__(self, stream_id: str, executor, runtimes: List):
        self.stream_id = stream_id
        self.executor = executor
        self.runtimes = runtimes

    def receive(self, batch: EventBatch):
        cur = batch.only(ev.CURRENT)
        if len(cur) == 0:
            return
        keys = self.executor.keys_array(cur)
        if keys.dtype == object:  # range partitions drop unmatched (None)
            keep = np.not_equal(keys, None)
            if not keep.all():
                cur = cur.mask(keep)
                if len(cur) == 0:
                    return
                keys = keys[keep]
            # range labels are strings: re-infer a native '<U' dtype so
            # the vectorized intern index applies
            keys = np.asarray(keys.tolist())
        for rt in self.runtimes:
            if hasattr(rt, "intern_keys"):  # dense NFA pattern runtime
                part = rt.intern_keys(keys)
                rt.process_stream_batch(self.stream_id, cur, part=part,
                                        keys=keys)
            else:  # partitioned device-query runtime
                rt.process_stream_batch(cur, keys=keys)


class PartitionStreamReceiver:
    """Subscriber on a partitioned stream's global junction: evaluates
    the partition executor once per batch, groups rows by key, and routes
    each sub-batch into that key's instance (reference:
    PartitionStreamReceiver.receive:82-118)."""

    def __init__(self, partition_runtime: "PartitionRuntime", stream_id: str, executor):
        self.partition_runtime = partition_runtime
        self.stream_id = stream_id
        self.executor = executor

    def receive(self, batch: EventBatch):
        pr = self.partition_runtime
        now = pr.app_context.timestamp_generator.current_time()
        keys = self.executor.keys(batch)
        # order-preserving group-by-key
        groups: Dict = {}
        for i, k in enumerate(keys):
            if k is None:
                continue  # range partitions drop unmatched rows
            groups.setdefault(k, []).append(i)
        for k, idx in groups.items():
            inst = pr.instance_for(k)
            sub = batch if len(idx) == len(batch) else batch.take(np.asarray(idx))
            inst.send(self.stream_id, sub, now)


class PartitionRuntime:
    """All instances of one ``partition ... begin ... end`` block
    (reference: PartitionRuntimeImpl.java:75)."""

    def __init__(self, partition: Partition, app_planner, index: int):
        self.partition = partition
        self.app_planner = app_planner
        self.app_context = app_planner.app_context
        self.name = f"partition_{index}"
        self.instances: Dict[object, PartitionInstance] = {}

        self.partitioned_defs: Dict[str, StreamDefinition] = {}
        self._executors: Dict[str, object] = {}
        from siddhi_tpu.planner.expr import ExpressionCompiler
        from siddhi_tpu.planner.query_planner import scope_for_definition

        for pt in partition.partition_types:
            sid = pt.stream_id
            if sid not in app_planner.definitions:
                raise SiddhiAppCreationError(
                    f"{self.name}: partitioned stream '{sid}' is not defined"
                )
            definition = app_planner.definitions[sid]
            self.partitioned_defs[sid] = definition
            compiler = ExpressionCompiler(
                scope_for_definition(definition, sid),
                functions=getattr(app_planner, "functions", None),
                table_resolver=app_planner.table_resolver,
            )
            if isinstance(pt, ValuePartitionType):
                ex = ValuePartitionExecutor(compiler.compile(pt.expression))
            elif isinstance(pt, RangePartitionType):
                ex = RangePartitionExecutor(
                    [(compiler.compile(c), label) for c, label in pt.ranges]
                )
            else:
                raise SiddhiAppCreationError(f"unknown partition type {pt!r}")
            self._executors[sid] = ex

        # @app:execution('tpu'): a partition whose body is all
        # dense-eligible pattern queries lowers to ONE engine per query
        # with the partition key interned onto the engine's partition
        # axis — per-key state rows in device memory instead of per-key
        # Python instances (the 1M-key hot path, BASELINE.json configs)
        self.dense_query_runtimes: Dict[str, object] = {}
        self.is_dense = False
        if app_planner.app_context.execution_mode == "tpu":
            import logging

            try:
                self._plan_dense(partition, app_planner)
                self.is_dense = True
                logging.getLogger("siddhi_tpu").info(
                    "%s: lowered to the dense TPU path (%d queries, "
                    "%d key rows)", self.name,
                    len(self.dense_query_runtimes),
                    app_planner.app_context.tpu_partitions)
            except SiddhiAppCreationError as e:
                self.dense_query_runtimes = {}
                # WARN: execution('tpu') was requested and this
                # partition is getting per-key host instances
                logging.getLogger("siddhi_tpu").warning(
                    "%s: dense TPU path unavailable (%s); using per-key "
                    "instances", self.name, e)
                sm = app_planner.app_context.statistics_manager
                if sm is not None:
                    sm.record_device_fallback(
                        self.name, f"dense partition: {e}")

        if not self.is_dense:
            for sid, ex in self._executors.items():
                app_planner.junctions[sid].subscribe(
                    PartitionStreamReceiver(self, sid, ex)
                )
            # plan an inert template instance eagerly: creates the global
            # output junctions (so downstream queries/callbacks can bind at
            # build time) and surfaces plan errors at app creation instead
            # of first event
            template = PartitionInstance(
                "__template__", partition, app_planner, self.partitioned_defs
            )
            template.close()  # only its planning side effects are needed

        # @purge(enable='true', interval='..', idle.period='..')
        self._purge_interval_ms: Optional[int] = None
        self._purge_idle_ms: Optional[int] = None
        self._next_purge: Optional[int] = None
        purge = find_annotation(partition.annotations, "purge")
        if purge is not None and (purge.element("enable") or "false").lower() == "true":
            from siddhi_tpu.compiler.parser import parse_time_string

            self._purge_interval_ms = parse_time_string(purge.element("interval") or "1 min")
            self._purge_idle_ms = parse_time_string(purge.element("idle.period") or "15 min")
            app_planner.scheduler.register_task(self)

    def _plan_dense(self, partition: Partition, app_planner):
        """Lower every inner query to a device engine or raise (caller
        falls back to per-key instances wholesale — mixed mode would
        split one partition's semantics across two engines).  Pattern
        queries lower to the dense NFA engine; general single-stream
        queries (filter/window/group-by) lower to the device query
        engine with the partition key composed into the group axis."""
        from siddhi_tpu.planner.query_planner import QueryPlanner
        from siddhi_tpu.query_api import (
            InsertIntoStream,
            Query,
            ReturnStream,
            SingleInputStream,
            StateInputStream,
        )
        from siddhi_tpu.query_api.annotation import find_annotation as _find

        # cheap AST-level validation of EVERY query before planning any,
        # so a late ineligibility doesn't leak side effects of earlier
        # fully-planned queries
        for q in partition.queries:
            if not isinstance(q, Query):
                raise SiddhiAppCreationError("nested element not a query")
            st = q.input_stream
            out = q.output_stream
            if isinstance(out, InsertIntoStream) and out.is_inner:
                raise SiddhiAppCreationError(
                    "'insert into #inner' needs per-key instances")
            elif not isinstance(out, (InsertIntoStream, ReturnStream)) and out is not None:
                raise SiddhiAppCreationError(
                    "table/window outputs need per-key instances")
            if isinstance(st, StateInputStream):
                for sid in _pattern_stream_ids(st):
                    if sid not in self.partitioned_defs:
                        raise SiddhiAppCreationError(
                            f"pattern input '{sid}' is not a partitioned stream")
            elif isinstance(st, SingleInputStream):
                if st.is_inner or st.is_fault:
                    raise SiddhiAppCreationError(
                        "inner/fault stream inputs need per-key instances")
                if st.stream_id not in self.partitioned_defs:
                    raise SiddhiAppCreationError(
                        f"input '{st.stream_id}' is not a partitioned stream")
            else:
                raise SiddhiAppCreationError(
                    "join queries inside partitions need per-key instances")

        qp = QueryPlanner(app_planner)
        planned = []  # (name, qr, runtime)
        try:
            for qi, q in enumerate(partition.queries):
                info = _find(q.annotations, "info")
                name = (info.element("name") if info else None) or f"{self.name}_q{qi}"
                if isinstance(q.input_stream, StateInputStream):
                    qr = qp._plan_dense_state(
                        q, name, q.input_stream,
                        n_partitions=app_planner.app_context.tpu_partitions,
                        subscribe=False,
                    )
                    planned.append((name, qr, qr.pattern_processor))
                else:
                    qr = qp._plan_device_single(
                        q, name, q.input_stream,
                        partition_mode=True, subscribe=False,
                    )
                    planned.append((name, qr, qr.device_runtime))
        except SiddhiAppCreationError:
            # unwind scheduler tasks of already-planned siblings before
            # the wholesale fallback to per-key instances
            for _n, qr, _r in planned:
                for attr in ("_rate_task", "_dense_timer_task"):
                    task = getattr(qr, attr, None)
                    if task is not None:
                        app_planner.scheduler.unregister_task(task)
            raise
        # all queries lowered — wire key-routed receivers
        for name, qr, runtime in planned:
            self.dense_query_runtimes[name] = qr
        for sid, ex in self._executors.items():
            runtimes = [
                r for _n, _qr, r in planned
                if (sid in r.engine.stream_keys
                    if hasattr(r, "intern_keys")
                    else r.engine.stream_id == sid)
            ]
            if runtimes:
                app_planner.junctions[sid].subscribe(
                    DensePartitionReceiver(sid, ex, runtimes)
                )

    def query_lowering(self) -> Dict[str, str]:
        """Engine placement of every inner query (see
        AppRuntime.lowering): dense-lowered bodies report per query;
        per-key instance bodies are host by construction."""
        if self.is_dense:
            return {
                n: getattr(qr, "lowered_to", "host")
                for n, qr in self.dense_query_runtimes.items()
            }
        out = {}
        for qi, q in enumerate(self.partition.queries):
            info = find_annotation(getattr(q, "annotations", []), "info")
            n = (info.element("name") if info else None) or f"{self.name}_q{qi}"
            out[n] = "host"
        return out

    def instance_for(self, key) -> PartitionInstance:
        inst = self.instances.get(key)
        if inst is None:
            inst = PartitionInstance(
                key, self.partition, self.app_planner, self.partitioned_defs
            )
            self.instances[key] = inst
        return inst

    # -- idle-key purging (scheduler task) ----------------------------------

    def next_wakeup(self) -> Optional[int]:
        return self._next_purge

    def on_start(self, now: int):
        if self._purge_interval_ms is not None:
            self._next_purge = now + self._purge_interval_ms

    def fire(self, now: int):
        while self._next_purge is not None and self._next_purge <= now:
            self._next_purge += self._purge_interval_ms
        if self.is_dense:
            # reclaim idle key rows of the shared engines (the dense
            # analog of dropping idle PartitionInstances)
            for qr in self.dense_query_runtimes.values():
                rt = (getattr(qr, "pattern_processor", None)
                      or getattr(qr, "device_runtime", None))
                rt.purge_idle(now, self._purge_idle_ms)
            return
        dead = [
            k
            for k, inst in self.instances.items()
            if now - inst.last_used >= self._purge_idle_ms
        ]
        for k in dead:
            self.instances.pop(k).close()

    # -- snapshot contract --------------------------------------------------

    def snapshot(self) -> Dict:
        if self.is_dense:
            return {
                "__dense__": {
                    qname: qr.snapshot_state()
                    for qname, qr in self.dense_query_runtimes.items()
                }
            }
        out: Dict = {}
        for k, inst in self.instances.items():
            qstates: Dict = {}
            for qname, qr in inst.query_runtimes.items():
                if hasattr(qr, "snapshot_state"):
                    qstates[qname] = qr.snapshot_state()
            out[k] = qstates
        return out

    def restore(self, state: Dict):
        if self.is_dense:
            dense = state.get("__dense__", {})
            for qname, qs in dense.items():
                qr = self.dense_query_runtimes.get(qname)
                if qr is not None:
                    qr.restore_state(qs)
            return
        for inst in self.instances.values():
            inst.close()
        self.instances.clear()
        import time as _time

        now = int(_time.time() * 1000)
        for k, qstates in state.items():
            inst = self.instance_for(k)
            # fresh instances must not look idle to the purge task
            inst.last_used = now
            for qname, qs in qstates.items():
                qr = inst.query_runtimes.get(qname)
                if qr is not None and hasattr(qr, "restore_state"):
                    qr.restore_state(qs)
