"""Core runtime: the TPU-native engine.

Re-design of the reference ``siddhi-core`` (SURVEY.md §1 L3): instead of
pooled linked-list event chunks walked by per-event virtual calls, events
move as columnar micro-batches (numpy on host, jax arrays on device), and
each query compiles to a step function over those batches.
"""
