"""Query runtime: receiver -> processor chain -> selector -> rate limiter
-> output callback.

Re-design of the reference ``core/query/`` (QueryRuntimeImpl.java:43,
ProcessStreamReceiver.java:44, FilterProcessor.java:32,
QuerySelector.java:44): operators transform columnar batches instead of
walking pooled event chunks, and per-group aggregation is computed with
segmented vectorized runs rather than per-event executor calls.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from siddhi_tpu.core import event as ev
from siddhi_tpu.core.event import Event, EventBatch, events_from_batch
from siddhi_tpu.core.exceptions import (
    SiddhiAppCreationError,
    SiddhiAppRuntimeError,
)
from siddhi_tpu.core.stream import QueryCallback, StreamJunction
from siddhi_tpu.ops.aggregators import AggExecutor
from siddhi_tpu.planner.expr import CompiledExpression, N_KEY, TS_KEY
from siddhi_tpu.query_api import AttrType


def build_env(batch: EventBatch, key_map: Optional[Dict[str, str]] = None) -> Dict:
    """Build the expression-eval environment from a batch.

    ``key_map`` maps env keys -> batch column names (identity when None).
    """
    if key_map is None:
        env = dict(batch.columns)
    else:
        env = {k: batch.columns[v] for k, v in key_map.items()}
    env[TS_KEY] = batch.timestamps
    env[N_KEY] = len(batch)
    return env


def format_group_keys(key_cols: List[np.ndarray], rows) -> List:
    """Host group-key IDENTITY format, shared by the selector and the
    device engines (key equality drives per-group state and rate-limit
    dedup): scalar for one key column, tuple otherwise, numpy scalars
    unboxed."""
    if len(key_cols) == 1:
        c = key_cols[0]
        return [c[i].item() if isinstance(c[i], np.generic) else c[i]
                for i in rows]
    return [
        tuple(c[i].item() if isinstance(c[i], np.generic) else c[i]
              for c in key_cols)
        for i in rows
    ]


class Processor:
    def process(self, batch: EventBatch, now: int) -> EventBatch:
        raise NotImplementedError


class FilterProcessor(Processor):
    """Drops rows whose boolean condition is false
    (reference: query/processor/filter/FilterProcessor.java:32)."""

    def __init__(self, condition: CompiledExpression, key_map: Optional[Dict[str, str]] = None):
        if condition.type != AttrType.BOOL:
            raise SiddhiAppCreationError("filter condition must be boolean")
        self.condition = condition
        self.key_map = key_map

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        if len(batch) == 0:
            return batch
        mask = np.broadcast_to(
            np.asarray(self.condition.fn(build_env(batch, self.key_map))), (len(batch),)
        )
        # control events (RESET/TIMER) always pass through
        keep = mask | (batch.types >= ev.TIMER)
        if keep.all():
            return batch
        return batch.mask(keep)


class WindowChainProcessor(Processor):
    """Adapts a WindowProcessor into the chain."""

    def __init__(self, window):
        self.window = window

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        return self.window.process(batch, now)


class StreamFunctionChainProcessor(Processor):
    """#ns:fn(...) stream processors (extension SPI)."""

    def __init__(self, fn):
        self.fn = fn

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        return self.fn.process(batch, now)


# ---------------------------------------------------------------------------
# Selector
# ---------------------------------------------------------------------------


class AggBinding:
    """One aggregator call inside the select clause: env key it publishes,
    the executor, and the compiled argument (None == count())."""

    def __init__(self, env_key: str, executor: AggExecutor, arg: Optional[CompiledExpression]):
        self.env_key = env_key
        self.executor = executor
        self.arg = arg


class SelectItem:
    def __init__(self, name: str, compiled: CompiledExpression):
        self.name = name
        self.compiled = compiled


class QuerySelector:
    """Projection + group-by + aggregation + having + order-by/limit
    (reference: query/selector/QuerySelector.java:44,76-205).

    ``batch_mode`` mirrors the reference's batched group-by processing
    (ProcessingMode.BATCH): with a batch window upstream, only the last
    row per group of each flush produces output.
    """

    def __init__(
        self,
        output_stream_id: str,
        items: Optional[List[SelectItem]],  # None == select *
        output_attribute_names: List[str],
        aggregations: List[AggBinding],
        group_keys: List[CompiledExpression],
        having: Optional[CompiledExpression],
        order_by: List[Tuple[str, bool]],
        limit: Optional[int],
        offset: Optional[int],
        batch_mode: bool = False,
    ):
        self.output_stream_id = output_stream_id
        self.items = items
        self.output_attribute_names = output_attribute_names
        self.aggregations = aggregations
        self.group_keys = group_keys
        self.having = having
        self.order_by = order_by
        self.limit = limit
        self.offset = offset
        self.batch_mode = batch_mode
        # group key -> {agg index -> state dict}
        self.group_states: Dict = {}
        # partitioned dense patterns set this True: each incoming match
        # row carries its partition key (aux["partition_keys"]), which is
        # prepended to the group id so ONE shared selector keeps per-key
        # aggregation state — the dense analog of the host's per-key
        # selector instances (PartitionStateHolder + GROUP_BY_KEY)
        self.partition_axis = False

    # -- state plumbing (snapshot contract) ---------------------------------

    def snapshot(self) -> Dict:
        return {"group_states": self.group_states}

    def restore(self, state: Dict):
        self.group_states = state["group_states"]

    # -- processing ---------------------------------------------------------

    def drop_partition_keys(self, keys) -> None:
        """Discard per-key aggregation state for purged partition keys
        (partition-axis selectors; host analog: the per-key instance —
        selector included — is destroyed on idle purge)."""
        doomed = set(keys)
        self.group_states = {
            gid: st for gid, st in self.group_states.items()
            if not (isinstance(gid, tuple) and len(gid) == 2
                    and gid[0] in doomed)
        }

    def _group_ids(self, env, n, pkeys=None) -> List:
        if not self.group_keys:
            base = [None] * n
        else:
            key_cols = [np.broadcast_to(np.asarray(k.fn(env)), (n,)) for k in self.group_keys]
            base = format_group_keys(key_cols, range(n))
        if pkeys is None:
            return base
        return [(pk, k) for pk, k in zip(pkeys, base)]

    def _agg_outputs(self, env, n, keys, is_remove: bool) -> Dict[str, np.ndarray]:
        """Segmented per-group aggregation preserving arrival order."""
        out: Dict[str, np.ndarray] = {}
        if not self.aggregations:
            return out
        # order-preserving group segments
        segments: Dict = {}
        for i, k in enumerate(keys):
            segments.setdefault(k, []).append(i)
        for ai, binding in enumerate(self.aggregations):
            if binding.arg is not None:
                vals = np.broadcast_to(np.asarray(binding.arg.fn(env)), (n,))
            else:
                vals = np.ones(n, dtype=np.int64)
            col: Optional[np.ndarray] = None
            for gkey, idx_list in segments.items():
                gstate = self.group_states.setdefault(gkey, {})
                if ai not in gstate:
                    gstate[ai] = binding.executor.new_state()
                idx = np.asarray(idx_list)
                seg_vals = vals[idx]
                # null inputs leave the aggregate UNCHANGED (reference
                # aggregators skip null data): feed only non-null values
                # and forward-fill the running output over null rows
                nulls = None
                if seg_vals.dtype == object:
                    nulls = np.frompyfunc(
                        lambda x: x is None, 1, 1)(seg_vals).astype(bool)
                    if nulls.any():
                        seg_vals = seg_vals[~nulls]
                    else:
                        nulls = None
                res = (
                    binding.executor.remove_run(gstate[ai], seg_vals)
                    if is_remove
                    else binding.executor.add_run(gstate[ai], seg_vals)
                )
                res = np.asarray(res)
                last_store = gstate.setdefault("_last_out", {})
                if nulls is not None:
                    full = np.empty(len(idx), dtype=object)
                    # position of the last non-null at or before each
                    # row; rows before any non-null value repeat the
                    # aggregate's LAST output from earlier batches
                    # (None only while the aggregate never saw a value)
                    prev = last_store.get(ai)
                    fill = np.cumsum((~nulls).astype(np.int64)) - 1
                    for j in range(len(idx)):
                        full[j] = res[fill[j]] if fill[j] >= 0 else prev
                    if len(res):
                        last_store[ai] = res[-1]
                    res = full
                elif len(res):
                    last_store[ai] = res[-1]
                if col is None:
                    col = np.empty(n, dtype=res.dtype if res.dtype != object else object)
                elif res.dtype == object and col.dtype != object:
                    # a later group emitted None (all-null inputs): the
                    # whole output column must carry real nulls, not
                    # coerced NaN/garbage
                    col = col.astype(object)
                col[idx] = res
            out[binding.env_key] = col if col is not None else np.empty(0)
        return out

    def process(self, batch: EventBatch, now: int) -> EventBatch:
        n = len(batch)
        if n == 0:
            return self._empty_output(batch)
        outputs: List[EventBatch] = []
        # split into maximal runs of equal event type (CURRENT/EXPIRED/...)
        change = np.flatnonzero(np.diff(batch.types)) + 1
        bounds = [0, *change.tolist(), n]
        for s, e in zip(bounds[:-1], bounds[1:]):
            run = batch.take(np.arange(s, e))
            rtype = int(run.types[0])
            if rtype == ev.RESET:
                for gstate in self.group_states.values():
                    for ai, st in gstate.items():
                        if ai == "_last_out":  # null-carry cache, not
                            st.clear()         # an executor state
                            continue
                        self.aggregations[ai].executor.reset(st)
                continue
            if rtype == ev.TIMER:
                continue
            outputs.append(self._process_run(run, rtype))
        outs = [o for o in outputs if len(o)]
        if not outs:
            return self._empty_output(batch)
        result = EventBatch.concat(outs)
        result = self._order_limit(result)
        return result

    def _process_run(self, run: EventBatch, rtype: int) -> EventBatch:
        n = len(run)
        env = build_env(run)
        pkeys = None
        if self.partition_axis:
            pkeys = run.aux.get("partition_keys")
            if pkeys is None or len(pkeys) != n:
                raise SiddhiAppRuntimeError(
                    "partition-axis selector received rows without the "
                    "partition-key side channel")
        keys = self._group_ids(env, n, pkeys)
        if not self.group_keys and not self.aggregations:
            # passthrough selector over a device-lowered query: adopt
            # the upstream group-key side channel so per-group/snapshot
            # rate limiters downstream still see it
            incoming = run.aux.get("group_keys")
            if incoming is not None and len(incoming) == n:
                keys = list(incoming)
        env.update(self._agg_outputs(env, n, keys, is_remove=(rtype == ev.EXPIRED)))
        if self.items is None:
            out_cols = {nm: run.columns[nm] for nm in self.output_attribute_names}
        else:
            out_cols = {}
            for item in self.items:
                col = np.asarray(item.compiled.fn(env))
                if col.ndim == 0:
                    col = np.broadcast_to(col, (n,)).copy()
                out_cols[item.name] = col
        out = EventBatch(
            self.output_stream_id,
            self.output_attribute_names,
            out_cols,
            run.timestamps,
            run.types,
        )
        out.aux["group_keys"] = list(keys)
        # batched processing (reference ProcessingMode.BATCH): with group-by
        # emit the last row per group; with aggregators but no group-by emit
        # only the final row of the flush
        keep_idx = None
        if self.batch_mode and (self.group_keys or self.aggregations):
            last_idx: Dict = {}
            for i, k in enumerate(keys):
                last_idx[k] = i
            keep_idx = np.asarray(sorted(last_idx.values()))
            out = out.take(keep_idx)
        if self.having is not None:
            # input columns + aggregate keys first; select outputs override
            # so an alias shadowing an input attribute sees the output value
            henv = {
                k: (v[keep_idx] if keep_idx is not None and isinstance(v, np.ndarray) and v.shape[:1] == (n,) else v)
                for k, v in env.items()
            }
            henv.update(build_env(out))
            mask = np.broadcast_to(np.asarray(self.having.fn(henv)), (len(out),))
            out = out.mask(mask)
        return out

    def _order_limit(self, out: EventBatch) -> EventBatch:
        if self.order_by:
            # stable sort by keys right-to-left; descending via dense-rank
            # negation so ties keep arrival order (a reversed permutation
            # would reverse ties and break secondary keys)
            idx = np.arange(len(out))
            for name, asc in reversed(self.order_by):
                col = np.asarray(out.columns[name][idx])
                nulls = None
                if col.dtype == object:
                    nulls = np.frompyfunc(
                        lambda x: x is None, 1, 1)(col).astype(bool)
                    if not nulls.any():
                        nulls = None
                if nulls is None:
                    _, dense = np.unique(col, return_inverse=True)
                    key = dense if asc else -dense
                else:
                    # nulls order LAST in both directions (reference
                    # OrderByEventComparator: a null value loses to any
                    # non-null regardless of asc/desc)
                    nn = col[~nulls]
                    key = np.zeros(len(col), dtype=np.int64)
                    if len(nn):
                        _, dense_nn = np.unique(nn, return_inverse=True)
                        key[~nulls] = dense_nn if asc else -dense_nn
                    key[nulls] = (int(key[~nulls].max()) + 1
                                  if len(nn) else 0)
                order = np.argsort(key, kind="stable")
                idx = idx[order]
            out = out.take(idx)
        if self.offset is not None:
            out = out.take(np.arange(min(self.offset, len(out)), len(out)))
        if self.limit is not None:
            out = out.take(np.arange(0, min(self.limit, len(out))))
        return out

    def _empty_output(self, batch: EventBatch) -> EventBatch:
        return EventBatch(
            self.output_stream_id,
            self.output_attribute_names,
            {nm: np.empty(0) for nm in self.output_attribute_names},
            np.empty(0, dtype=np.int64),
            np.empty(0, dtype=np.int8),
        )


# ---------------------------------------------------------------------------
# Output rate limiting (reference: query/output/ratelimit/)
# ---------------------------------------------------------------------------


class OutputRateLimiter:
    # time-driven limiters need a scheduler task (next_wakeup/on_time);
    # event-count limiters set this False so the planner registers none
    needs_scheduler_task = True

    def process(self, batch: EventBatch, now: int) -> Optional[EventBatch]:
        return batch

    def on_time(self, now: int) -> Optional[EventBatch]:
        return None

    def next_wakeup(self) -> Optional[int]:
        return None

    def snapshot(self) -> Dict:
        return {}

    def restore(self, state: Dict):
        pass


class PassThroughRateLimiter(OutputRateLimiter):
    needs_scheduler_task = False


class EventRateLimiter(OutputRateLimiter):
    """`output <all|first|last> every N events` (reference:
    ratelimit/event/*PerEventOutputRateLimiter)."""

    needs_scheduler_task = False

    def __init__(self, n: int, mode: str):
        self.n = n
        self.mode = mode  # all | first | last
        self._count = 0
        self._held: List[EventBatch] = []

    def process(self, batch: EventBatch, now: int) -> Optional[EventBatch]:
        n = len(batch)
        if n == 0:
            return None
        if self.mode in ("first", "last"):
            pos = (self._count + np.arange(n)) % self.n
            self._count += n
            target = 0 if self.mode == "first" else self.n - 1
            out = batch.mask(pos == target)
            return out if len(out) else None
        # all: hold rows, release complete groups of n
        self._count += n
        self._held.append(batch)
        total = sum(len(b) for b in self._held)
        k = (total // self.n) * self.n
        if k == 0:
            return None
        merged = EventBatch.concat(self._held)
        out = merged.take(np.arange(k))
        rest = merged.take(np.arange(k, total))
        self._held = [rest] if len(rest) else []
        return out

    def snapshot(self):
        return {"count": self._count, "held": self._held}

    def restore(self, state):
        self._count, self._held = state["count"], state["held"]


class GroupByEventRateLimiter(OutputRateLimiter):
    """`output <first|last> every N events` on a GROUPED query: first/last
    PER GROUP within each N-event window (reference:
    ratelimit/event/FirstGroupByPerEventOutputRateLimiter.java,
    LastGroupByPerEventOutputRateLimiter.java)."""

    needs_scheduler_task = False

    def __init__(self, n: int, mode: str):
        self.n = n
        self.mode = mode  # first | last
        self._count = 0
        self._seen: set = set()          # first: groups emitted this window
        # last: group -> held single-row batch (previous batches) or a
        # row index into the CURRENT batch; dict order == first arrival
        # of the group in the window (python dicts keep a key's position
        # on overwrite, matching the reference's LinkedHashMap)
        self._last: Dict = {}

    def process(self, batch: EventBatch, now: int) -> Optional[EventBatch]:
        nrows = len(batch)
        if nrows == 0:
            return None
        keys = batch.aux.get("group_keys")
        if keys is None or len(keys) != len(batch):
            # the planner only builds this limiter for grouped queries,
            # whose selector always attaches the side channel — a missing
            # aux is a wiring bug; degrading to one global group would be
            # silently wrong output
            raise SiddhiAppRuntimeError(
                "per-group rate limiter received a batch without the "
                "group-key side channel")
        outs: List[EventBatch] = []
        first_rows: List[int] = []

        def _flush_last():
            if not self._last:
                return
            pieces = [
                v if isinstance(v, EventBatch) else batch.take(np.asarray([v]))
                for v in self._last.values()
            ]
            outs.append(EventBatch.concat(pieces))
            self._last.clear()

        for i in range(nrows):
            k = keys[i]
            if self.mode == "first":
                if k not in self._seen:
                    self._seen.add(k)
                    first_rows.append(i)
            else:
                self._last[k] = i  # local index; materialized lazily
            self._count += 1
            if self._count % self.n == 0:  # window closes
                if self.mode == "first":
                    self._seen.clear()
                else:
                    _flush_last()
        if self.mode == "last":
            # batch ends with the window open: pin surviving local rows
            # (one take per GROUP, not per row)
            for k, v in list(self._last.items()):
                if not isinstance(v, EventBatch):
                    self._last[k] = batch.take(np.asarray([v]))
        if self.mode == "first" and first_rows:
            outs.insert(0, batch.take(np.asarray(first_rows)))
        if not outs:
            return None
        return outs[0] if len(outs) == 1 else EventBatch.concat(outs)

    def snapshot(self):
        return {"count": self._count, "seen": set(self._seen),
                "last": dict(self._last)}

    def restore(self, state):
        self._count = state["count"]
        self._seen = set(state["seen"])
        self._last = dict(state["last"])


class TimeRateLimiter(OutputRateLimiter):
    """`output <all|first|last> every <t>` (reference:
    ratelimit/time/*TimeOutputRateLimiter)."""

    def __init__(self, ms: int, mode: str):
        self.ms = ms
        self.mode = mode
        self._held: List[EventBatch] = []
        self._first_sent = False
        self._last: Optional[EventBatch] = None
        self._window_end: Optional[int] = None

    def _roll(self, now: int):
        if self._window_end is None:
            self._window_end = now + self.ms

    def process(self, batch: EventBatch, now: int) -> Optional[EventBatch]:
        self._roll(now)
        out = self.on_time(now)
        res: List[EventBatch] = [out] if out is not None else []
        if self.mode == "first":
            if not self._first_sent and len(batch):
                self._first_sent = True
                res.append(batch.take(np.asarray([0])))
        elif self.mode == "last":
            if len(batch):
                self._last = batch.take(np.asarray([len(batch) - 1]))
        else:
            self._held.append(batch)
        return EventBatch.concat(res) if res else None

    def on_time(self, now: int) -> Optional[EventBatch]:
        if self._window_end is None or now < self._window_end:
            return None
        outs: List[EventBatch] = []
        while now >= self._window_end:
            if self.mode == "all" and self._held:
                outs.extend(self._held)
                self._held = []
            elif self.mode == "last" and self._last is not None:
                outs.append(self._last)
                self._last = None
            self._first_sent = False
            self._window_end += self.ms
        return EventBatch.concat(outs) if outs else None

    def next_wakeup(self) -> Optional[int]:
        return self._window_end

    def snapshot(self):
        return {
            "held": self._held, "first_sent": self._first_sent,
            "last": self._last, "end": self._window_end,
        }

    def restore(self, state):
        self._held = state["held"]
        self._first_sent = state["first_sent"]
        self._last = state["last"]
        self._window_end = state["end"]


class GroupByTimeRateLimiter(OutputRateLimiter):
    """`output <first|last> every <t>` on a GROUPED query: first/last
    PER GROUP within each period (reference: ratelimit/time/
    FirstGroupByPerTimeOutputRateLimiter.java,
    LastGroupByPerTimeOutputRateLimiter.java)."""

    def __init__(self, ms: int, mode: str):
        self.ms = ms
        self.mode = mode  # first | last
        self._seen: set = set()      # first: groups emitted this period
        self._last: Dict = {}        # last: group -> single-row batch
        self._window_end: Optional[int] = None

    def process(self, batch: EventBatch, now: int) -> Optional[EventBatch]:
        if self._window_end is None:
            self._window_end = now + self.ms
        out = self.on_time(now)
        res: List[EventBatch] = [out] if out is not None else []
        if len(batch) == 0:
            # having/batch-window flushes can hand over empty outputs,
            # which legitimately carry no group-key side channel
            return EventBatch.concat(res) if res else None
        keys = batch.aux.get("group_keys")
        if keys is None or len(keys) != len(batch):
            raise SiddhiAppRuntimeError(
                "per-group rate limiter received a batch without the "
                "group-key side channel")
        if self.mode == "first":
            rows = []
            for i, k in enumerate(keys):
                if k not in self._seen:
                    self._seen.add(k)
                    rows.append(i)
            if rows:
                res.append(batch.take(np.asarray(rows)))
        else:
            for i, k in enumerate(keys):
                self._last[k] = i  # local index; materialized below
            for k, v in list(self._last.items()):
                if not isinstance(v, EventBatch):
                    self._last[k] = batch.take(np.asarray([v]))
        return EventBatch.concat(res) if res else None

    def on_time(self, now: int) -> Optional[EventBatch]:
        if self._window_end is None or now < self._window_end:
            return None
        outs: List[EventBatch] = []
        while now >= self._window_end:
            if self.mode == "last" and self._last:
                outs.extend(self._last.values())
                self._last = {}
            self._seen.clear()
            self._window_end += self.ms
        return EventBatch.concat(outs) if outs else None

    def next_wakeup(self) -> Optional[int]:
        return self._window_end

    @staticmethod
    def _copy_last(last: Dict) -> Dict:
        # re-materialize the per-group single-row batches: a shallow dict
        # copy would alias EventBatch internals between the live limiter
        # and the snapshot (restored batches could bleed mutations)
        return {k: v.copy() if isinstance(v, EventBatch) else v
                for k, v in last.items()}

    def snapshot(self):
        return {"seen": set(self._seen), "last": self._copy_last(self._last),
                "end": self._window_end}

    def restore(self, state):
        self._seen = set(state["seen"])
        self._last = self._copy_last(state["last"])
        self._window_end = state["end"]


class SnapshotRateLimiter(OutputRateLimiter):
    """`output snapshot every <t>`: periodically re-emits the latest
    output per group key (reference: ratelimit/snapshot/
    WrappedSnapshotOutputRateLimiter, simplified to last-value
    snapshots)."""

    def __init__(self, ms: int, group_names: Optional[List[str]] = None):
        self.ms = ms
        self.group_names = group_names or []
        self._latest: Dict = {}
        self._window_end: Optional[int] = None

    def process(self, batch: EventBatch, now: int) -> Optional[EventBatch]:
        if self._window_end is None:
            self._window_end = now + self.ms
        cur = batch.only(ev.CURRENT)
        group_keys = batch.aux.get("group_keys")
        if group_keys is not None and len(group_keys) == len(batch):
            # align to the CURRENT subset
            cur_mask = np.isin(batch.types, (ev.CURRENT,))
            group_keys = [k for k, m in zip(group_keys, cur_mask) if m]
        for i in range(len(cur)):
            row = cur.take(np.asarray([i]))
            if group_keys is not None:
                key = group_keys[i]
            elif self.group_names:
                key = tuple(
                    row.columns[g][0] if g in row.columns else None for g in self.group_names
                )
            else:
                key = None
            self._latest[key] = row
        return self.on_time(now)

    def on_time(self, now: int) -> Optional[EventBatch]:
        if self._window_end is None or now < self._window_end:
            return None
        outs: List[EventBatch] = []
        while now >= self._window_end:
            outs = list(self._latest.values())  # latest snapshot only
            self._window_end += self.ms
        return EventBatch.concat(outs) if outs else None

    def next_wakeup(self) -> Optional[int]:
        return self._window_end

    def snapshot(self):
        return {"latest": self._latest, "end": self._window_end}

    def restore(self, state):
        self._latest, self._window_end = state["latest"], state["end"]


# ---------------------------------------------------------------------------
# Output callbacks (reference: query/output/callback/)
# ---------------------------------------------------------------------------


class OutputCallback:
    def send(self, batch: EventBatch, now: int):
        raise NotImplementedError


class InsertIntoStreamCallback(OutputCallback):
    """Routes selected events into the target junction; expired events
    become CURRENT on the next stream (reference:
    InsertIntoStreamCallback.java)."""

    def __init__(self, junction: StreamJunction, event_type: str):
        self.junction = junction
        self.event_type = event_type

    def send(self, batch: EventBatch, now: int):
        if self.event_type == "current":
            out = batch.only(ev.CURRENT)
        elif self.event_type == "expired":
            out = batch.only(ev.EXPIRED)
        else:
            out = batch.only(ev.CURRENT, ev.EXPIRED)
        if len(out) == 0:
            return
        out = out.with_types(ev.CURRENT)
        out.stream_id = self.junction.stream_id
        self.junction.send(out)


class QueryCallbackOutput(OutputCallback):
    """Feeds user QueryCallbacks with (ts, inEvents, removeEvents).

    ``app_context``/``ledger_key`` (set by QueryRuntime.add_callback)
    plug this endpoint into the crash-recovery output ledger: during
    restore-and-replay the journal suppresses the prefix of events these
    callbacks already received before the crash."""

    def __init__(self):
        self.callbacks: List[QueryCallback] = []
        self.app_context = None
        self.ledger_key = None

    def send(self, batch: EventBatch, now: int):
        if not self.callbacks or len(batch) == 0:
            return
        jr = getattr(self.app_context, "input_journal", None)
        if jr is not None and self.ledger_key is not None:
            batch = jr.deliver(self.ledger_key, batch)
            if batch is None:
                return
        cur = batch.only(ev.CURRENT)
        exp = batch.only(ev.EXPIRED)
        in_events = events_from_batch(cur) if len(cur) else None
        out_events = events_from_batch(exp) if len(exp) else None
        if in_events is None and out_events is None:
            return
        ts = int(batch.timestamps[-1])
        for cb in self.callbacks:
            cb.receive(ts, in_events, out_events)


class FanOutOutput(OutputCallback):
    def __init__(self, outputs: List[OutputCallback]):
        self.outputs = outputs

    def send(self, batch: EventBatch, now: int):
        for o in self.outputs:
            o.send(batch, now)


# ---------------------------------------------------------------------------
# Receiver + query runtime
# ---------------------------------------------------------------------------


class ProcessStreamReceiver:
    """Junction subscriber driving one query's chain
    (reference: query/input/ProcessStreamReceiver.java:99-179)."""

    def __init__(self, query_runtime: "QueryRuntime", chain_index: int = 0):
        self.query_runtime = query_runtime
        self.chain_index = chain_index

    def receive(self, batch: EventBatch):
        self.query_runtime.process(batch, self.chain_index)


class QueryRuntime:
    """One compiled query (reference: QueryRuntimeImpl.java:43)."""

    def __init__(
        self,
        name: str,
        chains: List[List[Processor]],
        selector: QuerySelector,
        rate_limiter: OutputRateLimiter,
        output: OutputCallback,
        app_context,
    ):
        self.name = name
        self.chains = chains
        self.selector = selector
        self.rate_limiter = rate_limiter
        self.output = output
        self.app_context = app_context
        self.callback_output: Optional[QueryCallbackOutput] = None
        self.latency_tracker = None
        self.debugger = None  # set by SiddhiAppRuntime.debug()
        # which engine this query actually runs on: 'host' (columnar
        # numpy chain), 'dense' (jitted dense NFA), or 'device' (jitted
        # device query engine) — surfaced via statistics and the REST
        # introspection endpoint so `execution('tpu')` fallbacks are
        # visible, not silent
        self.lowered_to = "host"

    def add_callback(self, cb: QueryCallback):
        if self.callback_output is None:
            self.callback_output = QueryCallbackOutput()
            self.callback_output.app_context = self.app_context
            self.callback_output.ledger_key = ("query", self.name)
            self.output = FanOutOutput([self.output, self.callback_output])
        self.callback_output.callbacks.append(cb)

    def process(self, batch: EventBatch, chain_index: int = 0):
        # async emit pipeline: a deferred device emit carries the time
        # observed when its batch was PROCESSED (aux side channel) —
        # time-based rate limiters must see the same clock sequence the
        # synchronous path produces, not the later drain time
        now = batch.aux.pop("emit_now", None)
        if now is None:
            now = self.app_context.timestamp_generator.current_time()
        if self.latency_tracker is not None:
            self.latency_tracker.mark_in(len(batch))
        try:
            if self.debugger is not None and len(batch):
                self.debugger.check_breakpoint(self.name, "IN", batch)
            b = batch
            for p in self.chains[chain_index]:
                b = p.process(b, now)
                if len(b) == 0:
                    return
            out = self.selector.process(b, now)
            out = self.rate_limiter.process(out, now)
            if out is not None and len(out):
                if self.debugger is not None:
                    self.debugger.check_breakpoint(self.name, "OUT", out)
                self.output.send(out, now)
        finally:
            if self.latency_tracker is not None:
                self.latency_tracker.mark_out(len(batch))

    # -- state plumbing (snapshot contract) ---------------------------------

    def snapshot_state(self) -> Dict:
        """Collect every stateful element of this query (windows in the
        chain, selector group states, rate limiter, join-side windows,
        pattern NFA instances) — the analog of the reference's per-query
        StateHolder walk (util/snapshot/SnapshotService.java:101-169)."""
        self._drain_device_emits()
        state: Dict = {"selector": self.selector.snapshot()}
        if hasattr(self.rate_limiter, "snapshot"):
            state["rate_limiter"] = self.rate_limiter.snapshot()
        windows = {}
        for ci, chain in enumerate(self.chains):
            for pi, p in enumerate(chain):
                if isinstance(p, WindowChainProcessor):
                    windows[f"{ci}.{pi}"] = p.window.snapshot()
        if windows:
            state["windows"] = windows
        jr = getattr(self, "join_runtime", None)
        if jr is not None:
            jw = {}
            for label, side in (("left", jr.left), ("right", jr.right)):
                if side.window is not None:
                    jw[label] = side.window.snapshot()
            if jw:
                state["join_windows"] = jw
        pp = getattr(self, "pattern_processor", None)
        if pp is not None:
            state["pattern"] = pp.snapshot()
        dr = getattr(self, "device_runtime", None)
        if dr is not None:
            state["device"] = dr.snapshot()
        return state

    def _drain_device_emits(self):
        """Flush barrier of the async emit pipeline: this query's queued
        device emits materialize (through selector/limiter/output) BEFORE
        the surrounding snapshot/restore reads or replaces that state —
        exactly where the synchronous path would have delivered them."""
        for attr in ("device_runtime", "pattern_processor"):
            rt = getattr(self, attr, None)
            if rt is not None and hasattr(rt, "drain"):
                rt.drain()

    def restore_state(self, state: Dict):
        self._drain_device_emits()
        self.selector.restore(state["selector"])
        if "rate_limiter" in state and hasattr(self.rate_limiter, "restore"):
            self.rate_limiter.restore(state["rate_limiter"])
        for key, ws in state.get("windows", {}).items():
            ci, pi = (int(x) for x in key.split("."))
            self.chains[ci][pi].window.restore(ws)
        jr = getattr(self, "join_runtime", None)
        if jr is not None:
            jw = state.get("join_windows", {})
            for label, side in (("left", jr.left), ("right", jr.right)):
                if label in jw and side.window is not None:
                    side.window.restore(jw[label])
        pp = getattr(self, "pattern_processor", None)
        if pp is not None and "pattern" in state:
            pp.restore(state["pattern"])
        dr = getattr(self, "device_runtime", None)
        if dr is not None and "device" in state:
            dr.restore(state["device"])

    def on_time(self, now: int, payloads: Optional[EventBatch] = None):
        """Scheduler tick: run time-window evictions through the tail of
        the chain."""
        for ci, chain in enumerate(self.chains):
            for pi, p in enumerate(chain):
                if isinstance(p, WindowChainProcessor):
                    out = p.window.on_time(now)
                    if out is not None and len(out):
                        b = out
                        for q in chain[pi + 1 :]:
                            b = q.process(b, now)
                            if len(b) == 0:
                                break
                        else:
                            sel = self.selector.process(b, now)
                            sel = self.rate_limiter.process(sel, now)
                            if sel is not None and len(sel):
                                self.output.send(sel, now)
