"""Windowed joins: two-sided stream joins, stream-table joins, outer joins.

Re-design of the reference ``query/input/stream/join/`` (JoinProcessor.java:45,
JoinInputStreamParser.java): instead of per-event ``compiledCondition.find()``
probes against the opposite window, an arriving micro-batch is joined with
the opposite buffer via one vectorized cross-product condition evaluation
(repeat/tile + boolean mask).  Each side keeps its own window buffer;
CURRENT arrivals pre-probe, window-expired rows post-probe (emitting
EXPIRED joined events), matching the reference's pre/post join processor
sandwich around the window.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core import event as ev
from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.planner.expr import CompiledExpression, N_KEY, TS_KEY
from siddhi_tpu.query_api import AttrType, JoinInputStream


def _null_column(t: AttrType, n: int) -> np.ndarray:
    """Unmatched-side fill for outer joins: a column of object-dtype
    None for every attribute type — float included — so callbacks
    observe uniform real nulls (reference: boxed nulls in joined
    StateEvents).  NaN fills would make ``is None`` checks miss and
    break equality filters (NaN != NaN)."""
    col = np.empty(n, dtype=object)
    col[:] = None
    return col


class JoinSide:
    """One side of the join: filters + optional window buffer (or a table
    acting as a passive findable buffer)."""

    def __init__(
        self,
        ref: str,
        definition,
        filters: List,
        window,
        table=None,
        named_window=None,
        aggregation=None,
        triggers: bool = True,
    ):
        self.ref = ref
        self.definition = definition
        self.filters = filters
        self.window = window
        self.table = table
        self.named_window = named_window
        self.aggregation = aggregation
        # compiled `within`/`per` of an aggregation join, attached by the
        # planner (reference: AggregationRuntime.compileExpression)
        self.agg_within = None  # (CompiledExpression, CompiledExpression|None)
        self.agg_per = None  # CompiledExpression
        self.triggers = triggers

    def buffered(self, probe_env: Optional[Dict] = None) -> Optional[EventBatch]:
        if self.aggregation is not None:
            from siddhi_tpu.aggregation.runtime import within_bounds

            if self.agg_per is None:
                raise SiddhiAppCreationError(
                    f"aggregation join '{self.ref}': 'per' clause is required"
                )
            env = probe_env or {N_KEY: 0}
            per = str(np.asarray(self.agg_per.fn(env)).ravel()[0])
            within = None
            if self.agg_within is not None:
                start_c, end_c = self.agg_within
                v1 = np.asarray(start_c.fn(env)).ravel()[0]
                v2 = np.asarray(end_c.fn(env)).ravel()[0] if end_c is not None else None
                within = within_bounds(v1, v2)
            return self.aggregation.find(per, within)
        if self.table is not None:
            return self.table.rows_batch()
        if self.window is not None:
            return self.window.buffered()
        if self.named_window is not None:
            buf = self.named_window.buffered()
            # a named window's buffer is shared, so this side's filters must
            # run at probe time (a plain window side filters before buffering)
            if buf is not None:
                for f in self.filters:
                    buf = f.process(buf, 0)
            return buf
        return None  # pure stream side buffers nothing

    def qualified_key(self, attr: str) -> str:
        return f"{self.ref}.{attr}"


class DeviceJoinProbe:
    """Jitted cross-product condition mask — the join hot loop on
    device (reference: JoinProcessor.java:45 probing the opposite
    window via compiledCondition.find per event).

    Window buffering, expiry and outer-join fill stay with the host
    JoinRuntime; the O(B*W) condition evaluation runs as a static-shape
    [B, W] device kernel (arriving rows broadcast down columns, buffered
    rows across rows), and matched pairs materialize host-side from the
    mask in O(matches).

    Lane policy matches the device query engine (ops/device_query.py):
    INT rides int32 (bit-exact), BOOL bool, FLOAT/DOUBLE float32 — a
    documented precision subset of the host's float64 condition
    evaluation.  Conditions touching STRING or LONG attributes, or the
    event timestamp (whose epoch-ms magnitude exceeds device int32
    lanes), keep the numpy path — enforced by tracing the kernel env at
    plan time, which simply lacks those keys.  Batches whose numeric
    columns carry nulls (object dtype) fall back per batch.
    """

    MAX_ROWS = 2048  # [B, W] work bound per kernel call; both axes chunk
    MAX_BUF = 8192

    def __init__(self, condition: CompiledExpression,
                 left: JoinSide, right: JoinSide):
        import jax

        self.jax = jax
        self.condition = condition
        self._lanes: Dict[str, Dict[str, np.dtype]] = {}
        for side in (left, right):
            lanes = {}
            for a in side.definition.attributes:
                if a.type == AttrType.INT:
                    lanes[side.qualified_key(a.name)] = np.dtype(np.int32)
                elif a.type == AttrType.BOOL:
                    lanes[side.qualified_key(a.name)] = np.dtype(np.bool_)
                elif a.type.is_numeric and a.type != AttrType.LONG:
                    lanes[side.qualified_key(a.name)] = np.dtype(np.float32)
            self._lanes[side.ref] = lanes
        self._kernels: Dict[Tuple[int, int], object] = {}
        self._trace_check(left, right)

    def _trace_check(self, left, right):
        """Plan-time eligibility: the condition must trace over the 2-D
        lane env (raises SiddhiAppCreationError -> numpy probe kept).
        The env deliberately has NO timestamp key and no STRING/LONG
        lanes, so conditions touching those KeyError here and stay on
        the null-safe host evaluation.  Key accesses are recorded so
        only condition-REFERENCED attributes ride device lanes — an
        unrelated nullable column must neither ship to the device nor
        force a host fallback."""
        import jax

        # pass 1: record which env keys the condition actually reads
        # (small numpy evaluation through the dual-backend expression)
        class _Recorder(dict):
            def __getitem__(self, k):
                self.used.add(k)
                return super().__getitem__(k)

        rec = _Recorder()
        rec.used = set()
        for ref, lanes in self._lanes.items():
            for k, dt in lanes.items():
                shape = (4, 1) if ref == left.ref else (1, 4)
                rec[k] = np.ones(shape, dtype=dt)
        rec[N_KEY] = 16
        try:
            self.condition.fn(rec)
            for ref in self._lanes:
                self._lanes[ref] = {
                    k: dt for k, dt in self._lanes[ref].items()
                    if k in rec.used
                }
        except Exception as e:
            # probe-only failure: pass 2 below decides eligibility with
            # full lanes — but leave a trace (no-silent-fault contract)
            import logging

            logging.getLogger("siddhi_tpu").debug(
                "join lane-pruning probe failed (%s); keeping full "
                "lane set for the traceability check", e)
        # pass 2: the condition must trace over the (pruned) lane env
        env = {}
        for ref, lanes in self._lanes.items():
            for k, dt in lanes.items():
                shape = (4, 1) if ref == left.ref else (1, 4)
                env[k] = jax.ShapeDtypeStruct(shape, dt)
        env[N_KEY] = 16
        try:
            jax.eval_shape(lambda e: self.condition.fn(e), env)
        except Exception as e:
            raise SiddhiAppCreationError(
                f"join condition not device-traceable: {e}") from e

    def _kernel(self, B: int, W: int):
        k = self._kernels.get((B, W))
        if k is None:
            import jax.numpy as jnp

            def mask_fn(a_lanes, b_lanes):
                env = {key: v[:, None] for key, v in a_lanes.items()}
                env.update({key: v[None, :] for key, v in b_lanes.items()})
                env[N_KEY] = B * W
                return jnp.broadcast_to(
                    jnp.asarray(self.condition.fn(env)).astype(bool),
                    (B, W))

            k = self.jax.jit(mask_fn)
            self._kernels[(B, W)] = k
        return k

    @staticmethod
    def _pow2(n: int) -> int:
        return max(1 << (max(n, 1) - 1).bit_length(), 16)

    def _side_lanes(self, side: JoinSide, batch: EventBatch,
                    idx0: int, n: int, pad: int):
        """Device lanes for rows [idx0, idx0+n); None when a numeric
        column carries nulls (object dtype) — caller then falls back to
        the null-safe numpy probe for this batch."""
        import jax.numpy as jnp

        out = {}
        for key, dt in self._lanes[side.ref].items():
            attr = key.split(".", 1)[1]
            src = np.asarray(batch.columns[attr])[idx0:idx0 + n]
            if src.dtype.kind == "O":
                return None
            col = np.zeros(pad, dtype=dt)
            col[:n] = src.astype(dt, copy=False)
            out[key] = jnp.asarray(col)
        return out

    def mask(self, side: JoinSide, rows: EventBatch, other: JoinSide,
             buf: EventBatch) -> Optional[np.ndarray]:
        """[n_a, n_b] condition mask, or None when this batch is not
        device-evaluable (nulls in a numeric column)."""
        n_a, n_b = len(rows), len(buf)
        out = np.empty((n_a, n_b), dtype=bool)
        for bs in range(0, n_b, self.MAX_BUF):
            nb = min(self.MAX_BUF, n_b - bs)
            W = self._pow2(nb)
            b_lanes = self._side_lanes(other, buf, bs, nb, W)
            if b_lanes is None:
                return None
            for as_ in range(0, n_a, self.MAX_ROWS):
                na = min(self.MAX_ROWS, n_a - as_)
                B = self._pow2(na)
                a_lanes = self._side_lanes(side, rows, as_, na, B)
                if a_lanes is None:
                    return None
                m = self._kernel(B, W)(a_lanes, b_lanes)
                out[as_:as_ + na, bs:bs + nb] = np.asarray(m)[:na, :nb]
        return out


class JoinRuntime:
    """Drives both sides and emits joined batches to the query's selector
    (via ``emit``).  Registered as a scheduler task for time-window
    eviction on either side."""

    def __init__(
        self,
        left: JoinSide,
        right: JoinSide,
        join_type: str,
        condition: Optional[CompiledExpression],
        emit,
        out_stream_id: str,
    ):
        self.left = left
        self.right = right
        self.join_type = join_type
        self.condition = condition
        self.emit = emit
        self.out_stream_id = out_stream_id
        # set by the planner under @app:execution('tpu') when the
        # condition is device-traceable: jitted [B, W] probe kernel
        self.device_probe: Optional[DeviceJoinProbe] = None
        self.probe_invocations = 0  # proof the device probe ran (tests)
        self._out_names = [
            left.qualified_key(a.name) for a in left.definition.attributes
        ] + [right.qualified_key(a.name) for a in right.definition.attributes]

    # -- event entry --------------------------------------------------------

    def on_event(self, side_is_left: bool, batch: EventBatch, now: int):
        side = self.left if side_is_left else self.right
        other = self.right if side_is_left else self.left
        b = batch
        for f in side.filters:
            b = f.process(b, now)
            if len(b) == 0:
                return
        outs: List[EventBatch] = []
        cur = b.only(ev.CURRENT)
        # pre-join: arriving CURRENT events probe the opposite buffer
        if side.triggers and len(cur):
            j = self._join(side, cur, other, ev.CURRENT)
            if j is not None:
                outs.append(j)
        # window pass: buffer; expired rows post-join as EXPIRED
        if side.window is not None:
            wout = side.window.process(b, now)
            expired = wout.only(ev.EXPIRED)
            if side.triggers and len(expired):
                j = self._join(side, expired, other, ev.EXPIRED)
                if j is not None:
                    outs.append(j)
        elif side.named_window is not None and side.triggers:
            # a named-window source delivers its own EXPIRED flow
            expired = b.only(ev.EXPIRED)
            if len(expired):
                j = self._join(side, expired, other, ev.EXPIRED)
                if j is not None:
                    outs.append(j)
        if outs:
            self.emit(EventBatch.concat(outs), now)

    # -- scheduler task contract -------------------------------------------

    def next_wakeup(self) -> Optional[int]:
        cands = []
        for s in (self.left, self.right):
            if s.window is not None:
                w = s.window.next_wakeup()
                if w is not None:
                    cands.append(w)
        return min(cands) if cands else None

    def fire(self, now: int):
        for side, other in ((self.left, self.right), (self.right, self.left)):
            if side.window is None:
                continue
            out = side.window.on_time(now)
            if out is None or not side.triggers:
                continue
            expired = out.only(ev.EXPIRED)
            if len(expired):
                j = self._join(side, expired, other, ev.EXPIRED)
                if j is not None:
                    self.emit(j, now)

    # -- the vectorized probe ----------------------------------------------

    def _join(
        self, side: JoinSide, rows: EventBatch, other: JoinSide, out_type: int
    ) -> Optional[EventBatch]:
        probe_env = None
        if other.aggregation is not None and len(rows):
            # `within`/`per` may reference the arriving event's attributes;
            # evaluate them on the first probing row
            probe_env = {
                side.qualified_key(a.name): rows.columns[a.name][:1]
                for a in side.definition.attributes
            }
            probe_env[TS_KEY] = rows.timestamps[:1]
            probe_env[N_KEY] = 1
        buf = other.buffered(probe_env)
        n_a = len(rows)
        n_b = len(buf) if buf is not None else 0
        is_outer = self._side_outer(side)

        if n_b == 0:
            if not is_outer:
                return None
            return self._with_nulls(side, rows, other, out_type)

        # condition mask [n_a, n_b]: all-pairs, device probe, or the
        # numpy repeat/tile cross product (also the per-batch fallback
        # when the probe sees null-carrying numeric columns)
        mask2: Optional[np.ndarray] = None
        if self.condition is None:
            mask2 = np.ones((n_a, n_b), dtype=bool)
        elif self.device_probe is not None:
            mask2 = self.device_probe.mask(side, rows, other, buf)
            if mask2 is not None:
                self.probe_invocations += 1
        if mask2 is None:
            env: Dict[str, np.ndarray] = {}
            for a in side.definition.attributes:
                env[side.qualified_key(a.name)] = np.repeat(
                    rows.columns[a.name], n_b)
            for a in other.definition.attributes:
                env[other.qualified_key(a.name)] = np.tile(
                    buf.columns[a.name], n_a)
            env[TS_KEY] = np.repeat(rows.timestamps, n_b)
            env[N_KEY] = n_a * n_b
            mask2 = np.broadcast_to(
                np.asarray(self.condition.fn(env)),
                (n_a * n_b,)).reshape(n_a, n_b)

        # matched pairs materialize in O(matches), row-major (arriving
        # row order, buffer order within a row)
        ai, bi = np.nonzero(mask2)
        cols: Dict[str, np.ndarray] = {}
        for a in side.definition.attributes:
            cols[side.qualified_key(a.name)] = np.asarray(
                rows.columns[a.name])[ai]
        for a in other.definition.attributes:
            cols[other.qualified_key(a.name)] = np.asarray(
                buf.columns[a.name])[bi]
        out = EventBatch(
            self.out_stream_id,
            self._out_names,
            {k: cols[k] for k in self._out_names},
            np.asarray(rows.timestamps)[ai],
            np.full(len(ai), out_type, dtype=np.int8),
        )
        if is_outer:
            matched_any = mask2.any(axis=1)
            if not matched_any.all():
                unmatched = rows.mask(~matched_any)
                out = EventBatch.concat(
                    [out, self._with_nulls(side, unmatched, other, out_type)]
                )
        return out if len(out) else None

    def _side_outer(self, side: JoinSide) -> bool:
        """Does this trigger side emit unmatched rows (with the other side
        nulled)?  LEFT_OUTER preserves left rows, etc."""
        if self.join_type == JoinInputStream.FULL_OUTER:
            return True
        if self.join_type == JoinInputStream.LEFT_OUTER:
            return side is self.left
        if self.join_type == JoinInputStream.RIGHT_OUTER:
            return side is self.right
        return False

    def _with_nulls(
        self, side: JoinSide, rows: EventBatch, other: JoinSide, out_type: int
    ) -> EventBatch:
        n = len(rows)
        cols: Dict[str, np.ndarray] = {}
        for a in side.definition.attributes:
            cols[side.qualified_key(a.name)] = rows.columns[a.name]
        for a in other.definition.attributes:
            cols[other.qualified_key(a.name)] = _null_column(a.type, n)
        return EventBatch(
            self.out_stream_id,
            self._out_names,
            {k: cols[k] for k in self._out_names},
            rows.timestamps,
            np.full(n, out_type, dtype=np.int8),
        )


class JoinStreamReceiver:
    """Junction subscriber feeding one side of the join."""

    def __init__(self, join_runtime: JoinRuntime, side_is_left: bool, app_context):
        self.join_runtime = join_runtime
        self.side_is_left = side_is_left
        self.app_context = app_context

    def receive(self, batch: EventBatch):
        now = self.app_context.timestamp_generator.current_time()
        self.join_runtime.on_event(self.side_is_left, batch, now)
