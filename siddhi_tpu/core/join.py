"""Windowed joins: two-sided stream joins, stream-table joins, outer joins.

Re-design of the reference ``query/input/stream/join/`` (JoinProcessor.java:45,
JoinInputStreamParser.java): instead of per-event ``compiledCondition.find()``
probes against the opposite window, an arriving micro-batch is joined with
the opposite buffer via one vectorized cross-product condition evaluation
(repeat/tile + boolean mask).  Each side keeps its own window buffer;
CURRENT arrivals pre-probe, window-expired rows post-probe (emitting
EXPIRED joined events), matching the reference's pre/post join processor
sandwich around the window.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core import event as ev
from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.planner.expr import CompiledExpression, N_KEY, TS_KEY
from siddhi_tpu.query_api import AttrType, JoinInputStream


def _null_column(t: AttrType, n: int) -> np.ndarray:
    """Unmatched-side fill for outer joins: a column of object-dtype
    None for every attribute type — float included — so callbacks
    observe uniform real nulls (reference: boxed nulls in joined
    StateEvents).  NaN fills would make ``is None`` checks miss and
    break equality filters (NaN != NaN)."""
    col = np.empty(n, dtype=object)
    col[:] = None
    return col


class JoinSide:
    """One side of the join: filters + optional window buffer (or a table
    acting as a passive findable buffer)."""

    def __init__(
        self,
        ref: str,
        definition,
        filters: List,
        window,
        table=None,
        named_window=None,
        aggregation=None,
        triggers: bool = True,
    ):
        self.ref = ref
        self.definition = definition
        self.filters = filters
        self.window = window
        self.table = table
        self.named_window = named_window
        self.aggregation = aggregation
        # compiled `within`/`per` of an aggregation join, attached by the
        # planner (reference: AggregationRuntime.compileExpression)
        self.agg_within = None  # (CompiledExpression, CompiledExpression|None)
        self.agg_per = None  # CompiledExpression
        self.triggers = triggers

    def buffered(self, probe_env: Optional[Dict] = None) -> Optional[EventBatch]:
        if self.aggregation is not None:
            from siddhi_tpu.aggregation.runtime import within_bounds

            if self.agg_per is None:
                raise SiddhiAppCreationError(
                    f"aggregation join '{self.ref}': 'per' clause is required"
                )
            env = probe_env or {N_KEY: 0}
            per = str(np.asarray(self.agg_per.fn(env)).ravel()[0])
            within = None
            if self.agg_within is not None:
                start_c, end_c = self.agg_within
                v1 = np.asarray(start_c.fn(env)).ravel()[0]
                v2 = np.asarray(end_c.fn(env)).ravel()[0] if end_c is not None else None
                within = within_bounds(v1, v2)
            return self.aggregation.find(per, within)
        if self.table is not None:
            return self.table.rows_batch()
        if self.window is not None:
            return self.window.buffered()
        if self.named_window is not None:
            buf = self.named_window.buffered()
            # a named window's buffer is shared, so this side's filters must
            # run at probe time (a plain window side filters before buffering)
            if buf is not None:
                for f in self.filters:
                    buf = f.process(buf, 0)
            return buf
        return None  # pure stream side buffers nothing

    def qualified_key(self, attr: str) -> str:
        return f"{self.ref}.{attr}"


class JoinRuntime:
    """Drives both sides and emits joined batches to the query's selector
    (via ``emit``).  Registered as a scheduler task for time-window
    eviction on either side."""

    def __init__(
        self,
        left: JoinSide,
        right: JoinSide,
        join_type: str,
        condition: Optional[CompiledExpression],
        emit,
        out_stream_id: str,
    ):
        self.left = left
        self.right = right
        self.join_type = join_type
        self.condition = condition
        self.emit = emit
        self.out_stream_id = out_stream_id
        self._out_names = [
            left.qualified_key(a.name) for a in left.definition.attributes
        ] + [right.qualified_key(a.name) for a in right.definition.attributes]

    # -- event entry --------------------------------------------------------

    def on_event(self, side_is_left: bool, batch: EventBatch, now: int):
        side = self.left if side_is_left else self.right
        other = self.right if side_is_left else self.left
        b = batch
        for f in side.filters:
            b = f.process(b, now)
            if len(b) == 0:
                return
        outs: List[EventBatch] = []
        cur = b.only(ev.CURRENT)
        # pre-join: arriving CURRENT events probe the opposite buffer
        if side.triggers and len(cur):
            j = self._join(side, cur, other, ev.CURRENT)
            if j is not None:
                outs.append(j)
        # window pass: buffer; expired rows post-join as EXPIRED
        if side.window is not None:
            wout = side.window.process(b, now)
            expired = wout.only(ev.EXPIRED)
            if side.triggers and len(expired):
                j = self._join(side, expired, other, ev.EXPIRED)
                if j is not None:
                    outs.append(j)
        elif side.named_window is not None and side.triggers:
            # a named-window source delivers its own EXPIRED flow
            expired = b.only(ev.EXPIRED)
            if len(expired):
                j = self._join(side, expired, other, ev.EXPIRED)
                if j is not None:
                    outs.append(j)
        if outs:
            self.emit(EventBatch.concat(outs), now)

    # -- scheduler task contract -------------------------------------------

    def next_wakeup(self) -> Optional[int]:
        cands = []
        for s in (self.left, self.right):
            if s.window is not None:
                w = s.window.next_wakeup()
                if w is not None:
                    cands.append(w)
        return min(cands) if cands else None

    def fire(self, now: int):
        for side, other in ((self.left, self.right), (self.right, self.left)):
            if side.window is None:
                continue
            out = side.window.on_time(now)
            if out is None or not side.triggers:
                continue
            expired = out.only(ev.EXPIRED)
            if len(expired):
                j = self._join(side, expired, other, ev.EXPIRED)
                if j is not None:
                    self.emit(j, now)

    # -- the vectorized probe ----------------------------------------------

    def _join(
        self, side: JoinSide, rows: EventBatch, other: JoinSide, out_type: int
    ) -> Optional[EventBatch]:
        probe_env = None
        if other.aggregation is not None and len(rows):
            # `within`/`per` may reference the arriving event's attributes;
            # evaluate them on the first probing row
            probe_env = {
                side.qualified_key(a.name): rows.columns[a.name][:1]
                for a in side.definition.attributes
            }
            probe_env[TS_KEY] = rows.timestamps[:1]
            probe_env[N_KEY] = 1
        buf = other.buffered(probe_env)
        n_a = len(rows)
        n_b = len(buf) if buf is not None else 0
        is_outer = self._side_outer(side)

        if n_b == 0:
            if not is_outer:
                return None
            return self._with_nulls(side, rows, other, out_type)

        # cross-product condition evaluation: A-rows repeated, B-rows tiled
        env: Dict[str, np.ndarray] = {}
        for a in side.definition.attributes:
            env[side.qualified_key(a.name)] = np.repeat(rows.columns[a.name], n_b)
        for a in other.definition.attributes:
            env[other.qualified_key(a.name)] = np.tile(buf.columns[a.name], n_a)
        env[TS_KEY] = np.repeat(rows.timestamps, n_b)
        env[N_KEY] = n_a * n_b
        if self.condition is None:
            mask = np.ones(n_a * n_b, dtype=bool)
        else:
            mask = np.broadcast_to(np.asarray(self.condition.fn(env)), (n_a * n_b,))

        cols = {k: v[mask] for k, v in env.items() if k not in (TS_KEY, N_KEY)}
        ts = env[TS_KEY][mask]
        out = EventBatch(
            self.out_stream_id,
            self._out_names,
            {k: cols[k] for k in self._out_names},
            ts,
            np.full(int(mask.sum()), out_type, dtype=np.int8),
        )
        if is_outer:
            matched_any = mask.reshape(n_a, n_b).any(axis=1)
            if not matched_any.all():
                unmatched = rows.mask(~matched_any)
                out = EventBatch.concat(
                    [out, self._with_nulls(side, unmatched, other, out_type)]
                )
        return out if len(out) else None

    def _side_outer(self, side: JoinSide) -> bool:
        """Does this trigger side emit unmatched rows (with the other side
        nulled)?  LEFT_OUTER preserves left rows, etc."""
        if self.join_type == JoinInputStream.FULL_OUTER:
            return True
        if self.join_type == JoinInputStream.LEFT_OUTER:
            return side is self.left
        if self.join_type == JoinInputStream.RIGHT_OUTER:
            return side is self.right
        return False

    def _with_nulls(
        self, side: JoinSide, rows: EventBatch, other: JoinSide, out_type: int
    ) -> EventBatch:
        n = len(rows)
        cols: Dict[str, np.ndarray] = {}
        for a in side.definition.attributes:
            cols[side.qualified_key(a.name)] = rows.columns[a.name]
        for a in other.definition.attributes:
            cols[other.qualified_key(a.name)] = _null_column(a.type, n)
        return EventBatch(
            self.out_stream_id,
            self._out_names,
            {k: cols[k] for k in self._out_names},
            rows.timestamps,
            np.full(n, out_type, dtype=np.int8),
        )


class JoinStreamReceiver:
    """Junction subscriber feeding one side of the join."""

    def __init__(self, join_runtime: JoinRuntime, side_is_left: bool, app_context):
        self.join_runtime = join_runtime
        self.side_is_left = side_is_left
        self.app_context = app_context

    def receive(self, batch: EventBatch):
        now = self.app_context.timestamp_generator.current_time()
        self.join_runtime.on_event(self.side_is_left, batch, now)
