"""Stream bus: junctions, input handlers, callbacks.

Re-design of the reference ``core/stream/`` (StreamJunction.java:61,
InputManager.java:33).  A junction is the per-stream pub/sub hub.  The
default mode is synchronous depth-first fan-out of columnar batches (the
reference's sync mode, StreamJunction.java:166-178); ``@async`` marks a
junction for host-side micro-batching: a queue + worker that coalesces
small sends into larger device-friendly batches (the Disruptor analog,
StreamJunction.java:276-313).

``@OnError(action='stream')`` routes failures to an auto-defined fault
stream ``!name`` with the original attributes plus ``_error``
(reference: StreamJunction.handleError:368-430).
"""

from __future__ import annotations

import logging
import queue
import threading
import traceback
from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from siddhi_tpu.core import event as ev
from siddhi_tpu.core.context import SiddhiAppContext
from siddhi_tpu.core.event import (
    Event,
    EventBatch,
    batch_from_events,
    batch_from_rows,
    events_from_batch,
)
from siddhi_tpu.core.exceptions import OnErrorAction, SiddhiAppRuntimeError
from siddhi_tpu.query_api.definition import StreamDefinition

log = logging.getLogger("siddhi_tpu")


class StreamCallback:
    """User subscriber on a stream (reference:
    stream/output/StreamCallback.java).  Subclass and override
    ``receive`` or wrap a plain function via ``FunctionStreamCallback``."""

    stream_id: Optional[str] = None

    def receive(self, events: List[Event]):
        raise NotImplementedError

    def receive_batch(self, batch: EventBatch):
        """Columnar fast path; default converts to row events."""
        self.receive(events_from_batch(batch))


class FunctionStreamCallback(StreamCallback):
    def __init__(self, fn: Callable[[List[Event]], None]):
        self.fn = fn

    def receive(self, events: List[Event]):
        self.fn(events)


class QueryCallback:
    """Per-query subscriber receiving (timestamp, current, expired)
    (reference: query/output/callback/QueryCallback)."""

    def receive(self, timestamp: int, in_events: Optional[List[Event]], out_events: Optional[List[Event]]):
        raise NotImplementedError


class FunctionQueryCallback(QueryCallback):
    def __init__(self, fn):
        self.fn = fn

    def receive(self, timestamp, in_events, out_events):
        self.fn(timestamp, in_events, out_events)


class StreamJunction:
    """Per-stream pub/sub hub carrying columnar batches."""

    def __init__(
        self,
        definition: StreamDefinition,
        app_context: SiddhiAppContext,
        is_async: bool = False,
        buffer_size: int = 1024,
        batch_size_max: Optional[int] = None,
        on_error: str = OnErrorAction.LOG,
        fault_junction: Optional["StreamJunction"] = None,
    ):
        self.definition = definition
        self.stream_id = definition.id
        self.app_context = app_context
        self.receivers: List = []  # objects with .receive(EventBatch)
        self.callbacks: List[StreamCallback] = []
        self.on_error = on_error
        self.fault_junction = fault_junction
        self.is_async = is_async
        self.batch_size_max = batch_size_max or buffer_size
        self._queue: Optional[queue.Queue] = queue.Queue(maxsize=buffer_size) if is_async else None
        self._worker: Optional[threading.Thread] = None
        self._running = False
        self.throughput_tracker = None  # set when statistics enabled
        # dispatch cycles through this junction (host hop accounting:
        # fused chains keep this at 0 on intermediate streams — the
        # bench/test `junctionHops` counter)
        self.dispatches = 0

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        self._running = True
        if self.is_async:
            self._worker = threading.Thread(
                target=self._drain, name=f"junction-{self.stream_id}", daemon=True
            )
            self._worker.start()

    def stop(self):
        self._running = False
        if self._worker is not None:
            # the worker exits via the _running flag after its current
            # dispatch; the sentinel only matters when it is parked in
            # get() on an EMPTY queue — so never block on a FULL one
            # (a blocking put here deadlocks: the flagged worker stops
            # consuming and the queue never drains)
            try:
                self._queue.put_nowait(None)
            except queue.Full:
                pass
            self._worker.join(timeout=5)
            self._worker = None
            # free ring slots so producer threads blocked in put() on a
            # full queue complete their (discarded — pending batches are
            # dropped at stop) send instead of blocking forever
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass

    def subscribe(self, receiver):
        if receiver not in self.receivers:
            self.receivers.append(receiver)

    def add_callback(self, callback: StreamCallback):
        callback.stream_id = self.stream_id
        self.callbacks.append(callback)

    # -- send paths ---------------------------------------------------------

    def send(self, batch: EventBatch):
        if len(batch) == 0:
            return
        if self.throughput_tracker is not None:
            self.throughput_tracker.add(len(batch))
        if self.is_async and self._running:
            jr = getattr(self.app_context, "input_journal", None)
            if jr is not None and jr.replaying:
                # journal replay (replan / restore) runs single-threaded
                # under the process lock on FRESH junctions whose queues
                # are empty: dispatch inline so every re-delivery crosses
                # the suppressing ledger INSIDE the replay window — a
                # queued batch the worker dispatches after end_replay()
                # would escape suppression and double-emit
                self._dispatch(batch)
                return
            self._queue.put(batch)
            return
        self._dispatch(batch)

    def _drain(self):
        """Async worker: coalesce queued batches up to batch_size_max —
        micro-batching for device efficiency (the StreamHandler batching
        analog, util/event/handler/StreamHandler.java:57)."""
        while self._running:
            item = self._queue.get()
            if item is None:
                break
            batches = [item]
            total = len(item)
            while total < self.batch_size_max:
                try:
                    nxt = self._queue.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._running = False
                    break
                batches.append(nxt)
                total += len(nxt)
            self._dispatch(EventBatch.concat(batches))

    def _dispatch(self, batch: EventBatch):
        self.dispatches += 1
        # watchdog liveness: one beat per dispatched batch (robustness/)
        self.app_context.progress.beat()
        for r in self.receivers:
            try:
                r.receive(batch)
            except Exception as e:  # noqa: BLE001 — fault-stream contract
                self._handle_error(batch, e)
        if self.callbacks:
            # crash-recovery output ledger: receivers (query chains)
            # always reprocess during replay — they rebuild state — but
            # user-visible callbacks get the already-delivered prefix
            # suppressed so the observable sequence never duplicates
            jr = getattr(self.app_context, "input_journal", None)
            cb_batch = batch
            if jr is not None:
                cb_batch = jr.deliver(("stream", self.stream_id), batch)
                if cb_batch is None:
                    return
            fi = getattr(self.app_context, "fault_injector", None)
            for cb in self.callbacks:
                try:
                    if fi is not None:
                        fi.check("callback")
                    cb.receive_batch(cb_batch)
                except Exception as e:  # noqa: BLE001
                    self._handle_error(cb_batch, e)

    def route_fault(self, batch: EventBatch, e: Exception) -> bool:
        """Send ``batch`` + the error into this stream's ``!stream``
        fault junction (the @OnError(action='STREAM') contract); False
        when no STREAM fault route is configured.  Shared by the
        processing chain (_handle_error) and sink publish failures
        (Sink.on_error)."""
        if self.on_error != OnErrorAction.STREAM or self.fault_junction is None:
            return False
        fd = self.fault_junction.definition
        err = np.empty(len(batch), dtype=object)
        err[:] = e
        cols = dict(batch.columns)
        cols["_error"] = err
        self.fault_junction.send(
            EventBatch(fd.id, fd.attribute_names, cols, batch.timestamps, batch.types)
        )
        return True

    def _handle_error(self, batch: EventBatch, e: Exception):
        if self.route_fault(batch, e):
            return
        log.error(
            "error processing events on stream '%s' in app '%s': %s",
            self.stream_id,
            self.app_context.name,
            e,
            exc_info=e,
        )
        for listener in self.app_context.exception_listeners:
            listener(e)


class InputHandler:
    """External event entry for one stream (reference:
    stream/input/InputHandler.java:50-97).  Accepts single events, rows,
    or lists; stamps timestamps from the app clock when absent."""

    def __init__(self, junction: StreamJunction, app_context: SiddhiAppContext):
        self.junction = junction
        self.app_context = app_context
        self.definition = junction.definition

    def _check_running(self):
        # reference: InputHandler.send throws when the app is not
        # running (InputHandler.java:50-97 "cannot send event")
        if not getattr(self.app_context, "app_running", True):
            raise SiddhiAppRuntimeError(
                f"Siddhi app '{self.app_context.name}' is not running, "
                "cannot send events")

    def send(self, data: Union[Event, Sequence, List[Event]], timestamp: Optional[int] = None):
        self._check_running()
        tsgen = self.app_context.timestamp_generator
        if isinstance(data, Event):
            events = [data]
        elif isinstance(data, list) and data and isinstance(data[0], Event):
            events = data
        else:
            ts = timestamp if timestamp is not None else tsgen.current_time()
            events = [Event(ts, list(data))]
        for e in events:
            if e.timestamp < 0:
                e.timestamp = tsgen.current_time()
            tsgen.set_event_time(e.timestamp)
        batch = batch_from_events(self.definition, events)
        batch = self._admit(batch)
        if batch is None:
            return
        with self.app_context.process_lock:
            self._journal_and_check(batch)
            scheduler = self.app_context.scheduler
            if scheduler is not None:
                scheduler.advance(tsgen.current_time())
            self.junction.send(batch)

    def send_batch(self, batch: EventBatch):
        self._check_running()
        if len(batch):
            # event time is monotone-max; one update per batch suffices
            self.app_context.timestamp_generator.set_event_time(
                int(batch.timestamps.max()))
        batch = self._admit(batch)
        if batch is None:
            return
        with self.app_context.process_lock:
            self._journal_and_check(batch)
            scheduler = self.app_context.scheduler
            if scheduler is not None:
                scheduler.advance(self.app_context.timestamp_generator.current_time())
            self.junction.send(batch)

    def _admit(self, batch: EventBatch) -> Optional[EventBatch]:
        """Admission control (@app:limits, robustness/admission.py):
        trim the batch to the per-stream token budget BEFORE journaling
        — the journal records only admitted events, so a replay
        reproduces exactly the admitted set.  Replay itself bypasses
        admission (the decision was already made and journaled); apps
        without the annotation take the None fast path unchanged."""
        ac = getattr(self.app_context, "admission", None)
        if ac is None:
            return batch
        jr = getattr(self.app_context, "input_journal", None)
        if jr is not None and jr.replaying:
            return batch
        return ac.admit(self.junction.stream_id, batch)

    def _journal_and_check(self, batch: EventBatch):
        """Crash-recovery hook (under the process lock): journal the
        batch for restore-and-replay, then give the ``ingest`` injection
        site its shot.  A crash injected here fires AFTER the record —
        the batch is committed to the journal but never delivered, the
        exact state replay exists to repair."""
        jr = getattr(self.app_context, "input_journal", None)
        if jr is not None:
            jr.record(self.junction.stream_id, batch)
        # watchdog liveness: ingest accepted work (robustness/)
        self.app_context.progress.beat()
        fi = getattr(self.app_context, "fault_injector", None)
        if fi is not None:
            fi.check("ingest")


class InputManager:
    """Registry of input handlers (reference: stream/input/InputManager.java:33)."""

    def __init__(self, app_context: SiddhiAppContext):
        self.app_context = app_context
        self._handlers: Dict[str, InputHandler] = {}
        self._junctions: Dict[str, StreamJunction] = {}

    def register(self, junction: StreamJunction):
        self._junctions[junction.stream_id] = junction

    def get_input_handler(self, stream_id: str) -> InputHandler:
        if stream_id not in self._handlers:
            if stream_id not in self._junctions:
                raise SiddhiAppRuntimeError(f"stream '{stream_id}' is not defined")
            self._handlers[stream_id] = InputHandler(self._junctions[stream_id], self.app_context)
        return self._handlers[stream_id]
