"""Triggers: ``define trigger T at ('start' | every <t> | '<cron>')``.

Re-design of the reference ``core/trigger/`` (PeriodicTrigger /
CronTrigger / StartTrigger) without Quartz: periodic and cron triggers
are scheduler tasks computing their next fire time; each fire posts one
event ``[triggered_time]`` into the trigger's stream junction.
"""

from __future__ import annotations

import calendar
import datetime
from typing import List, Optional, Set

import numpy as np

from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.exceptions import SiddhiAppCreationError


# ---------------------------------------------------------------------------
# Minimal cron (Quartz 6/7-field or unix 5-field) next-fire computation
# ---------------------------------------------------------------------------


def _parse_field(spec: str, lo: int, hi: int, names=None) -> Set[int]:
    out: Set[int] = set()
    for part in spec.split(","):
        part = part.strip()
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
        if part in ("*", "?", ""):
            lo2, hi2 = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            lo2, hi2 = _name_to_int(a, names), _name_to_int(b, names)
        else:
            v = _name_to_int(part, names)
            lo2 = hi2 = v if step == 1 else v
            if step != 1:
                hi2 = hi
        for v in range(lo2, hi2 + 1, step):
            if lo <= v <= hi:
                out.add(v)
    return out


def _name_to_int(s: str, names) -> int:
    s = s.strip()
    if names and s.upper() in names:
        return names[s.upper()]
    return int(s)


_MONTHS = {m.upper(): i + 1 for i, m in enumerate(calendar.month_abbr[1:])}
# cron: 0/7=SUN..6=SAT ; python weekday(): 0=MON..6=SUN
_DOWS = {"SUN": 0, "MON": 1, "TUE": 2, "WED": 3, "THU": 4, "FRI": 5, "SAT": 6}


def _dow_field(spec: str, is_unix: bool) -> Set[int]:
    """Day-of-week field -> 0-based set (0=SUN..6=SAT).  Numeric values
    follow the expression dialect: unix 0/7=SUN..6=SAT, Quartz 1=SUN..7=SAT."""
    s = spec.upper()
    for name, num in _DOWS.items():
        s = s.replace(name, str(num if is_unix else num + 1))
    vals = _parse_field(s, 0, 7)
    if is_unix:
        return {v % 7 for v in vals}
    return {(v - 1) % 7 for v in vals}


class CronSchedule:
    """Parses a cron expression and computes next fire times (second
    granularity).  Accepts unix 5-field (min hour dom mon dow) and Quartz
    6/7-field (sec min hour dom mon dow [year])."""

    def __init__(self, expr: str):
        fields = expr.split()
        is_unix = len(fields) == 5
        if is_unix:
            fields = ["0"] + fields  # unix form: fire at second 0
        if len(fields) == 7:
            fields = fields[:6]  # ignore the year field
        if len(fields) != 6:
            raise SiddhiAppCreationError(f"invalid cron expression '{expr}'")
        sec, mnt, hr, dom, mon, dow = fields
        self.seconds = sorted(_parse_field(sec, 0, 59))
        self.minutes = sorted(_parse_field(mnt, 0, 59))
        self.hours = sorted(_parse_field(hr, 0, 23))
        self.dom = _parse_field(dom, 1, 31)
        self.months = _parse_field(mon, 1, 12, _MONTHS)
        self.dow = _dow_field(dow, is_unix)
        self.dom_any = dom.strip() in ("*", "?")
        self.dow_any = dow.strip() in ("*", "?")

    def _day_matches(self, d: datetime.date) -> bool:
        if d.month not in self.months:
            return False
        dom_ok = d.day in self.dom
        dow_ok = ((d.weekday() + 1) % 7) in self.dow  # python MON=0 -> cron SUN=0
        if self.dom_any and self.dow_any:
            return True
        if self.dom_any:
            return dow_ok
        if self.dow_any:
            return dom_ok
        return dom_ok or dow_ok  # Quartz semantics: either restricted field

    def next_fire(self, after_ms: int) -> Optional[int]:
        t = datetime.datetime.fromtimestamp(
            after_ms / 1000.0, datetime.timezone.utc
        ).replace(microsecond=0, tzinfo=None)
        t += datetime.timedelta(seconds=1)
        day = t.date()
        for _ in range(1500):  # ~4 years of days
            if self._day_matches(day):
                start_h, start_m, start_s = (
                    (t.hour, t.minute, t.second) if day == t.date() else (0, 0, 0)
                )
                for h in self.hours:
                    if h < start_h:
                        continue
                    for m in self.minutes:
                        if h == start_h and m < start_m:
                            continue
                        for s in self.seconds:
                            if h == start_h and m == start_m and s < start_s:
                                continue
                            dt = datetime.datetime(
                                day.year, day.month, day.day, h, m, s,
                                tzinfo=datetime.timezone.utc,
                            )
                            return int(dt.timestamp() * 1000)
            day += datetime.timedelta(days=1)
        return None


class TriggerRuntime:
    """Scheduler task injecting timer events into the trigger stream
    (reference: trigger/PeriodicTrigger.java, CronTrigger.java,
    StartTrigger.java)."""

    def __init__(self, definition, junction, app_context):
        self.definition = definition
        self.junction = junction
        self.app_context = app_context
        self._next: Optional[int] = None
        self._cron = CronSchedule(definition.at_cron) if definition.at_cron else None

    def on_start(self, now: int):
        if self.definition.at_start:
            self._send(now)
        if self.definition.at_every_ms is not None:
            self._next = now + self.definition.at_every_ms
        elif self._cron is not None:
            self._next = self._cron.next_fire(now)

    def next_wakeup(self) -> Optional[int]:
        return self._next

    def fire(self, now: int):
        while self._next is not None and self._next <= now:
            fire_at = self._next
            if self.definition.at_every_ms is not None:
                self._next = fire_at + self.definition.at_every_ms
            elif self._cron is not None:
                self._next = self._cron.next_fire(fire_at)
            else:
                self._next = None
            self._send(fire_at)

    def _send(self, ts: int):
        batch = EventBatch(
            self.junction.stream_id,
            ["triggered_time"],
            {"triggered_time": np.asarray([ts], dtype=np.int64)},
            np.asarray([ts], dtype=np.int64),
        )
        self.junction.send(batch)
