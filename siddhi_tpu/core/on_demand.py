"""On-demand (pull) queries: ``runtime.query("from Table select ...")``.

Re-design of the reference ``query/OnDemandQueryRuntime.java`` +
``util/parser/OnDemandQueryParser.java:101``: a pull query targets a table,
named window, or incremental aggregation; FIND evaluates the compiled
condition vectorized over the store's row batch and applies a one-shot
selector (projection / group-by / aggregators / having / order-limit);
INSERT / DELETE / UPDATE / UPDATE-OR-INSERT build a single synthetic row
from the select clause and reuse the table mutation callbacks.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core.event import Event, EventBatch, events_from_batch
from siddhi_tpu.core.exceptions import StoreQueryCreationError
from siddhi_tpu.core.query import QuerySelector, SelectItem, build_env
from siddhi_tpu.planner.expr import ExpressionCompiler, N_KEY, Scope, TS_KEY
from siddhi_tpu.planner.query_planner import AggregatorRewrite
from siddhi_tpu.query_api import (
    Attribute,
    AttrType,
    DeleteStream,
    InsertIntoStream,
    OnDemandQuery,
    UpdateOrInsertStream,
    UpdateStream,
    Variable,
)



def _rewrite_stream_refs(expr, old_ref: str, new_ref: str):
    """Replace Variable stream references 'old_ref.x' -> 'new_ref.x'
    throughout an expression tree (frozen dataclasses -> rebuild)."""
    import dataclasses

    from siddhi_tpu.query_api import expression as X

    def walk(e):
        if isinstance(e, X.Variable):
            if e.stream_id == old_ref:
                return dataclasses.replace(e, stream_id=new_ref)
            return e
        if isinstance(e, X.FunctionCall):
            return dataclasses.replace(e, args=tuple(walk(a) for a in e.args))
        changes = {}
        for f in ("left", "right", "expr"):
            child = getattr(e, f, None)
            if isinstance(child, X.Expression):
                changes[f] = walk(child)
        return dataclasses.replace(e, **changes) if changes else e

    return walk(expr)


class OnDemandQueryRuntime:
    """One compiled on-demand query, re-executable (the reference caches
    these in SiddhiAppRuntimeImpl.onDemandQueryRuntimeMap, cap 50)."""

    def __init__(self, odq: OnDemandQuery, app_runtime):
        self.odq = odq
        self.app = app_runtime
        self.type = odq.type
        self._plan()

    # -- planning -----------------------------------------------------------

    def _source(self, name: str):
        """table | named window | aggregation by id."""
        t = self.app.tables.get(name)
        if t is not None:
            return ("table", t)
        w = self.app.named_windows.get(name)
        if w is not None:
            return ("window", w)
        a = self.app.aggregations.get(name)
        if a is not None:
            return ("aggregation", a)
        raise StoreQueryCreationError(
            f"on-demand query: no table/window/aggregation named '{name}'"
        )

    def _store_attributes(self, kind, store) -> List[Attribute]:
        if kind == "aggregation":
            return list(store.output_definition.attributes)
        return list(store.definition.attributes)

    def _plan(self):
        odq = self.odq
        if odq.type == "find" or (odq.input_store is not None and odq.type in (
            "delete", "update", "update_or_insert"
        )):
            self.kind, self.store = self._source(odq.input_store)
        else:
            # `select ... insert into T` / `... update T ...` forms
            target = odq.output_stream.target
            self.kind, self.store = self._source(target)
            if self.kind != "table":
                raise StoreQueryCreationError(
                    f"on-demand {odq.type}: '{target}' is not a table"
                )

        ref = odq.input_alias or odq.input_store or self.store.definition.id
        attrs = self._store_attributes(self.kind, self.store)

        scope = Scope()
        for a in attrs:
            scope.add(ref, a.name, a.name, a.type)
        if odq.input_store is not None and odq.input_alias:
            scope.add_alias(odq.input_store, ref)
        self.scope = scope
        self.compiler = ExpressionCompiler(
            scope,
            functions=getattr(self.app, "functions", None),
            table_resolver=getattr(self.app, "table_resolver", None),
        )

        # condition over store rows
        self.condition = None
        self._pushdown = None
        if odq.on_condition is not None:
            c = self.compiler.compile(odq.on_condition)
            if c.type != AttrType.BOOL:
                raise StoreQueryCreationError("'on' condition must be boolean")
            self.condition = c
            if self.kind == "table":
                from siddhi_tpu.table.record import RecordTableRuntime

                if isinstance(self.store, RecordTableRuntime):
                    # push the condition to the external store instead of
                    # fetching every record and filtering host-side; an
                    # input alias is normalized to the table id first so
                    # the merged table scope resolves it
                    from siddhi_tpu.table.table import compile_table_condition

                    cond = odq.on_condition
                    if odq.input_alias and odq.input_alias != self.store.table_id:
                        cond = _rewrite_stream_refs(
                            cond, odq.input_alias, self.store.table_id)
                    self._pushdown = compile_table_condition(
                        self.store, cond, Scope(),
                        extra_functions=getattr(self.app, "functions", None),
                        table_resolver=getattr(self.app, "table_resolver", None),
                    )
                    self.condition = None

        # aggregation access clauses
        self.per = None
        self.within = None
        if self.kind == "aggregation":
            if odq.per is None:
                raise StoreQueryCreationError(
                    f"aggregation '{odq.input_store}': 'per' clause is required"
                )
            self.per = self.compiler.compile(odq.per)
            if odq.within is not None:
                start, end = odq.within
                self.within = (
                    self.compiler.compile(start),
                    self.compiler.compile(end) if end is not None else None,
                )
        elif odq.per is not None or odq.within is not None:
            raise StoreQueryCreationError(
                "'within'/'per' clauses only apply to aggregations"
            )

        # selector
        sel = odq.selector
        rewriter = AggregatorRewrite(
            scope, self.compiler,
            extensions=getattr(self.app, "extensions", None))
        items: Optional[List[SelectItem]] = None
        out_attrs: List[Attribute] = []
        if sel.is_select_all:
            out_attrs = list(attrs)
            out_names = [a.name for a in attrs]
        else:
            items = []
            for oa in sel.selection:
                rewritten = rewriter.rewrite(oa.expression)
                compiled = self.compiler.compile(rewritten)
                nm = oa.rename or (
                    oa.expression.attribute
                    if isinstance(oa.expression, Variable)
                    else None
                )
                if nm is None:
                    raise StoreQueryCreationError(
                        "select expression needs 'as <name>'"
                    )
                items.append(SelectItem(nm, compiled))
                out_attrs.append(Attribute(nm, compiled.type))
            out_names = [i.name for i in items]
            for a in out_attrs:
                scope.add_bare(a.name, a.type)
        group_keys = [self.compiler.compile(g) for g in sel.group_by]
        having = (
            self.compiler.compile(rewriter.rewrite(sel.having))
            if sel.having is not None
            else None
        )
        order_by = []
        for ob in sel.order_by:
            if ob.variable.attribute not in out_names:
                raise StoreQueryCreationError(
                    f"order by attribute '{ob.variable.attribute}' not in select output"
                )
            order_by.append((ob.variable.attribute, ob.ascending))

        def const_int(e):
            if e is None:
                return None
            return int(self.compiler.compile(e).fn({N_KEY: 0}))

        self._selector_args = (
            items, out_names, rewriter.bindings, group_keys, having,
            order_by, const_int(sel.limit), const_int(sel.offset),
        )
        self.output_attributes = out_attrs
        self.out_names = out_names

        # mutation plumbing
        if odq.type in ("update", "update_or_insert"):
            from siddhi_tpu.table.callbacks import compile_set_clause

            set_clause = getattr(odq.output_stream, "set_clause", None)
            event_scope = Scope()
            for a in out_attrs:
                event_scope.add_bare(a.name, a.type)
            self.set_ops = compile_set_clause(
                self._target_table(), set_clause, event_scope, out_names
            )
            self.mutate_condition = self._compile_table_condition(event_scope)
        elif odq.type == "delete":
            event_scope = Scope()
            for a in out_attrs:
                event_scope.add_bare(a.name, a.type)
            self.mutate_condition = self._compile_table_condition(event_scope)

    def _target_table(self):
        if self.odq.input_store is not None:
            if self.kind != "table":
                raise StoreQueryCreationError(
                    f"on-demand {self.odq.type} targets a table, got {self.kind}"
                )
            return self.store
        return self.store

    def _compile_table_condition(self, event_scope: Scope):
        from siddhi_tpu.table.table import compile_table_condition

        cond = getattr(self.odq.output_stream, "on_condition", None)
        if cond is None:
            cond = self.odq.on_condition
        return compile_table_condition(
            self._target_table(), cond, event_scope,
            extra_functions=getattr(self.app, "functions", None),
            table_resolver=getattr(self.app, "table_resolver", None),
        )

    # -- execution ----------------------------------------------------------

    def _rows(self) -> Optional[EventBatch]:
        if self.kind == "table":
            if self._pushdown is not None:
                slots = self._pushdown.slots_matching({N_KEY: 1})
                return self.store.rows_batch(slots)
            return self.store.rows_batch()
        if self.kind == "window":
            return self.store.buffered()
        # aggregation
        from siddhi_tpu.aggregation.runtime import within_bounds

        env = {N_KEY: 0}
        per = str(np.asarray(self.per.fn(env)).ravel()[0])
        within = None
        if self.within is not None:
            start_c, end_c = self.within
            v1 = np.asarray(start_c.fn(env)).ravel()[0]
            v2 = np.asarray(end_c.fn(env)).ravel()[0] if end_c is not None else None
            within = within_bounds(v1, v2)
        return self.store.find(per, within)

    def execute(self) -> List[Event]:
        # pull queries race the event path and the wall-clock scheduler;
        # both mutate store state under the app's process lock
        with self.app.app_context.process_lock:
            return self._execute_locked()

    def _execute_locked(self) -> List[Event]:
        odq = self.odq
        if odq.type == "find":
            return self._execute_find()
        if odq.type == "insert":
            row = self._synthetic_row()
            from siddhi_tpu.table.callbacks import InsertIntoTableCallback

            InsertIntoTableCallback(
                self._target_table(), "current", self.out_names
            ).send(row, 0)
            return []
        if odq.type == "delete":
            row = self._synthetic_row()
            from siddhi_tpu.table.callbacks import DeleteTableCallback

            DeleteTableCallback(
                self._target_table(), self.mutate_condition, "current"
            ).send(row, 0)
            return []
        if odq.type == "update":
            row = self._synthetic_row()
            from siddhi_tpu.table.callbacks import UpdateTableCallback

            UpdateTableCallback(
                self._target_table(), self.mutate_condition, self.set_ops, "current"
            ).send(row, 0)
            return []
        if odq.type == "update_or_insert":
            row = self._synthetic_row()
            from siddhi_tpu.table.callbacks import UpdateOrInsertTableCallback

            UpdateOrInsertTableCallback(
                self._target_table(), self.mutate_condition, self.set_ops,
                "current", self.out_names,
            ).send(row, 0)
            return []
        raise StoreQueryCreationError(f"unknown on-demand query type '{odq.type}'")

    def _execute_find(self) -> List[Event]:
        rows = self._rows()
        if rows is None or len(rows) == 0:
            return []
        if self.condition is not None:
            env = build_env(rows)
            mask = np.broadcast_to(np.asarray(self.condition.fn(env)), (len(rows),))
            rows = rows.mask(mask)
            if len(rows) == 0:
                return []
        items, out_names, bindings, group_keys, having, order_by, limit, offset = (
            self._selector_args
        )
        # fresh selector per execution: aggregator state must not leak
        # between pulls; batch_mode emits one row per group
        selector = QuerySelector(
            "__on_demand", items, out_names,
            bindings,
            group_keys, having, order_by, limit, offset,
            batch_mode=True,
        )
        out = selector.process(rows, 0)
        return events_from_batch(out)

    def _synthetic_row(self) -> EventBatch:
        """Evaluate the select clause on a single empty row (constants +
        functions only) — the matching-side event of mutation queries."""
        items, out_names, bindings, *_ = self._selector_args
        if items is None:
            raise StoreQueryCreationError(
                f"on-demand {self.odq.type}: explicit select clause required"
            )
        if bindings:
            raise StoreQueryCreationError(
                f"on-demand {self.odq.type}: aggregators not allowed in select"
            )
        env = {N_KEY: 1, TS_KEY: np.zeros(1, dtype=np.int64)}
        cols: Dict[str, np.ndarray] = {}
        for item in items:
            v = np.asarray(item.compiled.fn(env))
            cols[item.name] = v.reshape(1) if v.ndim == 0 else v[:1]
        return EventBatch("__on_demand", out_names, cols, np.zeros(1, dtype=np.int64))
