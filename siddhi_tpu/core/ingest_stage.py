"""Ingest-side staging pipeline: double-buffered H2D puts.

The emit (D2H) side has been queued and coalesced since the async emit
pipeline landed (core/emit_queue.py); the input side still paid one
synchronous round trip per batch — not on the ``device_put`` itself
(JAX enqueues transfers asynchronously) but on the ``int(n_match)``
count-gate fetch that every engine performed right after dispatching
its jitted step.  That fetch blocks until the H2D transfer AND the step
finish, so transfer and compute for consecutive batches were fully
serialized.

This module holds the pieces every device runtime shares:

- ``IngestStats``: per-runtime staging counters surfaced through
  ``util/statistics.py`` (``stagedBatches`` / ``devicePuts`` /
  ``ingestStalls`` / ``overlappedBatches`` / ``flushSyncs`` /
  ``maxStagingDepth``).
- ``IngestStage``: a bounded staging window.  ``submit(probe, finish)``
  records one dispatched batch whose count gate has NOT been fetched
  yet; the oldest entry's ``finish`` (fetch count, enqueue/skip its
  emit) runs only once the window exceeds ``depth - 1`` entries.  With
  ``ingest.depth='2'`` the count fetch for batch N happens strictly
  AFTER batch N+1's conversion, ``device_put`` and step dispatch have
  been issued — H2D for N+1 overlaps the step for N.  Depth 1 (the
  default) finishes inline, byte-identical in timing to the
  pre-pipeline path.
- ``staged_put``: the single sanctioned ``jax.device_put`` wrapper for
  ingest paths — arms the ``ingest.put`` fault-injection site with the
  same bounded retry-with-backoff the sharded engine used, so the
  crash-recovery journal semantics of the fault harness hold on every
  engine (tests/test_ingest_guard.py enforces that no ingest path
  bypasses it).

Exactness contract: state advancement, key interning and timer
bookkeeping all still happen at receive time — ONLY the count fetch and
the emit enqueue defer, and those already have barrier discipline from
the emit queue.  Runtimes flush the stage at every point the emit queue
drains (snapshot/restore, pull queries, timer fires, shutdown,
debugger), and always BEFORE draining the emit queue, so callback
content and order stay bit-identical to synchronous ingest.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional, Tuple

from .exceptions import TransferFaultError

log = logging.getLogger("siddhi_tpu.ingest")


class IngestStats:
    """Staging counters for one device runtime (host-side ints, same
    thin-gauge style as ``EmitStats``)."""

    __slots__ = ("staged_batches", "device_puts", "ingest_stalls",
                 "overlapped_batches", "flush_syncs", "max_staging_depth",
                 "auto_depth")

    def __init__(self):
        self.staged_batches = 0
        self.device_puts = 0
        self.ingest_stalls = 0
        self.overlapped_batches = 0
        self.flush_syncs = 0
        self.max_staging_depth = 0
        # effective window when ingest.depth='auto' (0 = fixed depth)
        self.auto_depth = 0

    def note_depth(self, depth: int):
        if depth > self.max_staging_depth:
            self.max_staging_depth = depth

    def as_dict(self) -> dict:
        return {
            "stagedBatches": self.staged_batches,
            "devicePuts": self.device_puts,
            "ingestStalls": self.ingest_stalls,
            "overlappedBatches": self.overlapped_batches,
            "flushSyncs": self.flush_syncs,
            "maxStagingDepth": self.max_staging_depth,
            "autoIngestDepth": self.auto_depth,
        }


def staged_put(x, sharding=None, faults=None, stats: Optional[IngestStats] = None):
    """H2D ``device_put`` behind the ``ingest.put`` injection site.

    The one sanctioned ingest-path transfer primitive: arms the fault
    injector's ``ingest.put`` site (when a harness is configured) with
    the same bounded retry-with-backoff ladder the emit drain uses, so
    transient tunnel faults recover and sticky ones propagate.  Counts
    one ``device_puts`` per call when ``stats`` is supplied.
    """
    import jax

    if stats is not None:
        stats.device_puts += 1
    if faults is None:
        return (jax.device_put(x, sharding) if sharding is not None
                else jax.device_put(x))
    fi = faults
    attempts = fi.transfer_retry_attempts
    backoff = None
    attempt = 0
    while True:
        try:
            fi.check("ingest.put")
            out = (jax.device_put(x, sharding) if sharding is not None
                   else jax.device_put(x))
            if attempt:
                fi.stats.drains_recovered += 1
            return out
        except TransferFaultError:
            if attempt >= attempts:
                raise
            attempt += 1
            fi.stats.transfer_retries += 1
            if backoff is None:
                from ..transport.retry import BackoffRetryCounter

                backoff = BackoffRetryCounter(scale=fi.transfer_retry_scale)
            wait_s = backoff.get_time_interval_ms() / 1000.0
            backoff.increment()
            log.warning("ingest put: transient device_put fault; "
                        "retry %d/%d in %.3fs", attempt, attempts, wait_s)
            if wait_s > 0:
                time.sleep(wait_s)


class IngestStage:
    """Bounded per-runtime staging window (FIFO, depth >= 1).

    Each entry is one junction batch whose jitted step has been
    DISPATCHED but whose count gate has not been fetched: ``probe`` is a
    device scalar whose readiness marks step completion (None when the
    batch produced no device work) and ``finish()`` fetches the count
    and enqueues or skips the batch's emit.  ``submit`` finishes the
    oldest entries until at most ``depth - 1`` remain in flight, so the
    blocking fetch for batch N runs only after batch N+1's transfer and
    dispatch are already queued on the device stream.

    ``on_fault(exc)`` mirrors the emit queue's isolation hook: a finish
    failure is logged and routed there instead of killing the runtime
    (and instead of surfacing under an unrelated later batch).
    """

    def __init__(self, depth=1, stats: Optional[IngestStats] = None,
                 faults=None, on_fault: Optional[Callable] = None):
        # depth 'auto': bounded self-tuning with the SAME controller the
        # emit queue uses (core/emit_queue.py EmitDepthController) — the
        # staging window re-derives its depth each submit from the
        # observed count-fetch round trip vs the batch arrival cadence,
        # so slow fetches widen the window (more H2D/step overlap) and
        # fast ones shrink it back toward the depth-1 latency profile.
        self.controller = None
        if depth == "auto":
            from .emit_queue import EmitDepthController

            self.controller = EmitDepthController()
            depth = 1
        self.depth = max(1, int(depth))
        self.stats = stats or IngestStats()
        self.faults = faults
        self.on_fault = on_fault
        self._entries: List[Tuple[object, Callable, object]] = []

    def __len__(self) -> int:
        return len(self._entries)

    def submit(self, probe, finish: Callable, trace=None):
        """Stage one dispatched batch; finish entries past the window.

        ``trace`` is the batch's sampled cycle token (observability/
        trace.py CycleToken, or None): submit time is the boundary where
        receive-time work — conversion, the H2D put, the jitted step
        dispatch — is all queued, so the token's ingest span ends here
        and its step span starts."""
        if self.controller is not None:
            self.controller.note_push()
            self.depth = self.controller.effective_depth
            self.stats.auto_depth = self.depth
        self.stats.staged_batches += 1
        if trace is not None:
            trace.dispatched()
        self._entries.append((probe, finish, trace))
        self.stats.note_depth(len(self._entries))
        while len(self._entries) >= self.depth:
            self._finish_oldest(barrier=False)

    def flush(self):
        """Barrier: finish every in-flight batch in submit order.
        Called wherever host code could observe ingest/emit timing —
        always BEFORE the owning runtime drains its emit queue."""
        while self._entries:
            self.stats.flush_syncs += 1
            self._finish_oldest(barrier=True)

    def _finish_oldest(self, barrier: bool):
        probe, finish, trace = self._entries.pop(0)
        # overlap evidence: if the step's count scalar is already
        # resident when we get around to fetching it, the device did the
        # work while the host staged the next batch (overlap); if not,
        # the host is about to block on it (stall).  Barrier-forced
        # finishes are counted separately — a flush right after submit
        # says nothing about steady-state overlap.
        if probe is not None and not barrier:
            is_ready = getattr(probe, "is_ready", None)
            if is_ready is not None:
                try:
                    if is_ready():
                        self.stats.overlapped_batches += 1
                    else:
                        self.stats.ingest_stalls += 1
                except Exception:  # pragma: no cover - probe died
                    self.stats.ingest_stalls += 1
        # RTT sample for depth='auto': the wall time of finish() is
        # dominated by the blocking count-gate fetch when the batch had
        # device work (probe is not None)
        t0 = (time.monotonic()
              if self.controller is not None and probe is not None
              else None)
        try:
            finish()
        except Exception as err:
            log.error("ingest finish failed; dropping one staged "
                      "batch's emit: %s", err)
            if trace is not None:
                trace.aborted("step")
            if self.on_fault is not None:
                self.on_fault(err)
            return
        if t0 is not None:
            self.controller.note_drain(time.monotonic() - t0)
