"""Dense (jitted TPU) pattern execution inside the product engine.

This is the glue the planner uses to route `SiddhiManager`-created
pattern/sequence queries through the bit-parallel dense NFA
(ops/dense_nfa.py) instead of the host instance engine (ops/nfa.py) —
the analog of the reference planner wiring the pattern hot path into the
runtime (util/parser/StateInputStreamParser.java:76-146,
QueryParser.java:90), re-designed so the hot path is one jit-compiled
step over partition-sharded state rows instead of a processor chain.

Activation: ``@app:execution('tpu')`` (the north-star gating from
BASELINE.json).  The planner attempts dense lowering for every
pattern/sequence query and falls back to the host engine — logging the
reason — when the query needs semantics outside the dense subset
(leading/sequence absent states, optional min-0 nodes, >32 nodes,
non-numeric captures/filters/selects, partial-chain group-every, ...).
Mid-chain and trailing absent states (`not X for t`) run densely via
per-instance deadline registers and a jitted timer step driven by the
app scheduler (``DensePatternRuntime.on_time``); whole-chain
group-every (`every (e1 -> e2)`) runs densely with an
arm-when-empty virgin.  Overlapping `every` arms
run independently on the engine's instance axis (up to
``@app:execution('tpu', instances='N')`` per (partition, node), default
4); instances dropped when every successor lane is full are counted in
the engine's per-partition ``overflow`` state — explicit capacity where
the reference grows unbounded pending lists.

Partitioned form: ``partition with (key of S) begin <pattern query> end``
lowers to ONE dense engine whose partition axis is the interned key —
per-key NFA state rows in device memory, no per-key Python instances.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional

import numpy as np

from siddhi_tpu.core import event as ev
from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.exceptions import (
    SiddhiAppCreationError,
    SiddhiAppRuntimeError,
)
from siddhi_tpu.query_api import AttrType, StateInputStream, Variable

log = logging.getLogger("siddhi_tpu")


def build_dense_engine(query, st: StateInputStream, resolve_def,
                       n_partitions: int, n_instances: int = 4,
                       select_override=None, builder=None):
    """Lower one pattern/sequence query to a DensePatternEngine or raise
    SiddhiAppCreationError with the reason it is not dense-eligible.

    ``select_override=(vars, names)`` bypasses the plain-select-items
    requirement: the engine emits those raw capture columns and the
    CALLER owns selection semantics (the aggregating-selector form runs
    the host QuerySelector over dense match rows).  ``builder`` reuses a
    caller's NFABuilder (one lowering serves both the selector scope and
    the engine)."""
    from siddhi_tpu.ops.dense_nfa import DensePatternEngine
    from siddhi_tpu.ops.nfa import NFABuilder

    sel = query.selector
    if select_override is not None:
        select_vars, select_names = select_override
    else:
        if sel.group_by or sel.having is not None:
            raise SiddhiAppCreationError(
                "dense path: group-by/having selectors take the "
                "host-selector dense form")
        if not sel.selection:
            raise SiddhiAppCreationError(
                "dense path: select * is not supported for patterns")

        select_vars = []
        select_names = []
        for oa in sel.selection:
            if not isinstance(oa.expression, Variable) or oa.expression.stream_id is None:
                raise SiddhiAppCreationError(
                    "dense path: select items must be event references (e1.attr)")
            select_vars.append(oa.expression)
            select_names.append(oa.name)

    if builder is None:
        builder = NFABuilder(st, resolve_def)
        nodes = builder.build()
    else:
        # caller's builder already lowered (build() is not idempotent —
        # it appends); reuse its node chain
        nodes = builder.nodes
    for node in nodes:
        for spec in node.specs:
            if spec.filter_presence_keys:
                raise SiddhiAppCreationError(
                    "dense path: 'is null' event-presence checks need the "
                    "host engine")

    every_start = any(n.rearm_to is not None for n in nodes)
    eng = DensePatternEngine(
        nodes=nodes,
        ref_defs=builder.ref_defs,
        stream_to_ref=builder.stream_to_ref,
        within_ms=st.within_ms,
        n_partitions=n_partitions,
        select_vars=select_vars,
        select_names=select_names,
        every_start=every_start,
        # `every`: a match consumes only the matched instance — siblings
        # (incl. the re-armed start) keep running, as in the host engine;
        # non-every stops the partition's automaton after its match
        reset_on_emit=not every_start,
        is_sequence=st.type == StateInputStream.SEQUENCE,
        n_instances=n_instances,
    )

    # INT/LONG captures, filters (plain comparisons) and selects ride
    # the engine's bit-exact hi/lo int32 pair bank; integer usage the
    # pair compiler cannot express (arithmetic, functions) raises inside
    # _trace_check below and falls back to the host engine.  Non-numeric
    # captures/selects (STRING/BOOL/OBJECT) have no device lane at all —
    # they must fall back, not silently emit zeros.  String keys belong
    # on the partition axis.
    def _check_numeric(ref_def, attr, what):
        if ref_def is None or attr not in ref_def.attribute_names:
            raise SiddhiAppCreationError(f"dense path: cannot type {what}")
        t = ref_def.attribute_type(attr)
        if not t.is_numeric:
            raise SiddhiAppCreationError(
                f"dense path: {what} has type {t.value}; only numeric "
                "attributes have device lanes — host engine used")

    for (ref, attr, _last) in eng.alloc.slots:
        _check_numeric(builder.ref_defs.get(ref), attr,
                       f"capture '{ref}.{attr}'")
    for _name, src in eng.out_spec:
        if isinstance(src, tuple):
            ref_def = None
            for spec in nodes[-1].specs:
                if src[1] in spec.stream_def.attribute_names:
                    ref_def = spec.stream_def
            _check_numeric(ref_def, src[1], f"select attribute '{src[1]}'")

    _trace_check(eng)
    return eng


def output_attr_types(eng) -> List[AttrType]:
    """Declared attribute type of each engine output lane (the engine
    computes in float32; callbacks/definitions keep the source types)."""
    out: List[AttrType] = []
    for _name, src in eng.out_spec:
        t = None
        if isinstance(src, tuple):  # ('cand', attr): from the last node
            for node in eng.nodes:
                for spec in node.specs:
                    if src[1] in spec.stream_def.attribute_names:
                        t = spec.stream_def.attribute_type(src[1])
        else:
            d = eng.ref_defs.get(src.ref)
            if d is not None and src.attr in d.attribute_names:
                t = d.attribute_type(src.attr)
        out.append(t or AttrType.DOUBLE)
    return out


def _numeric_attrs(eng, stream_key: str) -> List[str]:
    """Delegates to the engine so the runtime's col dict and the sharded
    step's fixed in_specs structure can never diverge."""
    return eng.numeric_stream_attrs(stream_key)


def _trace_check(eng):
    """Abstractly trace every per-stream step with exactly the env the
    runtime will provide (numeric columns only) so ineligible filters —
    e.g. referencing a string attribute — fail at plan time, not on the
    first event (mirrors DeviceQueryEngine._trace_check)."""
    import jax

    host = eng.init_state_host()
    state_shapes = {
        k: jax.ShapeDtypeStruct(v.shape, v.dtype) for k, v in host.items()
    }
    B = 16
    i32 = jax.ShapeDtypeStruct((B,), np.int32)
    b1 = jax.ShapeDtypeStruct((B,), bool)
    try:
        for sk in eng.stream_keys:
            cols = {
                k: jax.ShapeDtypeStruct(
                    (B,), np.int32 if "|" in k else np.float32)
                for k in eng.device_col_keys(sk)
            }
            step = eng.make_step(sk, jit=False)
            jax.eval_shape(step, state_shapes, i32, cols, i32, b1)
        if eng.has_deadlines:
            tstep = eng.make_time_step(jit=False)
            jax.eval_shape(tstep, state_shapes,
                           jax.ShapeDtypeStruct((), np.int32))
    except SiddhiAppCreationError:
        raise
    except Exception as e:
        raise SiddhiAppCreationError(
            f"dense path: step not traceable ({e})") from e


class DensePatternRuntime:
    """Product-side wrapper of one DensePatternEngine: converts junction
    batches to device columns, advances state with the jitted step, and
    emits match batches into the query's selector/output chain.

    ``key_fn(batch) -> list`` supplies partition keys (a partitioned
    pattern); plain queries run as one partition (row 0).

    ``mesh``: shard the partition axis over a jax.sharding.Mesh
    (@app:execution('tpu', devices='N')) — state rows live shard-major
    behind a ShardedPatternEngine per source stream, interned keys route
    to their owning shard host-side, and emitted matches come back
    globally (the all-gather is the host fetch of the sharded output
    arrays).  Interned rows are dealt round-robin across shards so load
    spreads from the first key on.
    """

    def __init__(self, engine, out_stream_id: str,
                 emit: Callable[[EventBatch], None],
                 key_fn: Optional[Callable] = None,
                 mesh=None, app_context=None, emit_depth=1,
                 ingest_depth=1):  # int or 'auto'
        from siddhi_tpu.core.emit_queue import EmitQueue, EmitStats
        from siddhi_tpu.core.ingest_stage import IngestStage, IngestStats

        self.engine = engine
        self.out_stream_id = out_stream_id
        self.emit_cb = emit
        self.key_fn = key_fn
        self.mesh = mesh
        self.emit_stats = EmitStats()
        self._app_context = app_context  # exception-listener channel
        # cycle-correlated span tracer (observability/trace.py), shared
        # per app; dense spans carry the engine kind (shard when meshed)
        self.tracer = getattr(app_context, "tracer", None)
        self.engine_kind = "dense" if mesh is None else "shard"
        # @app:faults harness: wired onto the engine (the step hook
        # reads engine.faults) and the emit queue (drain retry +
        # isolation); None when chaos testing is off
        self.faults = getattr(app_context, "fault_injector", None)
        if self.faults is not None:
            engine.faults = self.faults
        self.emit_queue = EmitQueue(depth=emit_depth, stats=self.emit_stats,
                                    faults=self.faults,
                                    on_fault=self._on_fault)
        # ingest staging window (@app:execution('tpu', ingest.depth='N')):
        # depth 2 defers each batch's match-count fetch until the next
        # batch's H2D puts + step dispatch are in flight; depth 1
        # (default) finishes inline, matching synchronous timing.  The
        # engine carries the stats ref so staged_put counts device puts.
        self.ingest_stats = IngestStats()
        engine.ingest_stats = self.ingest_stats
        self.ingest_stage = IngestStage(
            depth=ingest_depth, stats=self.ingest_stats, faults=self.faults,
            on_fault=self._on_fault)
        self._sharded: Optional[Dict[str, object]] = None
        if mesh is not None:
            from siddhi_tpu.parallel.mesh import ShardedPatternEngine

            # one sharded wrapper per source stream (the jitted step is
            # per-stream); all share one shard-major state layout
            self._sharded = {
                sk: ShardedPatternEngine(engine, mesh, stream_key=sk)
                for sk in engine.stream_keys
            }
            first = next(iter(self._sharded.values()))
            self.n_shards = first.n_shards
            self.parts_per_shard = first.parts_per_shard
            self.state = first.init_state()
        else:
            self.state = engine.init_state()
        self.step_invocations = 0  # proof the jitted path ran (tests)
        self.time_fires = 0  # timer-driven (absent deadline) emissions
        # next_wakeup cache: the scheduler polls every send, but the
        # earliest deadline can only change when a step touched state —
        # recompute (one device reduce + scalar D2H) only then
        self._wake_cache = None
        self._wake_dirty = True
        # partitioned aggregating form: notified with purged key values
        # so the shared selector can drop their per-key state
        self.on_purge_keys = None
        # instance-capacity overflow surfacing: dropped pending instances
        # are counted on device; poll cheaply (one D2H per _OVF_POLL
        # steps) and warn when the count grows — a dense-mode match set
        # is bit-exact exactly while this stays zero
        self._ovf_warned = 0
        self._key_rows: Dict = {}
        self._row_keys: Dict = {}  # reverse map: engine row -> key value
        self._next_row = 0
        self._free_rows: List[int] = []
        # sorted-key index backing the vectorized intern: _key_arr is the
        # sorted array of known keys (NATIVE dtype — int64/'<U' — so
        # searchsorted compares in C, not via boxed python objects),
        # _key_row_arr the row per sorted position.  _key_rows stays the
        # source of truth for snapshots/purges; the index is a
        # rebuildable cache.
        self._key_arr = np.empty(0, dtype=np.int64)
        self._key_row_arr = np.empty(0, dtype=np.int32)
        self._vector_intern = True
        # host-side per-row activity clock driving idle-key reclamation
        # (@purge on dense partitions; the instance path purges whole
        # PartitionInstances instead)
        self._row_last_used = np.zeros(engine.n_partitions, dtype=np.int64)
        # output dtypes: cast the engine's float32 lanes back to the
        # declared attribute types for callbacks/sinks
        self._out_dtypes: List[np.dtype] = [
            t.np_dtype for t in output_attr_types(engine)
        ]

    # -- partition interning -------------------------------------------------

    def _deal_rows(self, ids: np.ndarray) -> np.ndarray:
        """Allocation-counter ids -> logical partition ids.  Sharded
        runtimes deal ids round-robin across shards (key #k lives on
        shard k % n_shards) so load spreads from the first key on."""
        if self._sharded is None:
            return ids
        return ((ids % self.n_shards) * self.parts_per_shard
                + (ids // self.n_shards))

    def _phys_rows(self, rows: np.ndarray) -> np.ndarray:
        """Logical partition ids -> physical state-array rows (the
        shard-major layout inserts one scratch row per shard)."""
        if self._sharded is None:
            return rows
        pps = self.parts_per_shard
        return (rows // pps) * (pps + 1) + (rows % pps)

    def _logical_rows(self, phys: np.ndarray) -> np.ndarray:
        """Physical state-array rows -> logical partition ids (inverse
        of _phys_rows; scratch rows never carry armed deadlines, so
        timer-fired rows are always real partitions)."""
        if self._sharded is None:
            return phys
        rps = self.parts_per_shard + 1
        return (phys // rps) * self.parts_per_shard + (phys % rps)

    def intern_keys(self, keys) -> np.ndarray:
        """Partition-key values -> dense engine row ids (stable until the
        key is purged; shared by all source streams).

        Vectorized: the batch is factorized once (np.unique), existing
        keys resolve with one searchsorted against the sorted key index,
        and only NEVER-SEEN keys take the python allocation path — so a
        131k-event batch over warm keys costs O(n log n) numpy, not 131k
        dict probes.

        The sorted index only works while every key batch shares one
        dtype family (all-int, all-string, ...).  Mixing families — e.g.
        ``partition with (k of A, sym of B)`` with an int key on one
        stream and a string on the other — would corrupt searchsorted
        ordering (and 7 vs 7.0 alias under python hashing but not under
        dtype promotion), so the runtime then degrades permanently to
        the exact per-event dict intern."""
        arr = np.asarray(keys)
        if self._vector_intern:
            if arr.dtype.kind in ("O", "V"):
                self._vector_intern = False
            elif len(self._key_arr) == 0 and not self._key_rows:
                pass  # first batch adopts its dtype below
            elif arr.dtype != self._key_arr.dtype:
                if np.can_cast(arr.dtype, self._key_arr.dtype, "safe"):
                    arr = arr.astype(self._key_arr.dtype)
                elif np.can_cast(self._key_arr.dtype, arr.dtype, "safe"):
                    self._key_arr = self._key_arr.astype(arr.dtype)
                else:
                    log.warning(
                        "dense pattern: partition keys mix dtypes (%s vs "
                        "index %s); falling back to the exact dict intern",
                        arr.dtype, self._key_arr.dtype)
                    self._vector_intern = False
        if not self._vector_intern:
            return self._intern_keys_dict(arr)
        uniq, inv = np.unique(arr, return_inverse=True)
        nu = len(uniq)
        urows = np.empty(nu, dtype=np.int32)
        if len(self._key_arr):
            pos = np.searchsorted(self._key_arr, uniq)
            pos_c = np.minimum(pos, len(self._key_arr) - 1)
            found = self._key_arr[pos_c] == uniq
            urows[found] = self._key_row_arr[pos_c[found]]
            new_idx = np.flatnonzero(~found)
        else:
            new_idx = np.arange(nu)
        if len(new_idx):
            cap = self.engine.n_partitions
            n_new = len(new_idx)
            # bulk row allocation: recycled rows first, then a fresh range
            take_free = min(len(self._free_rows), n_new)
            fresh = n_new - take_free
            if self._next_row + fresh > cap:
                raise SiddhiAppRuntimeError(
                    f"dense pattern: partition-key cardinality exceeded "
                    f"capacity {cap} (raise it via "
                    f"@app:execution('tpu', partitions='N') or enable "
                    "@purge on the partition)")
            row_ids = np.empty(n_new, dtype=np.int32)
            if take_free:
                row_ids[:take_free] = self._free_rows[-take_free:][::-1]
                del self._free_rows[-take_free:]
            if fresh:
                row_ids[take_free:] = self._deal_rows(np.arange(
                    self._next_row, self._next_row + fresh, dtype=np.int64)
                ).astype(np.int32)
                self._next_row += fresh
            urows[new_idx] = row_ids
            self._key_rows.update(
                zip(uniq[new_idx].tolist(), row_ids.tolist()))
            self._row_keys.update(
                zip(row_ids.tolist(), uniq[new_idx].tolist()))
            # merge the (sorted) new keys into the sorted index with an
            # O(K+U) two-way merge (a full argsort of ~1M keys per batch
            # would dominate the step); dtype promotes explicitly so
            # widening string keys never truncate
            new_keys = uniq[new_idx]
            new_rows = urows[new_idx]
            K, U = len(self._key_arr), len(new_keys)
            if K == 0:
                self._key_arr = new_keys.copy()
                self._key_row_arr = new_rows.copy()
            else:
                ins = np.searchsorted(self._key_arr, new_keys)
                new_pos = ins + np.arange(U)
                old_mask = np.ones(K + U, dtype=bool)
                old_mask[new_pos] = False
                dt = np.promote_types(self._key_arr.dtype, new_keys.dtype)
                merged_keys = np.empty(K + U, dtype=dt)
                merged_keys[new_pos] = new_keys
                merged_keys[old_mask] = self._key_arr
                merged_rows = np.empty(K + U, dtype=np.int32)
                merged_rows[new_pos] = new_rows
                merged_rows[old_mask] = self._key_row_arr
                self._key_arr = merged_keys
                self._key_row_arr = merged_rows
        return urows[inv].astype(np.int32, copy=False)

    def _intern_keys_dict(self, keys) -> np.ndarray:
        """Exact per-event intern (hash semantics): the fallback when
        partition keys mix dtype families, and the behavior reference
        for the vectorized path."""
        out = np.zeros(len(keys), dtype=np.int32)
        rows = self._key_rows
        cap = self.engine.n_partitions
        for i, k in enumerate(keys):
            row = rows.get(k)
            if row is None:
                if self._free_rows:
                    row = self._free_rows.pop()
                elif self._next_row < cap:
                    row = int(self._deal_rows(np.asarray(self._next_row)))
                    self._next_row += 1
                else:
                    raise SiddhiAppRuntimeError(
                        f"dense pattern: partition-key cardinality exceeded "
                        f"capacity {cap} (raise it via "
                        f"@app:execution('tpu', partitions='N') or enable "
                        "@purge on the partition)")
                rows[k] = row
                self._row_keys[row] = k
            out[i] = row
        return out

    def _rebuild_key_index(self):
        """Rebuild the sorted intern index from _key_rows (after purge
        or restore); degrades to dict mode when the stored keys do not
        form one sortable dtype family."""
        if self._key_rows:
            try:
                karr = np.array(list(self._key_rows.keys()))
            except ValueError:  # inhomogeneous keys
                karr = None
            if karr is None or karr.dtype.kind in ("O", "V"):
                self._vector_intern = False
                self._key_arr = np.empty(0, dtype=np.int64)
                self._key_row_arr = np.empty(0, dtype=np.int32)
                return
            rarr = np.fromiter(
                (self._key_rows[k] for k in self._key_rows), np.int32,
                len(karr))
            order = np.argsort(karr, kind="stable")
            self._key_arr = karr[order]
            self._key_row_arr = rarr[order]
        else:
            self._key_arr = np.empty(0, dtype=np.int64)
            self._key_row_arr = np.empty(0, dtype=np.int32)

    def purge_idle(self, now: int, idle_ms: int):
        """Reclaim rows of keys idle for >= idle_ms: reset their device
        state to the init row and recycle the row ids (the dense analog
        of PartitionRuntime's idle-instance purge)."""
        if not self._key_rows:
            return
        idle = [
            (k, r) for k, r in self._key_rows.items()
            if now - int(self._row_last_used[r]) >= idle_ms
        ]
        if not idle:
            return
        # barrier: purged keys' pending matches must reach per-key
        # selector state before on_purge_keys drops it
        self.drain()
        rows = self._phys_rows(np.asarray([r for _k, r in idle],
                                          dtype=np.int32))
        init = self.engine.init_state_host()
        jnp = self.engine.jnp
        state = dict(self.state)
        for key, arr in state.items():
            # every init row is identical; row 0 is the template
            state[key] = arr.at[rows].set(jnp.asarray(init[key][0]))
        self.state = state
        for k, r in idle:
            del self._key_rows[k]
            self._row_keys.pop(r, None)
            self._free_rows.append(r)
        self._rebuild_key_index()
        self._wake_dirty = True
        if self.on_purge_keys is not None:
            # partition-axis selectors drop the purged keys' aggregation
            # state too (host analog: the whole per-key instance dies)
            self.on_purge_keys([k for k, _r in idle])

    # -- event path ----------------------------------------------------------

    def process_stream_batch(self, stream_key: str, batch: EventBatch,
                             part: Optional[np.ndarray] = None,
                             keys=None):
        """Advance the NFA with a junction batch.  ``part`` overrides the
        partition-row assignment (the partitioned receiver computes it
        from the partition executor + intern_keys); ``keys`` carries the
        raw partition-key values aligned with the batch so aggregating
        selectors can keep per-key state (aux side channel)."""
        cur = batch.only(ev.CURRENT)
        n = len(cur)
        if n == 0:
            return
        # one sampled-or-None cycle token per junction batch: ingest
        # span starts here, at receive time
        tok = (self.tracer.begin_cycle(self.engine_kind, n)
               if self.tracer is not None else None)
        eng = self.engine
        cols = {}
        for a in _numeric_attrs(eng, stream_key):
            col = cur.columns.get(a)
            if col is None:
                continue
            # native dtype: the engine splits integer columns into
            # bit-exact hi/lo pairs itself (prepare_cols)
            cols[a] = np.asarray(col)
        if part is None:
            if self.key_fn is None:
                part = np.zeros(len(cur), dtype=np.int32)
            else:
                if keys is None:
                    keys = self.key_fn(cur)
                part = self.intern_keys(keys)
        ts = np.asarray(cur.timestamps, dtype=np.int64)
        if len(ts):
            np.maximum.at(self._row_last_used, part, ts)
        if self._sharded is not None:
            self.state, pending = self._sharded[
                stream_key].process_deferred(self.state, part, cols, ts)
        else:
            self.state, pending = eng.process_deferred(
                self.state, stream_key, part, cols, ts)
        self.step_invocations += 1
        if eng.has_deadlines:
            self._wake_dirty = True
        if self.step_invocations % self._OVF_POLL == 0:
            self._check_overflow()
        from siddhi_tpu.core.emit_queue import PendingEmit

        # clock sampled at RECEIVE time: the finish step may run a batch
        # later (ingest.depth > 1) but replays the synchronous `now`
        now = (self._app_context.timestamp_generator.current_time()
               if self._app_context is not None else None)

        def _finish(p=pending, t=ts, k=keys, n=now, tk=tok):
            c = 0 if p is None else p.resolve()
            if tk is not None:
                # match-count gate resolved: the jitted step finished
                tk.step_done(c)
            if c == 0:
                self.emit_queue.skip()
                return
            self.emit_queue.push(PendingEmit(
                p.device_arrays(),
                lambda host, pp=p, tt=t, kk=k, nn=n: self._emit_deferred(
                    pp, tt, kk, host, now=nn),
                trace=tk))

        # the match-count fetch (resolve) is the blocking device sync;
        # staging it lets batch N+1's H2D puts + step dispatch go out
        # before batch N's count scalar is fetched
        self.ingest_stage.submit(
            pending.probe() if pending is not None else None, _finish,
            trace=tok)

    def drain(self):
        """Flush barrier: materialize and emit every queued match batch
        (one coalesced transfer) — called wherever host code could
        observe emit timing (snapshot/restore, timer fires, purges,
        shutdown).  The ingest stage flushes first: staged batches must
        enqueue (or skip) before the emit queue drains, preserving the
        synchronous callback order."""
        self.ingest_stage.flush()
        self.emit_queue.drain()

    def _on_fault(self, e: Exception):
        """Emit-queue fault channel: surface isolated drain/callback
        failures to the app's exception listeners (via the injector's
        listener list, wired to them by the planner)."""
        # freeze the span ring: the post-mortem shows the cycles that
        # led into the isolated failure
        if self.tracer is not None:
            self.tracer.dump(f"onerror-isolation:{type(e).__name__}")
        if self.faults is not None:
            self.faults.notify(e)

    def _emit_deferred(self, pending, ts, keys, host_arrays, now=None):
        ev_idx, out = pending.materialize(host_arrays)
        if len(ev_idx) == 0:
            return
        eng = self.engine
        out_cols: Dict[str, np.ndarray] = {}
        names = eng.output_names
        for oi, name in enumerate(names):
            out_cols[name] = out[:, oi].astype(self._out_dtypes[oi])
        mb = EventBatch(
            self.out_stream_id, names, out_cols,
            ts[ev_idx], np.full(len(ev_idx), ev.CURRENT, dtype=np.int8),
        )
        if keys is not None:
            mb.aux["partition_keys"] = [keys[int(i)] for i in ev_idx]
        # original-batch positions of the completing events: the hot-key
        # router splits each cycle into cold/hot sub-batches, and
        # consumers that need the interleaved order re-sort on these
        mb.aux["event_indices"] = ev_idx
        if now is not None:
            # the clock sampled when this batch was processed: deferred
            # drains replay time-based rate limiters exactly (the
            # sync-path `now` sequence, not the drain time)
            mb.aux["emit_now"] = now
        self.emit_cb(mb)

    # -- instance-capacity overflow ------------------------------------------

    _OVF_POLL = 256  # steps between device overflow polls (one D2H each)

    def overflow_total(self) -> int:
        """Total pending instances dropped because every successor lane
        was occupied (0 == the dense match set is bit-exact vs host).
        Reduced ON DEVICE — only a scalar crosses to host (transfers are
        expensive on tunneled/remote devices)."""
        return int(self.engine.jnp.sum(self.state["overflow"]))

    def stats(self) -> Dict:
        """Ops introspection (runtime.pattern_state() / the REST
        service): partition/instance occupancy of the dense engine.
        ``active_instances`` counts pending lanes of rows actually IN
        USE (interned keys; row 0 when unpartitioned) — the scratch row
        and never-touched pre-armed rows of non-every engines don't
        inflate it."""
        active = np.asarray(self.state["active"])
        partitioned = self.engine.n_partitions > 1
        if self._key_rows:
            rows = self._phys_rows(np.fromiter(
                self._key_rows.values(), dtype=np.int64,
                count=len(self._key_rows)))
            act = int(active[rows].sum())
        elif not partitioned:
            # unpartitioned: the single automaton lives in row 0
            act = int(active[0].sum())
        else:
            act = 0
        return {
            "engine": "dense",
            "partitions_in_use": (
                len(self._key_rows) if partitioned else 1),
            "partition_capacity": self.engine.n_partitions,
            "instance_lanes": self.engine.I,
            "active_instances": act,
            "dropped_instances": self.overflow_total(),
            "step_invocations": self.step_invocations,
        }

    def _check_overflow(self):
        total = self.overflow_total()
        if total > self._ovf_warned:
            msg = (
                f"dense pattern '{self.out_stream_id}': "
                f"{total} pending instance(s) dropped — instance lanes "
                "full; matches may be missing vs the host engine.  Raise "
                "@app:execution('tpu', instances='N') (current "
                f"{self.engine.I} per partition/node).")
            log.warning("%s", msg)
            # user-visible signal beyond the log: app exception
            # listeners observe lost-match capacity pressure (the
            # reference's runtime ExceptionListener channel,
            # SiddhiAppRuntimeImpl.handleRuntimeExceptionWith:827)
            listeners = getattr(self._app_context, "exception_listeners",
                                None) if self._app_context else None
            for listener in listeners or ():
                try:
                    listener(SiddhiAppRuntimeError(msg))
                except Exception:  # a bad listener must not kill the flow
                    log.exception("exception listener failed")
            self._ovf_warned = total

    def close(self):
        """App shutdown: drain pending emits, then the final overflow
        check — short-lived apps (< one poll interval of batches) still
        get the dropped-instance warning."""
        self.drain()
        self._check_overflow()

    # -- snapshot contract ---------------------------------------------------

    def snapshot(self) -> Dict:
        self.drain()
        self._check_overflow()
        return {
            "dense_state": {k: np.asarray(v) for k, v in self.state.items()},
            "base_ts": self.engine.base_ts,
            "key_rows": dict(self._key_rows),
            "next_row": self._next_row,
            "free_rows": list(self._free_rows),
            "row_last_used": self._row_last_used.copy(),
        }

    def restore(self, state: Dict):
        self.drain()
        jnp = self.engine.jnp
        rows = len(next(iter(state["dense_state"].values())))
        if self._sharded is not None:
            first = next(iter(self._sharded.values()))
            want = self.n_shards * (self.parts_per_shard + 1)
            if rows != want:
                raise SiddhiAppRuntimeError(
                    f"cannot restore: snapshot has {rows} state rows but "
                    f"this app's sharded layout needs {want} "
                    "(snapshot taken under a different "
                    "@app:execution devices/partitions setting)")
            self.state = {
                k: first._put(np.asarray(v), first.state_specs[k])
                for k, v in state["dense_state"].items()
            }
        else:
            want = self.engine.n_partitions + 1
            if rows != want:
                raise SiddhiAppRuntimeError(
                    f"cannot restore: snapshot has {rows} state rows but "
                    f"this app needs {want} (snapshot taken under a "
                    "different @app:execution devices/partitions setting)")
            self.state = {
                k: jnp.asarray(v) for k, v in state["dense_state"].items()}
        self.engine.base_ts = state["base_ts"]
        self._key_rows = dict(state["key_rows"])
        self._row_keys = {r: k for k, r in self._key_rows.items()}
        self._next_row = state.get("next_row", len(self._key_rows))
        self._free_rows = list(state.get("free_rows", []))
        rlu = state.get("row_last_used")
        if rlu is not None:
            self._row_last_used = np.asarray(rlu).copy()
        self._rebuild_key_index()
        self._wake_dirty = True

    # -- scheduler integration: absent-node deadline timers.  Engines
    # without deadline nodes keep these as no-ops (within expiry is
    # event-driven on the dense path, like StreamPreStateProcessor's
    # on-arrival pruning); engines with absent states are registered as
    # a scheduler task by the planner and fire matches here.

    def on_time(self, now: int):
        eng = self.engine
        if not getattr(eng, "has_deadlines", False):
            return
        # barrier BEFORE the timer fire: event matches queued before
        # this tick must emit first (the synchronous order)
        self.drain()
        self.state, fired = eng.on_time_state(self.state, now)
        self._wake_dirty = True
        if fired is None:
            return
        self.time_fires += 1
        out, fire_ts, rows = fired
        names = eng.output_names
        out_cols = {
            name: out[:, oi].astype(self._out_dtypes[oi])
            for oi, name in enumerate(names)
        }
        mb = EventBatch(
            self.out_stream_id, names, out_cols,
            fire_ts, np.full(len(fire_ts), ev.CURRENT, dtype=np.int8),
        )
        if self._row_keys:
            # partitioned form: timer matches carry their partition key
            # (reverse row->key map; partition-axis selectors need it)
            logical = self._logical_rows(np.asarray(rows))
            mb.aux["partition_keys"] = [
                self._row_keys.get(int(r)) for r in logical]
        mb.aux["emit_now"] = now
        self.emit_cb(mb)

    def next_wakeup(self):
        eng = self.engine
        if not getattr(eng, "has_deadlines", False):
            return None
        if self._wake_dirty:
            self._wake_cache = eng.next_wakeup_state(self.state)
            self._wake_dirty = False
        return self._wake_cache

    def fire(self, now: int):
        self.on_time(now)

    def on_start(self, now: int):
        pass


class _DenseStreamReceiver:
    """Junction subscriber feeding one source stream of a dense pattern."""

    def __init__(self, runtime: DensePatternRuntime, stream_key: str):
        self.runtime = runtime
        self.stream_key = stream_key

    def receive(self, batch: EventBatch):
        self.runtime.process_stream_batch(self.stream_key, batch)
