"""SiddhiAppRuntime: lifecycle + user API surface of one running app.

Mirrors the reference SiddhiAppRuntime/SiddhiAppRuntimeImpl
(SiddhiAppRuntimeImpl.java:99 — start :440, shutdown :543, callbacks,
input handlers).  Snapshot/restore and on-demand queries are wired in by
their subsystems.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Union

from siddhi_tpu.core.context import SiddhiAppContext
from siddhi_tpu.core.exceptions import SiddhiAppRuntimeError
from siddhi_tpu.core.stream import (
    FunctionQueryCallback,
    FunctionStreamCallback,
    InputHandler,
    InputManager,
    QueryCallback,
    StreamCallback,
    StreamJunction,
)


class SiddhiAppRuntime:
    def __init__(
        self,
        name: str,
        siddhi_app,
        app_context: SiddhiAppContext,
        junctions: Dict[str, StreamJunction],
        query_runtimes: Dict[str, object],
        input_manager: InputManager,
        scheduler,
        tables: Optional[Dict[str, object]] = None,
        named_windows: Optional[Dict[str, object]] = None,
        partitions: Optional[Dict[str, object]] = None,
        aggregations: Optional[Dict[str, object]] = None,
    ):
        self.name = name
        self.siddhi_app = siddhi_app
        self.app_context = app_context
        self.junctions = junctions
        self.query_runtimes = query_runtimes
        self.input_manager = input_manager
        self.scheduler = scheduler
        self.tables = tables or {}
        self.named_windows = named_windows or {}
        self.partitions = partitions or {}
        self.aggregations = aggregations or {}
        self._on_demand_cache: Dict[str, object] = {}
        self.running = False
        self._manager = None  # back-ref set by SiddhiManager

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        if self.running:
            return
        for j in self.junctions.values():
            j.start()
        self.scheduler.start()
        self.running = True

    def shutdown(self):
        if not self.running:
            self.running = False
        self.scheduler.stop()
        for j in self.junctions.values():
            j.stop()
        self.running = False
        if self._manager is not None:
            self._manager._app_runtimes.pop(self.name, None)

    # -- I/O ----------------------------------------------------------------

    def get_input_handler(self, stream_id: str) -> InputHandler:
        return self.input_manager.get_input_handler(stream_id)

    def add_callback(
        self,
        target: str,
        callback: Union[StreamCallback, QueryCallback, Callable],
    ):
        """Attach a callback to a stream (StreamCallback / function taking
        events list) or to a query by name (QueryCallback / function taking
        (ts, in_events, out_events))."""
        if target in self.junctions:
            if callable(callback) and not isinstance(callback, StreamCallback):
                callback = FunctionStreamCallback(callback)
            self.junctions[target].add_callback(callback)
            return
        if target in self.query_runtimes:
            if callable(callback) and not isinstance(callback, QueryCallback):
                callback = FunctionQueryCallback(callback)
            self.query_runtimes[target].add_callback(callback)
            return
        raise SiddhiAppRuntimeError(
            f"no stream or query named '{target}' in app '{self.name}'"
        )

    # Java-style aliases for drop-in familiarity
    addCallback = add_callback
    getInputHandler = get_input_handler

    # -- on-demand (pull) queries -------------------------------------------

    def table_resolver(self, table_name: str):
        table = self.tables.get(table_name)
        if table is None:
            raise SiddhiAppRuntimeError(f"'IN {table_name}': table is not defined")
        return table.contains_fn()

    def query(self, on_demand_query: str):
        """Execute a pull query against a table / named window / aggregation
        and return the matching events
        (reference: SiddhiAppRuntimeImpl.query:304, cache cap 50)."""
        from siddhi_tpu.compiler.compiler import SiddhiCompiler
        from siddhi_tpu.core.on_demand import OnDemandQueryRuntime

        rt = self._on_demand_cache.get(on_demand_query)
        if rt is None:
            odq = SiddhiCompiler.parse_on_demand_query(on_demand_query)
            rt = OnDemandQueryRuntime(odq, self)
            if len(self._on_demand_cache) >= 50:
                self._on_demand_cache.pop(next(iter(self._on_demand_cache)))
            self._on_demand_cache[on_demand_query] = rt
        return rt.execute()

    # -- persistence (full implementation arrives with SnapshotService) -----

    def persist(self):
        raise SiddhiAppRuntimeError(
            f"app '{self.name}': no persistence store configured "
            "(SiddhiManager.set_persistence_store)"
        )

    def get_stream_definitions(self):
        return self.siddhi_app.stream_definitions

    def query_names(self) -> List[str]:
        return list(self.query_runtimes)
