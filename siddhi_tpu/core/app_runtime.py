"""SiddhiAppRuntime: lifecycle + user API surface of one running app.

Mirrors the reference SiddhiAppRuntime/SiddhiAppRuntimeImpl
(SiddhiAppRuntimeImpl.java:99 — start :440, shutdown :543, callbacks,
input handlers).  Snapshot/restore and on-demand queries are wired in by
their subsystems.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Union

from siddhi_tpu.core.context import SiddhiAppContext
from siddhi_tpu.core.exceptions import SiddhiAppRuntimeError
from siddhi_tpu.core.stream import (
    FunctionQueryCallback,
    FunctionStreamCallback,
    InputHandler,
    InputManager,
    QueryCallback,
    StreamCallback,
    StreamJunction,
)


class SiddhiAppRuntime:
    def __init__(
        self,
        name: str,
        siddhi_app,
        app_context: SiddhiAppContext,
        junctions: Dict[str, StreamJunction],
        query_runtimes: Dict[str, object],
        input_manager: InputManager,
        scheduler,
        tables: Optional[Dict[str, object]] = None,
        named_windows: Optional[Dict[str, object]] = None,
        partitions: Optional[Dict[str, object]] = None,
        aggregations: Optional[Dict[str, object]] = None,
        sources: Optional[List] = None,
        sinks: Optional[List] = None,
        functions: Optional[Dict[str, object]] = None,
        handler_registrations: Optional[List] = None,
    ):
        self.name = name
        self.siddhi_app = siddhi_app
        self.app_context = app_context
        self.junctions = junctions
        self.query_runtimes = query_runtimes
        self.input_manager = input_manager
        self.scheduler = scheduler
        self.tables = tables or {}
        self.named_windows = named_windows or {}
        self.partitions = partitions or {}
        self.aggregations = aggregations or {}
        self.sources = sources or []
        self.sinks = sinks or []
        self.functions = functions or {}
        self._handler_registrations = handler_registrations or []
        self._on_demand_cache: Dict[str, object] = {}
        self.running = False
        self._manager = None  # back-ref set by SiddhiManager
        # raw app source (set by AppPlanner.build) — a live re-plan
        # rebuilds the whole engine set from a fresh parse of this
        self._app_string = ""
        # (target, callback) pairs as the user registered them, so a
        # re-plan can re-attach them to the replacement runtimes; the
        # ledger keys ("stream", id) / ("query", name) / ("sink", ...)
        # are structural, so replay suppression carries across
        self._user_callbacks: List = []
        self._apply_statistics_level(self.app_context.root_metrics_level)
        # fault-injection / recovery counters register UNGATED by the
        # metrics level: when @app:faults is armed, its evidence must be
        # visible in statistics()/REST even with statistics 'off'
        sm = self.app_context.statistics_manager
        fi = self.app_context.fault_injector
        if sm is not None and fi is not None:
            sm.fault_tracker("injector", fi.stats)
        # @app:limits counters register ungated too: shed/breaker/
        # watchdog evidence must survive statistics level 'off' — the
        # health endpoint and the metrics feed read the SAME object
        rb = self.app_context.robustness
        if sm is not None and rb is not None:
            sm.robustness_tracker("overload", rb)

    # -- async emit pipeline barriers ---------------------------------------

    def _device_runtimes(self):
        """Every device/dense runtime holding a pending-emit queue
        (core/emit_queue.py), across top-level queries and dense
        partitions."""
        for qr in self.query_runtimes.values():
            for attr in ("device_runtime", "pattern_processor"):
                rt = getattr(qr, attr, None)
                if rt is not None and hasattr(rt, "drain"):
                    yield rt
        for pr in self.partitions.values():
            for qr in getattr(pr, "dense_query_runtimes", {}).values():
                for attr in ("device_runtime", "pattern_processor"):
                    rt = getattr(qr, attr, None)
                    if rt is not None and hasattr(rt, "drain"):
                        yield rt

    def drain_device_emits(self):
        """App-wide flush barrier of the async emit pipeline: every
        device runtime's queued match batches materialize and emit (in
        the synchronous order) before host code observes state —
        snapshot/persist/restore, pull queries, shutdown.  Device
        tables drain LAST: an emit drain can trigger mutation callbacks,
        and the table barrier (compaction + revision advance + pinning)
        must see them."""
        for rt in self._device_runtimes():
            rt.drain()
        for t in self.tables.values():
            if hasattr(t, "drain"):
                t.drain()

    # -- overload gauges (robustness/watchdog.py reads these) ---------------

    def _pending_work(self) -> int:
        """Units of accepted-but-undelivered work: queued async-junction
        batches plus staged ingest probes and deferred device emits.
        Zero means a frozen beat is just idleness, not a stall."""
        n = 0
        for j in self.junctions.values():
            if j.is_async and j._queue is not None:
                n += j._queue.qsize()
        for rt in self._device_runtimes():
            eq = getattr(rt, "emit_queue", None)
            if eq is not None:
                n += len(eq)
            stage = getattr(rt, "ingest_stage", None)
            if stage is not None:
                n += len(stage)
        return n

    def _queue_fill(self) -> float:
        """Worst async-junction fill fraction in [0, 1] — the sustained-
        pressure signal the degradation ladder watches."""
        worst = 0.0
        for j in self.junctions.values():
            q = j._queue if j.is_async else None
            if q is not None and q.maxsize > 0:
                worst = max(worst, q.qsize() / q.maxsize)
        return min(worst, 1.0)

    # -- lifecycle ----------------------------------------------------------

    def debug(self):
        """Start in debug mode: returns a SiddhiDebugger wired to every
        query terminal (reference: SiddhiAppRuntimeImpl.debug:657)."""
        from siddhi_tpu.debugger import SiddhiDebugger

        debugger = SiddhiDebugger(self)
        for qr in self.query_runtimes.values():
            if hasattr(qr, "debugger"):
                qr.debugger = debugger
        # breakpoints must observe every emit at its own batch: force
        # the pending-emit queue to drain after each step (and pin it —
        # an auto controller would re-deepen it), and collapse the
        # ingest staging window back to synchronous
        for rt in self._device_runtimes():
            eq = getattr(rt, "emit_queue", None)
            if eq is not None:
                eq.depth = 1
                eq.controller = None
            stage = getattr(rt, "ingest_stage", None)
            if stage is not None:
                stage.flush()
                stage.depth = 1
        self.start()
        return debugger

    def start(self):
        if self.running:
            return
        for j in self.junctions.values():
            j.start()
        self.scheduler.start()
        for t in self.tables.values():
            if hasattr(t, "start"):
                t.start()  # record tables connect their stores
        # sinks connect before sources so output paths exist when events
        # flow; the running gate opens BEFORE sources connect — a source
        # may deliver on its transport thread the instant it subscribes.
        # Rolled back if a transport start raises, so a failed start()
        # leaves the InputHandler gate closed.
        self.app_context.app_running = True
        try:
            for s in self.sinks:
                s.start()
            for s in self.sources:
                s.start()
        except Exception as e:
            import logging

            # the rollback re-raises, but the failure must also leave a
            # trace in the error log (the no-silent-fault contract)
            logging.getLogger("siddhi_tpu").error(
                "app '%s': transport start failed, rolling back the "
                "running gate: %s", self.name, e)
            self.app_context.app_running = False
            raise
        from siddhi_tpu.util.statistics import Level

        sm = self.app_context.statistics_manager
        if sm is not None and Level.at_least(self.app_context.root_metrics_level, Level.BASIC):
            sm.start_reporting()
        self.running = True
        if self.app_context.playback and self.app_context.playback_idle_ms > 0:
            self._start_playback_heartbeat()
        if self.app_context.persist_interval_ms > 0:
            self._start_persist_daemon()
        if (self.app_context.plan_auto
                and self.app_context.plan_interval_ms > 0):
            from siddhi_tpu.planner.monitor import PlanMonitor

            # @app:plan(auto, interval): online refinement daemon — reads
            # the observability feed and re-lowers when the active plan's
            # observed cost exceeds a cheaper alternative by the
            # hysteresis margin
            self._plan_monitor = PlanMonitor(self)
            self._plan_monitor.start()
        if (self.app_context.watchdog_deadline_ms > 0
                and getattr(self, "_watchdog", None) is None):
            from siddhi_tpu.robustness import DegradationLadder, Watchdog

            # @app:limits(watchdog='...', ladder='true'): stall detector
            # + self-heal daemon, optionally driving the degradation
            # ladder.  replan() restarts the pair through here, with the
            # transplanted stats so counters survive the heal.
            rb = self.app_context.robustness
            self._ladder = (DegradationLadder(self, rb)
                            if self.app_context.ladder else None)
            self._watchdog = Watchdog(
                self, rb, self.app_context.watchdog_deadline_ms,
                ladder=self._ladder)
            self._watchdog.start()

    def _start_playback_heartbeat(self):
        """@app:playback(idle.time, increment): when no events arrive for
        idle.time, advance event time by increment so event-time windows
        and schedulers keep draining (reference:
        TimestampGeneratorImpl idle-time timer)."""
        import threading
        import time as _time

        idle_s = self.app_context.playback_idle_ms / 1000.0
        tg = self.app_context.timestamp_generator
        stop = threading.Event()

        def loop():
            while not stop.wait(idle_s):
                if _time.monotonic() - tg.last_update_wall >= idle_s:
                    with self.app_context.process_lock:
                        now = tg.advance_idle()
                        self.scheduler.advance(now)

        t = threading.Thread(target=loop, name=f"playback-{self.name}", daemon=True)
        self._playback_stop = stop
        self._playback_thread = t
        t.start()

    def _start_persist_daemon(self):
        """@app:persist(interval, mode): periodic checkpoint daemon — a
        persist() every interval, in the annotation's mode (async by
        default, so the loop only stalls for the in-barrier capture)."""
        import logging
        import threading

        log = logging.getLogger("siddhi_tpu")
        interval_s = self.app_context.persist_interval_ms / 1000.0
        stop = threading.Event()

        def loop():
            while not stop.wait(interval_s):
                try:
                    self.persist()
                except Exception as e:
                    log.error("app '%s': periodic persist failed: %s",
                              self.name, e)
                    for lst in self.app_context.exception_listeners:
                        try:
                            lst(e)
                        except Exception:
                            log.exception("exception listener failed")
                except BaseException as e:
                    # simulated crash on the daemon thread: record and
                    # stop ticking — the harness kills the app elsewhere
                    log.error("app '%s': persist daemon stopped: %s",
                              self.name, e)
                    break

        t = threading.Thread(target=loop, name=f"persist-{self.name}",
                             daemon=True)
        self._persist_stop = stop
        self._persist_thread = t
        t.start()

    def shutdown(self):
        # the watchdog stops FIRST: a daemon that can force a replan
        # must not race an intentional teardown
        wd = getattr(self, "_watchdog", None)
        if wd is not None:
            wd.stop()
            self._watchdog = None
            self._ladder = None
        mon = getattr(self, "_plan_monitor", None)
        if mon is not None:
            mon.stop()
            self._plan_monitor = None
        stop = getattr(self, "_persist_stop", None)
        if stop is not None:
            stop.set()
            self._persist_thread.join(timeout=2)
            self._persist_stop = None
        stop = getattr(self, "_playback_stop", None)
        if stop is not None:
            stop.set()
            self._playback_thread.join(timeout=2)
            self._playback_stop = None
        # in-flight async checkpoint reaches a terminal state, then the
        # writer thread exits — shutdown must not strand a half-written
        # revision mid-commit (the store's atomic-manifest protocol makes
        # even a stranded one recoverable, but exiting clean is free)
        w = self._durability_writer(create=False)
        if w is not None:
            w.shutdown()
        sm = self.app_context.statistics_manager
        if sm is not None:
            sm.stop_reporting()
        for s in self.sources:
            s.shutdown()
        # barrier: queued device emits reach their callbacks/sinks
        # before the scheduler and junctions stop accepting output
        self.drain_device_emits()
        for s in self.sinks:
            s.shutdown()
        self.scheduler.stop()
        for j in self.junctions.values():
            j.stop()
        # final dense-overflow check so short-lived apps still surface
        # dropped-instance warnings
        for qr in self.query_runtimes.values():
            pp = getattr(qr, "pattern_processor", None)
            if pp is not None and hasattr(pp, "close"):
                pp.close()
            # multiplexed tenants free their shared-engine seat here
            dr = getattr(qr, "device_runtime", None)
            if dr is not None and hasattr(dr, "close"):
                dr.close()
        for pr in self.partitions.values():
            for qr in getattr(pr, "dense_query_runtimes", {}).values():
                pp = getattr(qr, "pattern_processor", None)
                if pp is not None and hasattr(pp, "close"):
                    pp.close()
        for t in self.tables.values():
            if hasattr(t, "shutdown"):
                t.shutdown()
        for mgr, element_id in self._handler_registrations:
            mgr.unregister(element_id)
        self._handler_registrations = []
        self.running = False
        self.app_context.app_running = False
        if self._manager is not None:
            # identity-guarded: an unregistered or replaced runtime must
            # not evict a different runtime registered under this name
            if self._manager._app_runtimes.get(self.name) is self:
                self._manager._app_runtimes.pop(self.name, None)

    # -- I/O ----------------------------------------------------------------

    def get_input_handler(self, stream_id: str) -> InputHandler:
        return self.input_manager.get_input_handler(stream_id)

    def add_callback(
        self,
        target: str,
        callback: Union[StreamCallback, QueryCallback, Callable],
    ):
        """Attach a callback to a stream (StreamCallback / function taking
        events list) or to a query by name (QueryCallback / function taking
        (ts, in_events, out_events))."""
        if target in self.junctions:
            if callable(callback) and not isinstance(callback, StreamCallback):
                callback = FunctionStreamCallback(callback)
            self.junctions[target].add_callback(callback)
            self._user_callbacks.append((target, callback))
            return
        if target in self.query_runtimes:
            if callable(callback) and not isinstance(callback, QueryCallback):
                callback = FunctionQueryCallback(callback)
            self.query_runtimes[target].add_callback(callback)
            self._user_callbacks.append((target, callback))
            return
        raise SiddhiAppRuntimeError(
            f"no stream or query named '{target}' in app '{self.name}'"
        )

    def add_exception_listener(self, listener) -> None:
        """Register a runtime exception listener invoked with every
        error the engine logs instead of raising — @OnError LOG mode,
        sink publish failures, scheduler task errors (reference:
        SiddhiAppRuntimeImpl.handleRuntimeExceptionWith:827)."""
        self.app_context.exception_listeners.append(listener)

    # Java-style aliases for drop-in familiarity
    addCallback = add_callback
    getInputHandler = get_input_handler
    handleRuntimeExceptionWith = add_exception_listener

    # -- statistics ---------------------------------------------------------

    def _apply_statistics_level(self, level: str):
        """(Un)install throughput/latency/buffer trackers to match `level`
        (reference: SiddhiAppRuntimeImpl.setStatisticsLevel:859,
        registerForBufferedEvents:802-821)."""
        from siddhi_tpu.util.statistics import Level

        sm = self.app_context.statistics_manager
        if sm is None:
            return
        self.app_context.root_metrics_level = level
        basic = Level.at_least(level, Level.BASIC)
        detail = Level.at_least(level, Level.DETAIL)
        if not basic:
            # downgrade: drop trackers from the manager so statistics()
            # stops reporting stale metrics
            sm.throughput.clear()
            sm.latency.clear()
            sm.lowering.clear()
            sm.transfers.clear()
            sm.ingests.clear()
        else:
            sm.lowering.update(self.lowering())
            # async pipeline counters, one gauge pair per device-lowered
            # query: emit side (emitTransfers / deferredBatches /
            # zeroMatchSkips / maxPendingDepth / autoEffectiveDepth) and
            # ingest side (stagedBatches / devicePuts / ingestStalls /
            # overlappedBatches / flushSyncs / maxStagingDepth)
            for name, qr in list(self.query_runtimes.items()) + [
                (n, q)
                for pr in self.partitions.values()
                for n, q in getattr(pr, "dense_query_runtimes", {}).items()
            ]:
                for attr in ("device_runtime", "pattern_processor"):
                    rt = getattr(qr, attr, None)
                    if rt is not None and hasattr(rt, "emit_stats"):
                        sm.transfer_tracker(name, rt.emit_stats)
                    if rt is not None and hasattr(rt, "ingest_stats"):
                        sm.ingest_tracker(name, rt.ingest_stats)
        if not detail:
            sm.buffers.clear()
        for j in self.junctions.values():
            j.throughput_tracker = sm.throughput_tracker(j.stream_id) if basic else None
        for qname, qr in self.query_runtimes.items():
            if hasattr(qr, "latency_tracker"):
                qr.latency_tracker = sm.latency_tracker(qname) if basic else None
        if detail:
            for j in self.junctions.values():
                if j.is_async:
                    sm.buffer_tracker(j.stream_id, j)

    def set_statistics_level(self, level: str):
        """Runtime-switchable metrics level OFF/BASIC/DETAIL."""
        from siddhi_tpu.util.statistics import Level

        self._apply_statistics_level(level)
        sm = self.app_context.statistics_manager
        if sm is not None and self.running:
            if Level.at_least(level, Level.BASIC):
                sm.start_reporting()
            else:
                sm.stop_reporting()

    def statistics(self) -> Dict[str, float]:
        sm = self.app_context.statistics_manager
        return sm.stats() if sm is not None else {}

    def lowering(self) -> Dict[str, str]:
        """Per-query engine placement: ``'host'`` (columnar numpy
        chain), ``'dense'`` (jitted dense NFA), or ``'device'`` (jitted
        device query engine) — so an ``execution('tpu')`` user can see
        WHICH queries actually lowered instead of silently getting host
        execution (the dense path's capacity introspection analog for
        the general query path)."""
        out = {
            name: getattr(qr, "lowered_to", "host")
            for name, qr in self.query_runtimes.items()
        }
        for pr in self.partitions.values():
            if hasattr(pr, "query_lowering"):
                out.update(pr.query_lowering())
        return out

    # -- live re-planning ---------------------------------------------------

    def replan(self, pins: Optional[Dict[str, str]] = None,
               forced: bool = True, reason: str = "") -> Dict[str, str]:
        """Re-lower the RUNNING app under a new plan, bit-exact across
        the switch.

        Protocol (all under the process lock): pause ingest and drain
        the async emit pipeline; build a COMPLETE replacement engine set
        from a fresh parse with ``pins`` as per-query exact-path
        overrides (``{'q': 'fuse+shard'}``; absent queries re-plan by
        cost); cross the ``replan.reseat`` crash point (a kill there
        abandons the replacement and leaves the old engines fully
        operational); tear the old engines down; adopt the new
        internals onto this SAME runtime object (manager registry,
        handles, and REST routes keep working); re-attach user
        callbacks; then rebuild all engine state by replaying the input
        journal's FULL history with the output ledger suppressing every
        event each callback/sink already received — the observable
        sequence is identical to an uninterrupted run on either plan.

        Requires ``@app:faults(journal='N')`` with the whole input
        history still in memory; refused with a counted
        ``plannerFallbackReason`` otherwise.  Returns the new per-query
        lowering map."""
        import logging

        from siddhi_tpu.planner.app_planner import AppPlanner

        log = logging.getLogger("siddhi_tpu")
        sm = self.app_context.statistics_manager

        def refuse(why: str):
            if sm is not None:
                sm.record_planner_fallback(self.name,
                                           f"replan refused: {why}")
            log.warning("app '%s': replan refused (%s)", self.name, why)
            raise SiddhiAppRuntimeError(
                f"app '{self.name}': replan refused — {why}")

        if not self.running:
            refuse("app is not running")
        jr = self.app_context.input_journal
        if jr is None:
            refuse("no input journal — @app:faults(journal='N') is the "
                   "replay substrate a live re-plan rebuilds state from")
        with self.app_context.process_lock:
            old_sources = list(self.sources)
            for s in old_sources:
                s.pause()
            committed = False
            try:
                self.drain_device_emits()
                self._flush_persists()
                if not jr.covers_from_start():
                    refuse("journal no longer holds the full input "
                           "history (overflowed or spilled); raise the "
                           "journal depth to re-plan live")
                old_lowering = self.lowering()
                entries = jr.all_entries()

                app_str = getattr(self, "_app_string", "") or ""
                if app_str:
                    from siddhi_tpu.compiler.compiler import SiddhiCompiler

                    ast = SiddhiCompiler.parse(app_str)
                else:
                    ast = self.siddhi_app
                planner = AppPlanner(
                    ast, app_str, self.app_context.siddhi_context)
                planner.app_context.plan_pins = dict(pins or {})
                # robustness continuity (BEFORE build, so breakers and
                # trackers bind to the carried objects): shed/breaker
                # counters, token-bucket levels and the degradation rung
                # survive a self-heal exactly like the journal does
                rb = self.app_context.robustness
                if rb is not None and planner.app_context.robustness is not None:
                    planner.app_context.robustness = rb
                    ac = self.app_context.admission
                    if ac is not None:
                        ac.app_context = planner.app_context
                        ac.stats = rb
                        planner.app_context.admission = ac
                level = self.app_context.degrade_level
                if level:
                    from siddhi_tpu.robustness import apply_degradation

                    planner.app_context.degrade_level = level
                    # record what the rung disabled: the rebuilt ladder
                    # derives its rung list from these flags, and the
                    # now-cleared annotation flags alone would leave it
                    # zero-rung — unable to ever re-promote
                    planner.app_context.degraded_features = tuple(
                        apply_degradation(planner.app_context, level))
                new_rt = planner.build()

                fi = self.app_context.fault_injector
                try:
                    if fi is not None:
                        # crash point: replacement built, old engines not
                        # yet torn down — a kill here must leave the old
                        # runtime fully operational
                        fi.check("replan.reseat")
                except BaseException:
                    # abandon the replacement; drop its registrations so
                    # the old runtime keeps exclusive ownership
                    try:
                        new_rt._manager = None
                        new_rt.shutdown()
                    except Exception:
                        log.warning(
                            "replan: abandoned replacement engines did "
                            "not tear down cleanly", exc_info=True)
                    raise

                # ---- point of no return: adopt the replacement --------
                new_ctx = new_rt.app_context
                # ONE lock serializes both incarnations: transports of
                # the new sources must block on the lock this thread
                # holds until the replay below finishes
                new_ctx.process_lock = self.app_context.process_lock
                new_sm = new_ctx.statistics_manager
                if sm is not None and new_sm is not None:
                    # app-wide re-plan history survives the switch
                    new_sm.replans.extend(sm.replans)
                mgr = self._manager
                self._manager = None  # identity-guarded pop must not fire
                try:
                    self.shutdown()
                finally:
                    self._manager = mgr
                committed = True
                self.siddhi_app = new_rt.siddhi_app
                self.app_context = new_ctx
                self.junctions = new_rt.junctions
                self.query_runtimes = new_rt.query_runtimes
                # keep the OLD InputManager object (user code holds
                # InputHandlers it created): re-point it and every cached
                # handler at the replacement junctions/context in place
                old_im = self.input_manager
                new_im = new_rt.input_manager
                old_im.app_context = new_ctx
                old_im._junctions = new_im._junctions
                for sid, h in list(old_im._handlers.items()):
                    nj = new_im._junctions.get(sid)
                    if nj is None:  # pragma: no cover - defs are static
                        old_im._handlers.pop(sid)
                        continue
                    h.junction = nj
                    h.app_context = new_ctx
                    h.definition = nj.definition
                self.scheduler = new_rt.scheduler
                self.tables = new_rt.tables
                self.named_windows = new_rt.named_windows
                self.partitions = new_rt.partitions
                self.aggregations = new_rt.aggregations
                self.sources = new_rt.sources
                self.sinks = new_rt.sinks
                self.functions = new_rt.functions
                self._handler_registrations = new_rt._handler_registrations
                self._on_demand_cache = {}
                self._snapshot_svc = None
                self._ckpt_writer = None
                self._durab_stats = None
                cbs, self._user_callbacks = self._user_callbacks, []
                for target, cb in cbs:
                    self.add_callback(target, cb)

                # restart under the new plan, then rebuild engine state
                # by replaying the full journaled history through the
                # suppressing output ledger
                self.start()
                jr.begin_replay_from_start()
                try:
                    for stream_id, batch in entries:
                        self.input_manager.get_input_handler(
                            stream_id).send_batch(batch)
                        if jr.stats is not None:
                            jr.stats.replayed_batches += 1
                    # barrier INSIDE the replay window (same contract as
                    # _replay_journal): deferred emits must flow through
                    # the suppressing ledger, not escape as duplicates
                    self.drain_device_emits()
                finally:
                    jr.end_replay()
                new_lowering = self.lowering()
                rsm = self.app_context.statistics_manager
                if rsm is not None:
                    changed = False
                    for q, p in sorted(new_lowering.items()):
                        o = old_lowering.get(q, "")
                        if o != p:
                            changed = True
                            rsm.record_replan(q, o, p, forced, reason)
                    if not changed:
                        rsm.record_replan("*", "", "", forced,
                                          reason or "no lowering change")
                log.info("app '%s': re-planned (%s); lowering now %s",
                         self.name, reason or "forced", new_lowering)
                return new_lowering
            finally:
                if not committed:
                    for s in old_sources:
                        try:
                            s.resume()
                        except Exception:  # pragma: no cover - best effort
                            log.exception("replan: source resume failed")

    # -- health -------------------------------------------------------------

    def health(self) -> Dict:
        """Overload-protection health report (``GET /siddhi-health``).

        ``healthy`` is the roll-up verdict: running, not shedding within
        the admission window, no OPEN breaker, watchdog not wedged.  All
        counters come off the live ``RobustnessStats`` object — the same
        one the statistics feed wraps, so the two can never disagree.
        Lock-free by design: a health probe must answer even while the
        app is wedged."""
        ctx = self.app_context
        ac = ctx.admission
        rb = ctx.robustness
        wd = getattr(self, "_watchdog", None)
        ld = getattr(self, "_ladder", None)
        breakers = []
        for s in list(self.sinks) + list(self.sources):
            for t in [s] + list(getattr(s, "children", None) or []):
                b = getattr(t, "_breaker", None)
                if b is not None:
                    breakers.append(b.describe())
        shedding = ac.shedding_now() if ac is not None else False
        wedged = wd.wedged if wd is not None else False
        healthy = (self.running and not shedding and not wedged
                   and not any(b["state"] == "open" for b in breakers))
        return {
            "app": self.name,
            "healthy": healthy,
            "running": self.running,
            "shedding": shedding,
            "wedged": wedged,
            "degrade_level": ctx.degrade_level,
            "admission": ac.snapshot() if ac is not None else None,
            "breakers": breakers,
            "watchdog": wd.describe() if wd is not None else None,
            "ladder": ld.describe() if ld is not None else None,
            "counters": rb.as_dict() if rb is not None else {},
        }

    def pattern_state(self) -> Dict[str, Dict]:
        """Ops introspection of every pattern/sequence query's engine
        state (dense: partition/instance occupancy + overflow; host:
        live instance count) — parity for the TPU path with the
        reference's runtime inspection surface
        (reference: core/query/OnDemandQueryRuntime.java for the pull
        model; the dense counters have no Java analog).

        Takes the app lock: dense state buffers are DONATED to the
        jitted step mid-batch, so an unlocked read from another thread
        (the REST server) could touch deleted device buffers."""
        with self.app_context.process_lock:
            out: Dict[str, Dict] = {}
            for name, qr in self.query_runtimes.items():
                pp = getattr(qr, "pattern_processor", None)
                if pp is not None and hasattr(pp, "stats"):
                    out[name] = pp.stats()
            for pr in self.partitions.values():
                for qname, qr in getattr(pr, "dense_query_runtimes", {}).items():
                    pp = getattr(qr, "pattern_processor", None)
                    if pp is not None and hasattr(pp, "stats"):
                        out[qname] = pp.stats()
            return out

    # -- on-demand (pull) queries -------------------------------------------

    def table_resolver(self, table_name: str, obj: bool = False):
        table = self.tables.get(table_name)
        if table is None:
            raise SiddhiAppRuntimeError(f"'IN {table_name}': table is not defined")
        return table if obj else table.contains_fn()

    def query(self, on_demand_query: str):
        """Execute a pull query against a table / named window / aggregation
        and return the matching events
        (reference: SiddhiAppRuntimeImpl.query:304, cache cap 50)."""
        from siddhi_tpu.compiler.compiler import SiddhiCompiler
        from siddhi_tpu.core.on_demand import OnDemandQueryRuntime

        # barrier: a pull query reads tables/windows/aggregations that
        # queued device emits may still feed — flush them first so the
        # result matches the synchronous path
        self.drain_device_emits()
        rt = self._on_demand_cache.get(on_demand_query)
        if rt is None:
            odq = SiddhiCompiler.parse_on_demand_query(on_demand_query)
            rt = OnDemandQueryRuntime(odq, self)
            if len(self._on_demand_cache) >= 50:
                self._on_demand_cache.pop(next(iter(self._on_demand_cache)))
            self._on_demand_cache[on_demand_query] = rt
        return rt.execute()

    # -- persistence --------------------------------------------------------

    def _snapshot_service(self):
        from siddhi_tpu.util.snapshot import SnapshotService

        # cached: incremental mode tracks per-element digests across persists
        svc = getattr(self, "_snapshot_svc", None)
        if svc is None:
            svc = self._snapshot_svc = SnapshotService(self)
        return svc

    def _persistence_store(self):
        from siddhi_tpu.core.exceptions import NoPersistenceStoreError

        store = getattr(self.app_context.siddhi_context, "persistence_store", None)
        if store is None:
            raise NoPersistenceStoreError(
                f"app '{self.name}': no persistence store configured "
                "(SiddhiManager.set_persistence_store)"
            )
        return store

    def _durability_stats(self):
        from siddhi_tpu.durability.writer import DurabilityStats

        st = getattr(self, "_durab_stats", None)
        if st is None:
            st = self._durab_stats = DurabilityStats()
            sm = self.app_context.statistics_manager
            if sm is not None:
                # ungated like the fault counters: checkpoint health must
                # be visible even at statistics level 'off'
                sm.durability_tracker(self.name, st)
        return st

    def _durability_writer(self, create: bool = True):
        from siddhi_tpu.durability.writer import AsyncCheckpointWriter

        w = getattr(self, "_ckpt_writer", None)
        if w is None and create:
            w = self._ckpt_writer = AsyncCheckpointWriter(
                self.name, stats=self._durability_stats(),
                fault_injector=self.app_context.fault_injector,
                listeners=self.app_context.exception_listeners,
                tracer=self.app_context.tracer)
        return w

    def _flush_persists(self, timeout: float = 30.0):
        """Barrier: any in-flight async checkpoint reaches a terminal
        state before host code reads or replaces persisted state."""
        w = self._durability_writer(create=False)
        if w is not None:
            w.wait(timeout=timeout)

    def wait_for_persist(self, revision: Optional[str] = None,
                         timeout: Optional[float] = None) -> Optional[str]:
        """Block until an async persist finishes.  Returns the terminal
        status ('committed' / 'failed' / 'superseded' / 'crashed' /
        'idle') or None on timeout.  No-op ('idle') when nothing was
        ever submitted."""
        w = self._durability_writer(create=False)
        if w is None:
            return "idle"
        return w.wait(revision=revision, timeout=timeout)

    def _persist_write(self, store, revision: str, capture):
        """Serialize + store + commit one captured checkpoint.  Runs on
        the checkpoint writer thread (async) or inline (sync)."""
        fi = self.app_context.fault_injector
        st = self._durability_stats()
        if hasattr(store, "save_tree"):
            blobs = capture.materialize_blobs()
            store.save_tree(self.name, revision, blobs,
                            checker=fi.check if fi is not None else None,
                            version=capture.version)
            st.blobs_written += len(blobs)
            st.bytes_written += sum(len(b) for _, _, b in blobs)
        else:
            data = capture.tree_bytes()
            store.save(self.name, revision, data)
            st.bytes_written += len(data)
        if fi is not None:
            # crash point: revision durable, journal mark not committed
            fi.check("persist.post_manifest")
        jr = self.app_context.input_journal
        if jr is not None:
            jr.commit_revision(revision)

    def persist(self, mode: Optional[str] = None) -> str:
        """Snapshot all state and save it under a new revision
        (reference: SiddhiAppRuntimeImpl.persist:677).  Returns the
        revision id.

        ``mode='sync'`` (historical default) writes inside the call;
        ``mode='async'`` (or ``@app:persist(mode='async')``) stalls the
        batch loop only for the in-barrier capture and hands
        serialization + store write to the checkpoint writer thread
        (durability/writer.py) with single-in-flight coalescing
        backpressure.  Incremental stores force the sync path (their
        digest chain cannot interleave with background writes) with a
        counted ``persistFallbackReason``."""
        from siddhi_tpu.util.persistence import IncrementalPersistenceStore
        from siddhi_tpu.util.snapshot import SnapshotService

        store = self._persistence_store()
        svc = self._snapshot_service()
        if mode is None:
            mode = self.app_context.persist_mode
        if mode not in ("sync", "async"):
            raise SiddhiAppRuntimeError(
                f"app '{self.name}': persist mode {mode!r} must be "
                "'sync' or 'async'")
        sm = self.app_context.statistics_manager
        st = self._durability_stats()
        if mode == "async" and isinstance(store, IncrementalPersistenceStore):
            if sm is not None:
                sm.record_persist_fallback(self.name,
                                           "incremental-store-sync-only")
            mode = "sync"
        revision = SnapshotService.new_revision(self.name)
        jr = self.app_context.input_journal
        if mode == "sync" and isinstance(store, IncrementalPersistenceStore):
            # historical incremental path, unchanged
            for s in self.sources:
                s.pause()
            self.drain_device_emits()
            try:
                kind, data = svc.incremental_snapshot()
                store.save(self.name, revision, kind, data)
            finally:
                for s in self.sources:
                    s.resume()
            st.persists_sync += 1
            if jr is not None:
                jr.mark_revision(revision)
            return revision

        def on_fallback(element, reason):
            st.capture_fallback_elements += 1
            if sm is not None:
                sm.record_persist_fallback(f"{self.name}.{element}", reason)

        # quiesce external input around the capture
        # (reference: SiddhiAppRuntimeImpl.persist:677-691 pauses sources)
        for s in self.sources:
            s.pause()
        # barrier: queued device emits must land in downstream state
        # (selectors, windows, tables) before it is captured
        self.drain_device_emits()
        tracer = self.app_context.tracer
        try:
            t_cap = tracer.clock() if tracer is not None else 0.0
            capture = svc.capture(on_fallback=on_fallback)
            if tracer is not None:
                # the in-barrier capture is THE persist-path stall the
                # batch loop feels — span it like a pipeline stage
                tracer.record_span("persist.capture", "persist",
                                   t_cap, tracer.clock())
            if jr is not None:
                # watermark + ledger counts at the capture point; the
                # prune happens at commit, AFTER the store write lands
                jr.note_capture(revision)
        finally:
            for s in self.sources:
                s.resume()
        if mode == "async":
            writer = self._durability_writer()
            writer.submit(
                revision,
                lambda: self._persist_write(store, revision, capture),
                on_abandon=jr.drop_mark if jr is not None else None)
            return revision
        fi = self.app_context.fault_injector
        try:
            if fi is not None:
                fi.check("persist.write")
            self._persist_write(store, revision, capture)
        except Exception:
            # failed sync persist: abandon the journal mark so a later
            # commit cannot prune uncovered entries.  A simulated crash
            # (BaseException) keeps the mark — the journal models a log
            # that survives the process, marks included.
            if jr is not None:
                jr.drop_mark(revision)
            raise
        st.persists_sync += 1
        return revision

    def snapshot(self) -> bytes:
        """Raw snapshot bytes without a store (reference:
        SiddhiAppRuntimeImpl.snapshot)."""
        self.drain_device_emits()
        return self._snapshot_service().full_snapshot()

    def restore(self, snapshot: bytes):
        self._flush_persists()
        # barrier: pending emits flush into the PRE-restore state (the
        # synchronous path delivered them before restore was called)
        self.drain_device_emits()
        self._snapshot_service().restore(snapshot)
        jr = self.app_context.input_journal
        if jr is not None:
            # raw-bytes restore: the journal's revision mark and output
            # ledger no longer correspond to the restored state
            jr.reset()

    def _replay_journal(self, revision: str):
        """Restore-and-replay second half: re-send every input batch the
        journal recorded after ``revision`` was persisted, with the
        output ledger suppressing already-delivered callback/sink events
        — the observable sequence ends up bit-identical to an
        uninterrupted run (util/faults.py InputJournal)."""
        import logging

        log = logging.getLogger("siddhi_tpu")
        jr = self.app_context.input_journal
        if jr is None:
            return
        entries = jr.entries_after(revision)
        if entries is None:
            log.warning(
                "app '%s': input journal cannot replay after revision "
                "'%s' (unmarked revision or journal overflow); restored "
                "state only — post-checkpoint input is lost", self.name,
                revision)
            jr.reset()
            return
        if not self.app_context.app_running:
            if entries:
                log.warning(
                    "app '%s': %d journaled batch(es) pending but the "
                    "app is not running; start() it before restoring to "
                    "replay", self.name, len(entries))
            return
        jr.begin_replay(revision)
        try:
            for stream_id, batch in entries:
                self.input_manager.get_input_handler(stream_id).send_batch(
                    batch)
                if jr.stats is not None:
                    jr.stats.replayed_batches += 1
            # barrier INSIDE the replay window: deferred emits produced
            # by replayed batches must flow through the suppressing
            # ledger, not escape after end_replay as duplicates
            self.drain_device_emits()
        finally:
            jr.end_replay()
        if entries:
            log.info("app '%s': replayed %d journaled batch(es) after "
                     "revision '%s'", self.name, len(entries), revision)

    def restore_revision(self, revision: str):
        from siddhi_tpu.util.persistence import IncrementalPersistenceStore

        self._flush_persists()
        store = self._persistence_store()
        if isinstance(store, IncrementalPersistenceStore):
            chain = store.load_chain(self.name, until_revision=revision)
            if chain is None:
                raise SiddhiAppRuntimeError(
                    f"app '{self.name}': no base snapshot at or before "
                    f"revision '{revision}'")
            _, base_bytes, incs = chain
            self._snapshot_service().restore_incremental(
                base_bytes, [b for _, b in incs])
            self._replay_journal(revision)
            return
        data = store.load(self.name, revision)
        if data is None:
            raise SiddhiAppRuntimeError(
                f"app '{self.name}': revision '{revision}' not found"
            )
        # inline (not self.restore): the journal must survive the state
        # restore so the post-checkpoint batches can replay after it
        self.drain_device_emits()
        self._snapshot_service().restore(data)
        self._replay_journal(revision)

    def restore_last_revision(self) -> Optional[str]:
        """Restore the newest saved revision; returns its id (None when no
        revision exists — reference: SiddhiAppRuntimeImpl.restoreLastRevision).
        With an incremental store, replays newest base + later increments.
        A corrupted newest revision (truncated file, bad unpickle) is
        skipped with a warning and the walk falls back to older ones."""
        import logging

        from siddhi_tpu.core.exceptions import (
            CannotRestoreSiddhiAppStateError,
        )
        from siddhi_tpu.util.persistence import IncrementalPersistenceStore

        log = logging.getLogger("siddhi_tpu")
        # crash-restore post-mortem: freeze the pre-restore span ring
        # BEFORE state is replaced — it is the last evidence of what the
        # pipeline was doing when the previous incarnation died
        tracer = self.app_context.tracer
        if tracer is not None:
            tracer.dump("crash-restore")
        self._flush_persists()
        store = self._persistence_store()
        if isinstance(store, IncrementalPersistenceStore):
            chain = store.load_chain(self.name)
            if chain is None:
                return None
            base_rev, base_bytes, incs = chain
            self._snapshot_service().restore_incremental(
                base_bytes, [b for _, b in incs]
            )
            rev = incs[-1][0] if incs else base_rev
            self._replay_journal(rev)
            return rev
        revs = store.revisions(self.name)
        if not revs:
            return None
        last_error = None
        for rev in reversed(revs):
            try:
                self.restore_revision(rev)
                return rev
            except Exception as e:
                last_error = e
                log.warning(
                    "app '%s': revision '%s' failed to restore (%s); "
                    "falling back to the previous revision", self.name,
                    rev, e)
        raise CannotRestoreSiddhiAppStateError(
            f"app '{self.name}': all {len(revs)} persisted revisions "
            f"failed to restore (last error: {last_error})")

    def clear_all_revisions(self):
        self._flush_persists()
        self._persistence_store().clear_all_revisions(self.name)

    # Java-style aliases
    restoreRevision = restore_revision
    restoreLastRevision = restore_last_revision

    def get_stream_definitions(self):
        return self.siddhi_app.stream_definitions

    def query_names(self) -> List[str]:
        return list(self.query_runtimes)
