"""SiddhiManager: the top-level entry point.

Mirrors the reference ``io.siddhi.core.SiddhiManager`` (SiddhiManager.java:49):
holds the per-manager context (extensions, persistence stores) and
creates/tracks app runtimes.
"""

from __future__ import annotations

from typing import Dict, Optional, Union

from siddhi_tpu.compiler import SiddhiCompiler
from siddhi_tpu.core.app_runtime import SiddhiAppRuntime
from siddhi_tpu.core.context import SiddhiContext
from siddhi_tpu.query_api import SiddhiApp


class SiddhiManager:
    def __init__(self):
        self.siddhi_context = SiddhiContext()
        self._app_runtimes: Dict[str, SiddhiAppRuntime] = {}

    def create_siddhi_app_runtime(self, app: Union[str, SiddhiApp],
                                  register: bool = True) -> SiddhiAppRuntime:
        from siddhi_tpu.planner.app_planner import AppPlanner

        if isinstance(app, str):
            app_string = SiddhiCompiler.update_variables(app)
            siddhi_app = SiddhiCompiler.parse(app_string)
        else:
            app_string = ""
            siddhi_app = app
        runtime = AppPlanner(siddhi_app, app_string, self.siddhi_context).build()
        runtime._manager = self
        if register:
            self._app_runtimes[runtime.name] = runtime
        return runtime

    # Java-style alias
    createSiddhiAppRuntime = create_siddhi_app_runtime

    def validate_siddhi_app(self, app: Union[str, SiddhiApp]):
        """Plan the app end-to-end, then discard it — raises
        SiddhiAppCreationError/SiddhiParserError on any problem
        (reference: SiddhiManager.validateSiddhiApp:144-165)."""
        # unregistered: validating 'X' must not disturb a running 'X'
        runtime = self.create_siddhi_app_runtime(app, register=False)
        runtime.shutdown()

    def create_sandbox_siddhi_app_runtime(self, app: Union[str, SiddhiApp]) -> SiddhiAppRuntime:
        """Create a runtime with external transports stripped: non-inMemory
        @source/@sink and every @store annotation are removed so the app
        runs fully in-process (reference:
        SiddhiManager.createSandboxSiddhiAppRuntime:104-132)."""
        if isinstance(app, str):
            app = SiddhiCompiler.parse(SiddhiCompiler.update_variables(app))
        else:
            import copy

            app = copy.deepcopy(app)  # never strip the caller's object

        def keep(ann) -> bool:
            nm = ann.name.lower()
            if nm not in ("source", "sink"):
                return True
            return (ann.element("type") or "").lower() == "inmemory"

        for sd in app.stream_definitions.values():
            sd.annotations[:] = [a for a in sd.annotations if keep(a)]
        for td in app.table_definitions.values():
            td.annotations[:] = [a for a in td.annotations if a.name.lower() != "store"]
        return self.create_siddhi_app_runtime(app)

    # Java-style aliases
    validateSiddhiApp = validate_siddhi_app
    createSandboxSiddhiAppRuntime = create_sandbox_siddhi_app_runtime

    def get_attributes(self) -> Dict[str, object]:
        return self.siddhi_context.attributes

    def set_attribute(self, key: str, value):
        """Shared objects visible to extensions
        (reference: SiddhiManager.setAttribute:76)."""
        self.siddhi_context.attributes[key] = value

    def remove_extension(self, name: str, kind: str = "function"):
        ns, _, nm = name.rpartition(":")
        self.siddhi_context.extensions.unregister(kind, nm, ns or None)

    def get_siddhi_app_runtime(self, name: str) -> Optional[SiddhiAppRuntime]:
        return self._app_runtimes.get(name)

    def get_siddhi_app_runtimes(self):
        return dict(self._app_runtimes)

    def health(self) -> Dict[str, dict]:
        """Overload-protection health of every registered app (the
        manager-wide roll-up of ``SiddhiAppRuntime.health`` — what
        ``GET /siddhi-health/<app>`` serves per app)."""
        return {name: rt.health()
                for name, rt in sorted(self._app_runtimes.items())}

    def set_extension(self, name: str, factory, kind: str = "function"):
        """Register a custom extension: name may be 'ns:name' or 'name'
        (reference: SiddhiManager.setExtension)."""
        ns, _, nm = name.rpartition(":")
        self.siddhi_context.extensions.register(kind, nm, factory, ns or None)

    def set_persistence_store(self, store):
        self.siddhi_context.persistence_store = store

    def set_source_handler_manager(self, m):
        """HA interception for sources (reference:
        SiddhiManager.setSourceHandlerManager:185)."""
        self.siddhi_context.source_handler_manager = m

    def set_sink_handler_manager(self, m):
        """reference: SiddhiManager.setSinkHandlerManager:176"""
        self.siddhi_context.sink_handler_manager = m

    def set_record_table_handler_manager(self, m):
        """reference: SiddhiManager.setRecordTableHandlerManager:194"""
        self.siddhi_context.record_table_handler_manager = m

    def set_data_source(self, name: str, data_source):
        """Named shared data sources for store extensions
        (reference: SiddhiManager.setDataSource:245)."""
        self.siddhi_context.data_sources[name] = data_source

    setSourceHandlerManager = set_source_handler_manager
    setSinkHandlerManager = set_sink_handler_manager
    setRecordTableHandlerManager = set_record_table_handler_manager
    setDataSource = set_data_source

    def set_config_manager(self, config_manager):
        """Deployment config source for extensions and refs
        (reference: SiddhiManager.setConfigManager:203)."""
        self.siddhi_context.config_manager = config_manager

    setConfigManager = set_config_manager

    def persist(self):
        for rt in list(self._app_runtimes.values()):
            rt.persist()

    def restore_last_state(self):
        """Restore every app to its newest saved revision
        (reference: SiddhiManager.restoreLastState:292)."""
        for rt in list(self._app_runtimes.values()):
            rt.restore_last_revision()

    # Java-style aliases
    setPersistenceStore = set_persistence_store
    restoreLastState = restore_last_state

    def shutdown(self):
        for rt in list(self._app_runtimes.values()):
            rt.shutdown()
        self._app_runtimes.clear()
