"""Typed runtime exceptions (reference: io/siddhi/core/exception/*)."""


class SiddhiAppCreationError(Exception):
    """Raised when an app fails to plan/compile
    (reference: SiddhiAppCreationException)."""


class SiddhiAppRuntimeError(Exception):
    """Raised for failures while processing events
    (reference: SiddhiAppRuntimeException)."""


class DefinitionNotExistError(SiddhiAppCreationError):
    """Unknown stream/table/window referenced
    (reference: DefinitionNotExistException)."""


class StoreQueryCreationError(Exception):
    """On-demand query failed to plan
    (reference: OnDemandQueryCreationException)."""


class CannotRestoreSiddhiAppStateError(Exception):
    """Snapshot restore failed
    (reference: CannotRestoreSiddhiAppStateException)."""


class ConnectionUnavailableError(Exception):
    """Source/Sink transport connection failure; triggers backoff retry
    (reference: ConnectionUnavailableException)."""


class InjectedFaultError(SiddhiAppRuntimeError):
    """Deterministic fault raised by the fault-injection harness
    (util/faults.py) at a runtime choke point.  No reference analog:
    the TPU build's chaos-testing surface."""


class TransferFaultError(InjectedFaultError):
    """Transient device<->host transfer failure (injected, or classed
    retryable by a hook).  The async emit pipeline retries these with
    bounded backoff before routing to the fault handler."""


class DeviceLostError(InjectedFaultError):
    """Sticky device loss: NOT retryable — every transfer against the
    lost device fails until the runtime is restored onto a healthy
    one."""


class SimulatedCrashError(BaseException):
    """Injected process crash.  Deliberately a BaseException: it must
    tear through every ``except Exception`` recovery layer exactly as a
    SIGKILL would, so crash-recovery tests exercise the real
    restore-and-replay path rather than some hardened catch site."""


class OnErrorAction:
    """@OnError(action=...) values (reference: StreamJunction.OnErrorAction)."""

    LOG = "log"
    STREAM = "stream"
    STORE = "store"


class SiddhiParserException(SiddhiAppCreationError):
    """Alias space for compiler errors surfaced through app creation."""


class NoSuchAttributeError(SiddhiAppCreationError):
    """Attribute not found on a definition
    (reference: NoSuchAttributeException)."""


class QueryNotExistError(SiddhiAppRuntimeError):
    """Unknown query name (reference: QueryNotExistException)."""


class OperationNotSupportedError(SiddhiAppRuntimeError):
    """Operation not valid for the target element
    (reference: OperationNotSupportedException)."""


class OnDemandQueryRuntimeError(SiddhiAppRuntimeError):
    """On-demand query failed during execution
    (reference: OnDemandQueryRuntimeException)."""


class NoPersistenceStoreError(SiddhiAppRuntimeError):
    """persist() without a configured store
    (reference: NoPersistenceStoreException)."""


class PersistenceStoreError(SiddhiAppRuntimeError):
    """Store-level save/load failure
    (reference: PersistenceStoreException)."""


class CannotClearSiddhiAppStateError(SiddhiAppRuntimeError):
    """Revision cleanup failed
    (reference: CannotClearSiddhiAppStateException)."""


class DataPurgingError(SiddhiAppRuntimeError):
    """Incremental-aggregation purge failure
    (reference: DataPurgingException)."""


class QueryableRecordTableError(SiddhiAppRuntimeError):
    """Store-side query compilation/execution failure
    (reference: QueryableRecordTableException)."""


class CannotLoadConfigurationError(SiddhiAppCreationError):
    """Config plane failure (reference: CannotLoadConfigurationException,
    YAMLConfigManagerException)."""


class SiddhiAppValidationError(SiddhiAppCreationError):
    """Plan-time validation failure — bad extension arguments, invalid
    definitions (reference: SiddhiAppValidationException)."""


# Java-style aliases (the reference's exact names, for drop-in familiarity)
SiddhiAppCreationException = SiddhiAppCreationError
SiddhiAppValidationException = SiddhiAppValidationError
SiddhiAppRuntimeException = SiddhiAppRuntimeError
OnDemandQueryCreationException = StoreQueryCreationError
StoreQueryCreationException = StoreQueryCreationError
CannotRestoreSiddhiAppStateException = CannotRestoreSiddhiAppStateError
ConnectionUnavailableException = ConnectionUnavailableError
DefinitionNotExistException = DefinitionNotExistError
