"""Typed runtime exceptions (reference: io/siddhi/core/exception/*)."""


class SiddhiAppCreationError(Exception):
    """Raised when an app fails to plan/compile
    (reference: SiddhiAppCreationException)."""


class SiddhiAppRuntimeError(Exception):
    """Raised for failures while processing events
    (reference: SiddhiAppRuntimeException)."""


class DefinitionNotExistError(SiddhiAppCreationError):
    """Unknown stream/table/window referenced
    (reference: DefinitionNotExistException)."""


class StoreQueryCreationError(Exception):
    """On-demand query failed to plan
    (reference: OnDemandQueryCreationException)."""


class CannotRestoreSiddhiAppStateError(Exception):
    """Snapshot restore failed
    (reference: CannotRestoreSiddhiAppStateException)."""


class ConnectionUnavailableError(Exception):
    """Source/Sink transport connection failure; triggers backoff retry
    (reference: ConnectionUnavailableException)."""


class OnErrorAction:
    """@OnError(action=...) values (reference: StreamJunction.OnErrorAction)."""

    LOG = "log"
    STREAM = "stream"
    STORE = "store"
