"""Contexts: per-manager and per-app shared services.

Mirrors the reference ``core/config/`` (SiddhiContext / SiddhiAppContext,
SURVEY.md §2.2 Contexts) minus JVM thread machinery: the TPU build is
deterministic batch processing, so ThreadBarrier becomes a simple
processing lock and partition/group-by flow ids become explicit keyed-state
indices rather than ThreadLocals.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional


class TimestampGenerator:
    """Event/wall time source.  In playback mode (@app:playback) current
    time derives from event timestamps (reference:
    util/timestamp/TimestampGeneratorImpl.java:31, currentTime :78)."""

    def __init__(self, playback: bool = False, increment_ms: int = 0):
        self.playback = playback
        self.increment_ms = increment_ms
        self._event_time: int = -1
        self.last_update_wall: float = time.monotonic()

    def current_time(self) -> int:
        if self.playback:
            return self._event_time + self.increment_ms if self._event_time >= 0 else 0
        return int(time.time() * 1000)

    def set_event_time(self, ts: int):
        self.last_update_wall = time.monotonic()
        if ts > self._event_time:
            self._event_time = ts

    def advance_idle(self) -> int:
        """Idle heartbeat: push event time forward by the increment when no
        events arrive (reference: TimestampGeneratorImpl idle-time timer)."""
        self.last_update_wall = time.monotonic()
        if self._event_time >= 0:
            self._event_time += self.increment_ms
        return self.current_time()


class ProgressBeat:
    """Monotone liveness counter for the watchdog (robustness/).

    Bumped on every journaled ingest and every junction dispatch — one
    integer increment per BATCH, not per event, so the hot path cost is
    negligible and behavior stays bit-identical.  The watchdog reads it
    against the pending-work gauges: beats frozen + work pending =
    stalled batch cycle.
    """

    __slots__ = ("beats",)

    def __init__(self):
        self.beats = 0

    def beat(self):
        self.beats += 1


class SiddhiContext:
    """Per-manager shared state: extensions, persistence stores, config
    (reference: config/SiddhiContext)."""

    def __init__(self):
        from siddhi_tpu.extension.registry import default_registry

        self.extensions = default_registry()
        self.persistence_store = None
        self.config: Dict[str, str] = {}
        from siddhi_tpu.util.config import InMemoryConfigManager

        self.config_manager = InMemoryConfigManager()
        self.attributes: Dict[str, object] = {}
        self.data_sources: Dict[str, object] = {}
        self.source_handler_manager = None
        self.sink_handler_manager = None
        self.record_table_handler_manager = None
        # Crash-recovery journals keyed by app name: the journal lives on
        # the MANAGER context so it survives a simulated runtime crash —
        # a fresh runtime for the same app picks it up and replays
        # post-checkpoint batches (util/faults.py InputJournal).
        self.input_journals: Dict[str, object] = {}
        # Multiplex groups live on the MANAGER context because grouping
        # is cross-app: distinct apps under one manager share engines
        # when their queries fingerprint alike (multiplex/registry.py).
        # Lazily created by the planner on first @app:multiplex app.
        self.multiplex_registry = None


class SiddhiAppContext:
    """Per-app shared state: name, time, scheduler, snapshot service,
    statistics (reference: config/SiddhiAppContext)."""

    def __init__(self, siddhi_context: SiddhiContext, name: str):
        self.siddhi_context = siddhi_context
        self.name = name
        self.playback = False
        self.playback_idle_ms = 0
        self.enforce_order = False
        self.root_metrics_level = "off"
        # @app:execution('tpu' | 'host'): 'tpu' routes eligible queries
        # through the jitted device paths with host fallback (the
        # BASELINE.json north-star gate); tpu_partitions sizes the
        # partition axis of dense pattern state, tpu_instances its
        # per-(partition, node) pending-instance capacity
        # reference contract: InputHandler.send before start()/after
        # shutdown() raises "app is not running" (InputHandler.java:50)
        self.app_running = False
        self.execution_mode = "host"
        self.tpu_partitions = 65536
        self.tpu_instances = 4
        # @app:execution('tpu', devices='N'): shard the dense partition
        # axis over an N-device jax.sharding.Mesh (None = single device)
        self.tpu_devices = None
        # @app:execution('tpu', emit.depth='N'): pending-emit queue
        # depth of the async emit pipeline (core/emit_queue.py) — device
        # runtimes hold up to N matched batches device-resident before
        # one coalesced drain.  1 (default) drains after every batch.
        # 'auto' derives the effective depth at runtime from observed
        # transfer RTT vs batch cadence (EmitDepthController).
        self.tpu_emit_depth = 1
        # @app:execution('tpu', ingest.depth='N'): ingest staging window
        # (core/ingest_stage.py) — each batch's count-gate fetch defers
        # until N-1 later batches have dispatched, overlapping H2D
        # transfer with the jitted step.  1 (default) = synchronous;
        # 'auto' = RTT-vs-cadence adaptive (EmitDepthController).
        self.tpu_ingest_depth = 1
        # @app:execution('tpu', agg.device.min.batch='N'): minimum batch
        # size before incremental aggregation uses the jitted device
        # segment-reduce instead of the host np.add.at path
        self.tpu_agg_min_batch = 512
        # @app:multiplex(slots='N'): pack this app's eligible queries
        # into manager-wide shared device engines (multiplex/) so ONE
        # jitted step serves every compatible tenant per cycle.  Off by
        # default; slots bounds the tenant axis of each shared engine.
        self.multiplex = False
        self.multiplex_slots = 8
        # @app:fuse: fuse chains of device-lowered queries linked by
        # `insert into` streams into ONE jitted program per chain, with
        # intermediate event columns kept in HBM (planner/fusion.py).
        # Off by default; ineligible chains fall back to the junction
        # path with counted reasons.
        self.fuse = False
        # @app:hotkeys(k='8', promote='0.25', demote='0.10'): skew-aware
        # hot-key routing (core/hotkey_router.py) — partitioned dense
        # pattern queries watch the junction's key histogram with a
        # space-saving sketch and route keys whose decayed traffic share
        # crosses `promote` onto the batched associative-scan engine
        # (k slots); they return to the dense path below `demote`
        # (hysteresis: demote < promote or thrash).  Off by default;
        # ineligible queries fall back with counted reasons.
        self.hotkeys = False
        self.hotkey_k = 8
        self.hotkey_promote = 0.25
        self.hotkey_demote = 0.10
        # @app:kernels('nfa,bank,scan'): swap the hot inner step of
        # eligible runtimes for hand-written Pallas kernels
        # (siddhi_tpu/kernels/), each pinned bit-identical to the XLA
        # formulation it replaces (planner/kernels.py).  Off by
        # default; ineligible/unlowertable cases fall back with counted
        # kernelFallbackReasons.
        self.kernels = False
        self.kernel_kinds = ("nfa", "bank", "scan")
        # @app:devtables(capacity='N'): store eligible tables as
        # device-resident columnar arrays (siddhi_tpu/devtable/) — one
        # [capacity] device column per attribute + validity lane, jitted
        # scatter mutations, [B,C] masked join probes.  Off by default;
        # ineligible tables/queries keep the host path with counted
        # devtableFallbackReasons.  capacity is the per-table slot count.
        self.devtables = False
        self.devtable_capacity = 1024
        # @app:plan(auto='true', hysteresis='0.3', interval='5 sec'):
        # cost-based unified lowering (planner/costmodel.py).  auto turns
        # the model on for un-annotated queries — it enumerates every
        # eligible lowering, scores them statically and picks the
        # cheapest; legacy annotations stay pins that override it.
        # hysteresis is the margin an alternative's predicted cost must
        # beat the active plan's observed cost by before the PlanMonitor
        # re-lowers the live query; interval (0 = no daemon) paces the
        # monitor's background sweep.
        self.plan_auto = False
        self.plan_hysteresis = 0.3
        self.plan_interval_ms = 0
        # Per-query path pins ('device', 'dense+hotkey', ...) that
        # override BOTH the annotations and the cost model — the replan
        # machinery rebuilds an app through these so the new runtime
        # lands on the exact target path (core/app_runtime.py replan()).
        self.plan_pins: Dict[str, str] = {}
        # @app:persist(interval='30 sec', mode='async'): default persist()
        # mode ('sync' keeps the historical stop-the-world behavior;
        # 'async' captures under the barrier and writes on the checkpoint
        # writer thread — durability/) and the optional periodic-persist
        # daemon interval (0 = no daemon).
        self.persist_mode = "sync"
        self.persist_interval_ms = 0
        # @app:limits(rate='N/s', burst='M', shed='drop|oldest|block',
        # block.max='1 sec', watchdog='2 sec', breaker='3',
        # breaker.cooldown='1 sec', ladder='true'): overload protection
        # (robustness/).  All off by default — without the annotation
        # the admission/watchdog/breaker/ladder hooks are None and
        # behavior is bit-identical to an unprotected app.
        self.limits_rate = 0.0          # events/s per stream (0 = off)
        self.limits_burst = 0.0         # bucket depth (default = rate)
        self.limits_shed = "drop"
        self.limits_block_max_ms = 1000
        self.watchdog_deadline_ms = 0   # 0 = watchdog off
        self.breaker_threshold = 0      # 0 = breakers off
        self.breaker_cooldown_ms = 1000
        self.ladder = False
        # degradation-ladder rung currently applied (replan() threads it
        # through each rebuilt context via robustness.apply_degradation)
        # plus the features that rung disabled — a rebuilt context's
        # annotation flags no longer show them as enabled, so the ladder
        # needs this record to keep its rung list (and the ability to
        # re-promote) across the rebuild
        self.degrade_level = 0
        self.degraded_features = ()
        # live robustness handles: counters, admission controller.
        # Created by the planner when @app:limits is present; replan()
        # re-adopts BOTH onto the replacement context so budgets and
        # shed accounting survive a self-heal like the journal does.
        self.robustness = None
        self.admission = None
        # watchdog liveness counter — always present, always beating
        self.progress = ProgressBeat()
        self.timestamp_generator = TimestampGenerator()
        # one re-entrant lock quiesces the whole app for snapshot/restore —
        # the ThreadBarrier analog (reference: util/ThreadBarrier.java:30)
        self.process_lock = threading.RLock()
        self.scheduler = None  # set by app runtime
        self.snapshot_service = None  # set by app runtime
        self.statistics_manager = None
        self.exception_listeners: List = []
        # @app:faults(...) fault-injection harness (util/faults.py).
        # None when chaos testing is off — every hook site no-ops.
        self.fault_injector = None
        # Cycle-correlated span tracer + flight recorder
        # (observability/trace.py), created unconditionally by the
        # planner (default-on at 1-in-64 sampling; @app:trace tunes or
        # disables it).  None only for hand-built contexts in tests.
        self.tracer = None
        # Bounded input journal for restore-and-replay (util/faults.py
        # InputJournal); shared through siddhi_context.input_journals so
        # it outlives a crashed runtime.  None = journaling disabled.
        self.input_journal = None

    def set_playback(self, enabled: bool, increment_ms: int = 0):
        self.playback = enabled
        self.timestamp_generator.playback = enabled
        self.timestamp_generator.increment_ms = increment_ms
