"""Device (jitted TPU) execution of general single-stream queries inside
the product engine.

The glue the planner uses to route `SiddhiManager`-created
filter/window/group-by queries through the jitted device pipeline
(ops/device_query.py) instead of the host columnar chain — the analog of
the reference planner wiring ProcessStreamReceiver -> FilterProcessor ->
WindowProcessor -> QuerySelector
(util/parser/QueryParser.java:90, query/input/ProcessStreamReceiver.java:99-179,
query/selector/QuerySelector.java:76-99), re-designed so the hot path is
one jit-compiled step over columnar micro-batches with per-group state
rows in device memory.

Activation: ``@app:execution('tpu')``.  The planner attempts device
lowering for every eligible single-stream query and falls back to the
host engine — logging the reason — when the query is outside the device
subset (unsupported windows/aggregators, non-traceable expressions,
LONG-typed device operands, order-by/limit, non-CURRENT output event
types, ...).  See ops/device_query.py's module docstring for the full
subset contract, including the float32 precision stance.

Emission subset: the device path emits CURRENT events only (the default
``insert into``/callback contract).  Queries whose output event type is
'expired' or 'all' — i.e. consumers of window-expiry events — keep the
host engine, as do queries reading named windows (whose CURRENT+EXPIRED
feed drives add/remove aggregation).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np

from siddhi_tpu.core import event as ev
from siddhi_tpu.core.emit_queue import EmitQueue, EmitStats, PendingEmit
from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.ingest_stage import IngestStage, IngestStats
from siddhi_tpu.core.exceptions import SiddhiAppRuntimeError

import logging

log = logging.getLogger("siddhi_tpu")


class DeviceQueryRuntime:
    """Product-side wrapper of one DeviceQueryEngine: converts junction
    batches to device columns, advances per-group state with the jitted
    step, and emits output batches into the query's output chain.

    Emission runs through the async emit pipeline (core/emit_queue.py):
    each junction batch fetches ONE match-count scalar; zero-match
    batches transfer nothing, matched batches stay device-resident in a
    bounded pending-emit queue (``@app:execution('tpu',
    emit.depth='N')``; default 1 drains immediately) until a coalesced
    drain.  Every host-observable point — snapshot/restore, timer
    fires, pull queries, shutdown — calls :meth:`drain` first, so
    callback content and order are bit-identical to the synchronous
    path.

    Also a scheduler task: ``next_wakeup``/``fire`` drive timer-based
    timeBatch pane flushes so tumbling panes close on watermark time
    even when no further events arrive (the host TimeBatchWindow's
    scheduler contract)."""

    def __init__(self, engine, out_stream_id: str,
                 emit: Callable[[EventBatch], None], emit_depth=1,
                 clock: Optional[Callable[[], int]] = None, faults=None,
                 ingest_depth=1, tracer=None):  # int or 'auto'
        self.engine = engine
        self.out_stream_id = out_stream_id
        self.emit_cb = emit
        self.state = engine.init_state()
        # cycle-correlated span tracer (observability/trace.py), wired by
        # the planner; the engine kind labels this runtime's spans
        self.tracer = tracer
        self.engine_kind = getattr(engine, "engine_kind", "device")
        self.step_invocations = 0  # proof the jitted path ran (tests)
        self.emit_stats = EmitStats()
        # @app:faults(...) injector: arms the emit.drain/state.poison
        # sites and the isolation hook so a failing drain batch is
        # logged + fed to exception listeners instead of killing the app
        self.faults = faults
        self.emit_queue = EmitQueue(depth=emit_depth, stats=self.emit_stats,
                                    faults=faults, on_fault=self._on_fault)
        # ingest staging window (@app:execution('tpu', ingest.depth='N')):
        # depth 2 defers each batch's count-gate fetch until the NEXT
        # batch's H2D put + step dispatch are in flight, overlapping
        # transfer with compute; depth 1 (default) finishes inline —
        # identical timing to synchronous ingest.  The engine carries the
        # stats ref so staged_put (ops layer) counts its device puts.
        self.ingest_stats = IngestStats()
        engine.ingest_stats = self.ingest_stats
        self.ingest_stage = IngestStage(
            depth=ingest_depth, stats=self.ingest_stats, faults=faults,
            on_fault=self._on_fault)
        # last known-poison-free host copy of the device state, kept
        # only while a state.poison fault is armed (quarantine source)
        self._last_good = None
        # app clock sampled at ENQUEUE time: deferred emits replay with
        # the `now` the synchronous path would have used (time-based
        # rate limiters key their period grid off it)
        self.clock = clock

    def _on_fault(self, e: BaseException):
        # a batch just died in isolation (@OnError route): freeze the
        # span ring so the post-mortem shows the cycles leading up to it
        if self.tracer is not None:
            self.tracer.dump(f"onerror-isolation:{type(e).__name__}")
        if self.faults is not None:
            self.faults.notify(e)

    def _poison_guard(self) -> bool:
        """NaN/Inf quarantine, active only while a ``state.poison``
        fault is armed.  Poisons the state when the fault trips, then
        scans it; on detection, re-materializes from the last clean host
        copy (or re-initializes) and reports True so the caller drops
        the corrupted batch's outputs."""
        fi = self.faults
        if fi is None or not fi.watches("state.poison"):
            return False
        from siddhi_tpu.util import faults as _faults

        if fi.poisoned("state.poison"):
            self.state = _faults.poison_state(self.state)
        if not _faults.state_has_poison(self.state):
            self._last_good = _faults.host_copy(self.state)
            return False
        fi.stats.poison_quarantines += 1
        eng = self.engine
        if self._last_good is not None:
            log.error("device state poisoned (NaN/Inf); quarantining "
                      "batch and re-materializing last clean state")
            if hasattr(eng, "put_state"):  # sharded: restore placement
                self.state = eng.put_state(self._last_good)
            else:
                jnp = eng.jnp
                self.state = {
                    k: jnp.asarray(v) for k, v in self._last_good.items()
                }
        else:
            log.error("device state poisoned (NaN/Inf) with no clean "
                      "copy; quarantining batch and re-initializing")
            self.state = eng.init_state()
        return True

    # -- event path ----------------------------------------------------------

    def process_stream_batch(self, batch: EventBatch, keys=None):
        """Advance the device pipeline with a junction batch.  Only
        CURRENT rows drive it (control events — TIMER/RESET — have no
        device meaning; RESET cannot reach a device query because batch
        windows, their only producer, are ineligible upstream).
        ``keys`` (partition mode): raw partition-key value per row,
        already aligned to the batch's CURRENT rows by the partition
        receiver."""
        cur = batch.only(ev.CURRENT)
        n = len(cur)
        if n == 0:
            return
        # one sampled-or-None cycle token per junction batch: ingest
        # span starts here, at receive time
        tok = (self.tracer.begin_cycle(self.engine_kind, n)
               if self.tracer is not None else None)
        eng = self.engine
        cols = {
            a: np.asarray(cur.columns[a])
            for a in eng.all_attrs if a in cur.columns
        }
        ts = np.asarray(cur.timestamps, dtype=np.int64)
        self.state, pending = eng.process_batch_deferred(
            self.state, cols, ts, part_keys=keys)
        self.step_invocations += 1
        if self._poison_guard():
            # corrupted step: state was re-materialized from the last
            # clean copy; this batch's device outputs are quarantined
            if tok is not None:
                tok.aborted("step")
            if self.tracer is not None:
                self.tracer.dump("poison-quarantine")
            return
        # `now` is the clock the SYNCHRONOUS path would have read; the
        # finish step may run a batch later (ingest.depth > 1), so it is
        # captured here, at receive time
        now = self.clock() if self.clock is not None else None

        def _finish(p=pending, t=now, tk=tok):
            if p is None:
                c = 0
            else:
                c = p.resolve()
            if tk is not None:
                # count gate resolved: the jitted step finished
                tk.step_done(c)
            if c == 0:
                self.emit_queue.skip()
                return
            self.emit_queue.push(PendingEmit(
                p.device_arrays(),
                lambda host, pp=p, tt=t: self._emit_deferred(pp, host, tt),
                trace=tk))

        # the count-gate fetch (resolve) is what blocks on the device;
        # staging it lets batch N+1's H2D put + step dispatch go out
        # before batch N's scalar is fetched
        self.ingest_stage.submit(
            pending.probe() if pending is not None else None, _finish,
            trace=tok)

    def drain(self):
        """Flush barrier: materialize and emit every queued batch (one
        coalesced transfer).  Called wherever host code could observe
        emit timing — snapshot/restore, timer fires, rate-limiter
        decisions, pull queries, shutdown, debugger.  The ingest stage
        flushes first: staged batches must enqueue (or skip) before the
        emit queue drains, preserving the synchronous callback order."""
        self.ingest_stage.flush()
        self.emit_queue.drain()

    def _emit_deferred(self, pending, host_arrays, now=None):
        out_cols, out_ts, keys = pending.materialize(host_arrays)
        self._emit(out_cols, out_ts, keys, now=now)

    def purge_idle(self, now: int, idle_ms) -> int:
        """Partition-mode idle-key purge (the dense analog of dropping
        idle PartitionInstances).  Drains first: purged keys' pending
        emits must reach per-key selector state before it is dropped."""
        self.drain()
        self.state, n = self.engine.purge_idle_keys(self.state, now, idle_ms)
        return n

    def _emit(self, out_cols: Dict[str, np.ndarray], out_ts: np.ndarray,
              keys=None, now=None):
        if len(out_ts) == 0:
            return
        mb = EventBatch(
            self.out_stream_id, self.engine.output_names, out_cols,
            out_ts, np.full(len(out_ts), ev.CURRENT, dtype=np.int8),
        )
        if keys is not None:
            if len(keys) != len(mb):
                # a misaligned side channel is a wiring bug: degrading
                # to one global group would be silently wrong per-group
                # output (the host limiter's loud-failure contract,
                # core/query.py GroupBy*RateLimiter)
                raise SiddhiAppRuntimeError(
                    f"device query emitted {len(mb)} rows but "
                    f"{len(keys)} group keys")
            # group-key side channel: per-group/snapshot rate limiters
            # read it exactly like the host selector's
            mb.aux["group_keys"] = list(keys)
        if now is not None:
            mb.aux["emit_now"] = now
        self.emit_cb(mb)

    # -- scheduler task (timeBatch pane flushes) -----------------------------

    def next_wakeup(self) -> Optional[int]:
        return self.engine.pane_wakeup()

    def fire(self, now: int):
        # barrier BEFORE the pane flush: batches processed before this
        # timer tick must emit first (the synchronous order)
        self.drain()
        self.state, out_cols, out_ts = self.engine.flush_due(self.state, now)
        self._emit(out_cols, out_ts,
                   getattr(self.engine, "last_group_keys", None), now=now)

    def on_start(self, now: int):
        pass

    def on_time(self, now: int):
        pass

    # -- snapshot contract ---------------------------------------------------

    def snapshot(self) -> Dict:
        self.drain()
        return {
            "device_state": {k: np.asarray(v) for k, v in self.state.items()},
            "host": self.engine.host_snapshot(),
        }

    def restore(self, state: Dict):
        self.drain()
        self._last_good = None
        eng = self.engine
        if hasattr(eng, "put_state"):  # sharded: restore the placement
            self.state = eng.put_state(state["device_state"])
        else:
            # row-count guard: a snapshot persisted under a SHARDED
            # layout (@app:execution devices='N') has N extra scratch
            # rows and a shard-major row bijection — restoring it here
            # would silently cross-wire group rows
            expect = {k: v.shape for k, v in eng.init_state_host().items()}
            for k, v in state["device_state"].items():
                if k in expect and np.asarray(v).shape != expect[k]:
                    raise SiddhiAppRuntimeError(
                        f"device-query snapshot '{k}' has shape "
                        f"{np.asarray(v).shape}; this engine expects "
                        f"{expect[k]} — persist and restore must use "
                        "the same @app:execution devices count")
            jnp = eng.jnp
            self.state = {
                k: jnp.asarray(v) for k, v in state["device_state"].items()
            }
        eng.host_restore(state["host"])


class _DeviceQueryReceiver:
    """Junction subscriber feeding one device-lowered query."""

    def __init__(self, runtime: DeviceQueryRuntime):
        self.runtime = runtime

    def receive(self, batch: EventBatch):
        self.runtime.process_stream_batch(batch)
