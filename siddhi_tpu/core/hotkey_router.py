"""Hybrid skew router: hot partition keys ride the associative scan.

The partition axis cannot split ONE key's event stream — the dense
engine (ops/dense_nfa.py) advances a partition's events through
sequential collision rounds, so a single hot key throttles the whole
batch cycle (the canonical skew failure under the ROADMAP's
millions-of-users north star).  ``HotKeyRouterRuntime`` wraps a
partitioned ``DensePatternRuntime`` and, per junction cycle:

1. feeds a host-side space-saving heavy-hitter sketch (O(k) state,
   deterministic — crash replay reproduces every routing decision)
   with the cycle's key histogram;
2. applies promote/demote hysteresis (``@app:hotkeys(k, promote,
   demote)`` knobs): keys whose decayed share crosses ``promote`` move
   onto a ``HotKeyScanEngine`` slot (ops/hotkey_scan.py), keys that
   cool below ``demote`` move back;
3. converts pending-match state EXACTLY at each boundary — a dense
   partition row's instance lanes to/from the scan's per-lane
   (youngest start, count) pair — so routing never alters emissions;
4. splits the batch: cold keys take the unchanged dense path, hot
   keys are packed on the scan's ``[H, n_pad]`` slot axis and advance
   in O(log n) scan depth via ONE jitted step.

The hot path rides the dense runtime's OWN machinery: its
``IngestStage`` (``staged_put`` H2D + count-gate staging), its
count-gated async ``EmitQueue`` (the only device→host path — state
handoffs at promote/demote fetch through a queued ``PendingEmit`` +
drain barrier, so the fault harness's ``emit.drain`` retry ladder and
isolation cover them), and the ``state.poison`` quarantine idiom of
``core/device_single.py``.  Emission content is bit-identical to the
host engine on the eligible class; within one cycle the cold
sub-batch's rows emit before the hot sub-batch's (each internally in
event order, carrying ``aux["event_indices"]`` for consumers that need
the interleaved order).

Snapshot/restore demotes every hot key first, so the persisted tree is
a plain dense snapshot (plus sketch counters) — restorable by older
readers and by apps with different ``@app:hotkeys`` settings.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

import numpy as np

from siddhi_tpu.core import event as ev
from siddhi_tpu.core.event import EventBatch

log = logging.getLogger("siddhi_tpu")


class HotKeyStats:
    """Router decision counters (host ints, thin-gauge style — the
    statistics manager reads them live)."""

    __slots__ = ("promotions", "demotions", "routed_events",
                 "routed_cycles", "handoff_aborts")

    def __init__(self):
        self.promotions = 0
        self.demotions = 0
        self.routed_events = 0
        self.routed_cycles = 0
        # state-handoff fetches dropped by a fault (the key kept its
        # previous placement — routing stayed correct, only later)
        self.handoff_aborts = 0


class SpaceSavingSketch:
    """Space-saving heavy hitters: at most ``cap`` counters; a new key
    arriving at capacity evicts the minimum counter and inherits its
    count (the classic overestimate bound).  ``decay`` ages counts each
    cycle so share tracks the recent mix, not all history.  Entirely
    deterministic: same input sequence, same estimates."""

    __slots__ = ("cap", "decay", "counts", "total")

    def __init__(self, cap: int, decay: float = 0.9):
        self.cap = int(cap)
        self.decay = float(decay)
        self.counts: Dict = {}
        self.total = 0.0

    def update(self, keys: np.ndarray, counts: np.ndarray):
        """One cycle's key histogram (np.unique output)."""
        self.total = self.total * self.decay + float(counts.sum())
        for k in list(self.counts):
            v = self.counts[k] * self.decay
            if v < 0.5:
                del self.counts[k]
            else:
                self.counts[k] = v
        for k, c in zip(keys.tolist(), counts.tolist()):
            cur = self.counts.get(k)
            if cur is not None:
                self.counts[k] = cur + c
            elif len(self.counts) < self.cap:
                self.counts[k] = float(c)
            else:
                mk = min(self.counts, key=self.counts.get)
                mv = self.counts.pop(mk)
                self.counts[k] = mv + c

    def share(self, key) -> float:
        if self.total <= 0:
            return 0.0
        return self.counts.get(key, 0.0) / self.total

    def heavy(self, threshold: float) -> List:
        """Keys at or above ``threshold`` share, heaviest first
        (deterministic tie-break on the printable key)."""
        floor = threshold * self.total
        out = [(v, k) for k, v in self.counts.items() if v >= floor]
        out.sort(key=lambda vk: (-vk[0], repr(vk[1])))
        return [k for _v, k in out]


class HotKeyRouterRuntime:
    """Junction-facing wrapper of one partitioned DensePatternRuntime
    plus one HotKeyScanEngine.  Presents the full pattern-processor
    surface; everything not routing-specific delegates to the dense
    runtime (``__getattr__``), so the partition receiver, scheduler,
    snapshot and stats wiring see one runtime."""

    def __init__(self, dense, scan_engine, *, promote: float,
                 demote: float, app_context=None, query_name: str = ""):
        self._dense = dense
        self._scan = scan_engine
        self._promote_at = float(promote)
        self._demote_at = float(demote)
        self._app_context = app_context
        self.query_name = query_name
        self.hot_stats = HotKeyStats()
        self.sketch = SpaceSavingSketch(
            cap=max(16, 4 * scan_engine.n_slots))
        # key -> {"slot": int, "row": dense logical row}
        self._slots: Dict = {}
        self._free_slots: List[int] = list(
            range(scan_engine.n_slots))[::-1]
        self._state = scan_engine.init_state()
        self._last_good = None  # poison-quarantine restore point
        self.faults = dense.faults
        self.lowered_to = "hotkey"

    # everything not overridden IS the dense runtime's behavior —
    # intern_keys, engine, emit_stats, overflow_total, on_time,
    # next_wakeup, fire, on_start, step_invocations, ...
    def __getattr__(self, name):
        return getattr(self._dense, name)

    @property
    def on_purge_keys(self):
        return self._dense.on_purge_keys

    @on_purge_keys.setter
    def on_purge_keys(self, cb):
        self._dense.on_purge_keys = cb

    # -- metrics -------------------------------------------------------------

    def hot_metrics(self) -> Dict[str, float]:
        """Stats-feed gauges (util/statistics.py HotKeyTracker)."""
        s = self.hot_stats
        return {
            "hotkeyPromotions": s.promotions,
            "hotkeyDemotions": s.demotions,
            "hotkeyRoutedEvents": s.routed_events,
            "hotkeyActiveKeys": len(self._slots),
        }

    def stats(self) -> Dict:
        d = self._dense.stats()
        d["engine"] = "hotkey"
        d["hot_slots"] = self._scan.n_slots
        d["hot_keys"] = [rec["slot"] for rec in self._slots.values()]
        d.update(self.hot_metrics())
        return d

    # -- state handoff -------------------------------------------------------

    def _fetch_rows(self, arrays) -> Optional[List[np.ndarray]]:
        """Barrier-fetch small device slices through the sanctioned
        emit-queue path (FIFO with pending emissions, ``emit.drain``
        fault site + bounded retry).  Returns None when a fault dropped
        the drain — the caller aborts the handoff and the key keeps its
        current placement (graceful: only WHEN it routes changes)."""
        from siddhi_tpu.core.emit_queue import PendingEmit

        got: Dict[str, List[np.ndarray]] = {}

        def grab(host):
            got["host"] = list(host)

        self._dense.emit_queue.push(PendingEmit(list(arrays), grab))
        self._dense.drain()
        if "host" not in got:
            self.hot_stats.handoff_aborts += 1
            return None
        return got["host"]

    def _promote(self, key, row: int) -> bool:
        if not self._free_slots:
            return False
        dense, scan = self._dense, self._scan
        jnp = scan.jnp
        phys = int(dense._phys_rows(np.int64(row)))
        st = dense.state
        host = self._fetch_rows(
            [st["active"][phys], st["first_ts"][phys]])
        if host is None:
            return False
        dense_base = dense.engine.base_ts or 0
        if scan.base_ts is None:
            scan.base_ts = dense_base
        v_row, c_row = scan.dense_row_to_slot(
            host[0], host[1], dense_base, scan.base_ts)
        slot = self._free_slots.pop()
        self._state = {
            "v": self._state["v"].at[slot].set(jnp.asarray(v_row)),
            "c": self._state["c"].at[slot].set(jnp.asarray(c_row)),
        }
        # clear the dense row to its init template (the pending chains
        # moved); the row stays interned to the key — demotion writes
        # back into it.  `overflow` is a durable drop counter, keep it.
        init = dense.engine.init_state_host()
        new_state = dict(st)
        for k, arr in new_state.items():
            if k == "overflow":
                continue
            new_state[k] = arr.at[phys].set(jnp.asarray(init[k][0]))
        dense.state = new_state
        self._slots[key] = {"slot": slot, "row": row}
        self.hot_stats.promotions += 1
        log.info("hotkey router '%s': promoted key %r (share %.3f) to "
                 "scan slot %d", self.query_name, key,
                 self.sketch.share(key), slot)
        return True

    def _demote(self, key) -> bool:
        rec = self._slots.pop(key)
        slot, row = rec["slot"], rec["row"]
        dense, scan = self._dense, self._scan
        jnp = scan.jnp
        host = self._fetch_rows(
            [self._state["v"][slot], self._state["c"][slot]])
        if host is None:
            self._slots[key] = rec  # keep hot; retry next cycle
            return False
        active, first_ts, dropped = scan.slot_to_dense_row(
            host[0], host[1], scan.base_ts or 0,
            dense.engine.base_ts or 0, dense.engine.I)
        phys = int(dense._phys_rows(np.int64(row)))
        st = dict(dense.state)
        st["active"] = st["active"].at[phys].set(jnp.asarray(active))
        st["first_ts"] = st["first_ts"].at[phys].set(
            jnp.asarray(first_ts))
        if dropped:
            st["overflow"] = st["overflow"].at[phys].add(
                np.int32(dropped))
        dense.state = st
        v0, c0 = scan.slot_init_rows()
        self._state = {
            "v": self._state["v"].at[slot].set(jnp.asarray(v0)),
            "c": self._state["c"].at[slot].set(jnp.asarray(c0)),
        }
        self._free_slots.append(slot)
        self.hot_stats.demotions += 1
        log.info("hotkey router '%s': demoted key %r (share %.3f) back "
                 "to dense row %d", self.query_name, key,
                 self.sketch.share(key), row)
        return True

    def demote_all(self):
        for key in list(self._slots):
            self._demote(key)

    # -- routing decisions ---------------------------------------------------

    def _route_cycle(self, keys: np.ndarray, part: np.ndarray):
        """Update the sketch with this cycle's histogram and apply the
        promote/demote hysteresis.  Promotion needs the key's dense row,
        so only keys present in this cycle promote (hot keys are, by
        definition)."""
        try:
            uniq, counts = np.unique(keys, return_counts=True)
        except TypeError:  # mixed-type keys cannot histogram — stay dense
            return
        self.sketch.update(uniq, counts)
        for key in list(self._slots):
            if self.sketch.share(key) < self._demote_at:
                self._demote(key)
        if self._free_slots:
            hot_now = self.sketch.heavy(self._promote_at)
            if hot_now:
                in_cycle = {k: i for i, k in enumerate(uniq.tolist())}
                for key in hot_now:
                    if not self._free_slots:
                        break
                    if key in self._slots or key not in in_cycle:
                        continue
                    pos = np.flatnonzero(keys == key)
                    self._promote(key, int(part[pos[0]]))

    # -- event path ----------------------------------------------------------

    def process_stream_batch(self, stream_key: str, batch: EventBatch,
                             part: Optional[np.ndarray] = None,
                             keys=None):
        cur = batch.only(ev.CURRENT)
        n = len(cur)
        if n == 0:
            return
        if (part is None or keys is None
                or getattr(keys, "dtype", None) is None
                or len(part) != n):
            # no key side channel (or misaligned) — the whole batch
            # stays on the dense path, no routing this cycle
            self._dense.process_stream_batch(
                stream_key, cur, part=part, keys=keys)
            return
        self._route_cycle(keys, part)
        if not self._slots:
            self._dense.process_stream_batch(
                stream_key, cur, part=part, keys=keys)
            return
        hot_mask = np.zeros(n, dtype=bool)
        slot_pos: Dict[int, np.ndarray] = {}
        for key, rec in self._slots.items():
            pos = np.flatnonzero(keys == key)
            if len(pos):
                hot_mask[pos] = True
                slot_pos[rec["slot"]] = pos
        if not slot_pos:
            self._dense.process_stream_batch(
                stream_key, cur, part=part, keys=keys)
            return
        cold_mask = ~hot_mask
        if cold_mask.any():
            self._dense.process_stream_batch(
                stream_key, cur.mask(cold_mask),
                part=part[cold_mask], keys=keys[cold_mask])
        # hot keys stay "in use" for the idle-purge clock even though
        # their dense rows see no events while promoted
        np.maximum.at(self._dense._row_last_used, part[hot_mask],
                      cur.timestamps[hot_mask])
        self._process_hot(slot_pos, cur, keys)

    def _process_hot(self, slot_pos: Dict[int, np.ndarray],
                     cur: EventBatch, keys):
        from siddhi_tpu.core.emit_queue import PendingEmit
        from siddhi_tpu.core.ingest_stage import staged_put

        dense, scan = self._dense, self._scan
        cols = {a: c for a, c in cur.columns.items()
                if a in scan.base._lane_dtype}
        ts = cur.timestamps
        # hot-path batches get their own cycle tokens (engine kind
        # 'hotkey'); the cold remainder traced under 'dense' already
        tracer = dense.tracer
        tok = (tracer.begin_cycle("hotkey", len(ts))
               if tracer is not None else None)
        put, meta = scan.pack_cycle(slot_pos, cols, ts)
        put_dev = staged_put(put, faults=self.faults,
                             stats=dense.ingest_stats)
        self._state, emit_dev, n_rows = scan.dispatch(
            self._state, put_dev)
        self._poison_guard()
        n_routed = int(sum(len(p) for p in slot_pos.values()))
        self.hot_stats.routed_events += n_routed
        self.hot_stats.routed_cycles += 1
        dense.step_invocations += 1
        now = (self._app_context.timestamp_generator.current_time()
               if self._app_context is not None else None)
        out_cols = {attr: cur.columns[attr]
                    for _nm, attr in self._out_pairs()}
        keys_ref = keys

        def _finish(nr=n_rows, emit=emit_dev, m=meta, oc=out_cols,
                    t=ts, k=keys_ref, nw=now, tk=tok):
            c = int(nr)
            if tk is not None:
                # row-count gate resolved: the scan cycle finished
                tk.step_done(c)
            if c == 0:
                dense.emit_queue.skip()
                return
            dense.emit_queue.push(PendingEmit(
                [emit],
                lambda host: self._emit_hot(host, m, oc, t, k, nw),
                trace=tk))

        dense.ingest_stage.submit(n_rows, _finish, trace=tok)

    def _out_pairs(self):
        """(output name, final-node attribute) pairs — eligibility
        guarantees every dense out_spec source is ('cand', attr)."""
        return [(nm, src[1]) for nm, src in self._dense.engine.out_spec]

    def _emit_hot(self, host, meta, out_cols, ts, keys, now):
        emit_h = host[0]  # [H, n_pad] f32 per-event row counts
        parts = []
        for slot, pos in meta["slot_pos"].items():
            cnt = np.rint(emit_h[slot, :len(pos)]).astype(np.int64)
            if cnt.any():
                parts.append(np.repeat(pos, cnt))
        if not parts:
            return
        rep = np.sort(np.concatenate(parts))
        pairs = self._out_pairs()
        names = [nm for nm, _a in pairs]
        mb = EventBatch(
            self._dense.out_stream_id, names,
            {nm: out_cols[attr][rep] for nm, attr in pairs},
            ts[rep], np.full(len(rep), ev.CURRENT, dtype=np.int8),
        )
        mb.aux["partition_keys"] = keys[rep].tolist()
        mb.aux["event_indices"] = rep
        if now is not None:
            mb.aux["emit_now"] = now
        self._dense.emit_cb(mb)

    # -- poison quarantine (device_single._poison_guard idiom) ---------------

    def _poison_guard(self):
        fi = self.faults
        if fi is None or not fi.watches("state.poison"):
            return
        from siddhi_tpu.util import faults as _faults

        if fi.poisoned("state.poison"):
            self._state = _faults.poison_state(self._state)
        if _faults.state_has_poison(self._state):
            fi.stats.poison_quarantines += 1
            log.warning(
                "hotkey router '%s': NaN/Inf poison in scan state; "
                "restoring last good copy", self.query_name)
            if self._last_good is not None:
                jnp = self._scan.jnp
                self._state = {
                    k: jnp.asarray(v) for k, v in self._last_good.items()
                }
            else:
                self._state = self._scan.init_state()
        else:
            self._last_good = _faults.host_copy(self._state)

    # -- barriers / lifecycle ------------------------------------------------

    def drain(self):
        self._dense.drain()

    def purge_idle(self, now: int, idle_ms: int):
        """Hot rows' activity clocks advance every routed cycle, so a
        promoted key only looks idle when it IS idle — demote it first
        so its pending chains survive in the recycled-row protocol."""
        for key in list(self._slots):
            row = self._slots[key]["row"]
            if now - int(self._dense._row_last_used[row]) >= idle_ms:
                self._demote(key)
        self._dense.purge_idle(now, idle_ms)

    def snapshot(self) -> Dict:
        """Demote-all first: the persisted tree is a plain dense
        snapshot (restorable under different @app:hotkeys settings);
        the sketch rides along so routing warmth survives restore."""
        self.demote_all()
        tree = self._dense.snapshot()
        tree["hotkey_sketch"] = {
            "counts": dict(self.sketch.counts),
            "total": self.sketch.total,
        }
        return tree

    def restore(self, state: Dict):
        self._slots.clear()
        self._free_slots = list(range(self._scan.n_slots))[::-1]
        self._state = self._scan.init_state()
        self._scan.base_ts = None
        self._last_good = None
        sk = state.get("hotkey_sketch")
        self.sketch = SpaceSavingSketch(cap=self.sketch.cap,
                                        decay=self.sketch.decay)
        if sk:
            self.sketch.counts = dict(sk["counts"])
            self.sketch.total = float(sk["total"])
        self._dense.restore(
            {k: v for k, v in state.items() if k != "hotkey_sketch"})

    def close(self):
        self._dense.close()
