"""Product-side runtime of one fused chain (ops/fused_graph.py).

`FusedChainRuntime` is the chain's analog of DeviceQueryRuntime
(core/device_single.py): it converts the HEAD stream's junction batches
to device columns, advances the whole chain with ONE jitted fused step,
and emits the TAIL's output batches into the tail query's
selector/output chain.  Intermediate streams never build EventBatches
and never dispatch through their junctions — their event columns live
in HBM between stages.

It rides the same async machinery as the per-query runtimes — ingest
staging window (core/ingest_stage.py), bounded pending-emit queue
(core/emit_queue.py), fault choke-points (ingest.put / step.device /
step.dense / emit.drain), NaN/Inf poison quarantine — and the same
barriers: drain on snapshot/restore, rate-limiter fires, pull queries,
and shutdown, so callback content and order stay bit-identical to the
junction path.

Snapshot/restore: the planner attaches this runtime as the TAIL
query's ``device_runtime``, so QueryRuntime.snapshot_state persists the
whole chain's state (per-stage device arrays + host epochs) under the
tail query's name and crash replay (input journal) reproduces it.

This module is scanned by the `host-sync-hazard` analysis rule with no
allowlist entries: snapshots deep-copy through util.faults.host_copy,
restores re-materialize with jnp.asarray, and every column fetch goes
through the emit queue's coalesced drain.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional

import numpy as np

from siddhi_tpu.core import event as ev
from siddhi_tpu.core.emit_queue import EmitQueue, EmitStats, PendingEmit
from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.exceptions import SiddhiAppRuntimeError
from siddhi_tpu.core.ingest_stage import IngestStage, IngestStats

log = logging.getLogger("siddhi_tpu")


class FusedChainRuntime:
    """One fused chain: head-junction subscriber in, tail-query output
    chain out, everything between device-resident."""

    def __init__(self, graph, out_stream_id: str,
                 emit: Callable[[EventBatch], None], emit_depth=1,
                 clock: Optional[Callable[[], int]] = None, faults=None,
                 ingest_depth=1, tracer=None):  # int or 'auto'
        self.graph = graph
        self.out_stream_id = out_stream_id
        self.emit_cb = emit
        self.state = graph.init_state()
        # cycle-correlated span tracer (observability/trace.py); one
        # fused dispatch is one cycle, labeled with the 'fused' kind
        self.tracer = tracer
        self.engine_kind = "fused"
        self.step_invocations = 0  # fused program dispatches (tests)
        # hops kept device-resident: (stages - 1) junction dispatches
        # saved per fused dispatch (the bench's fusedHops counter)
        self.hops_per_dispatch = (
            len(graph.stages) + (1 if graph.dense is not None else 0) - 1)
        self.fused_hops = 0
        self.emit_stats = EmitStats()
        self.faults = faults
        graph.faults = faults
        self.emit_queue = EmitQueue(depth=emit_depth, stats=self.emit_stats,
                                    faults=faults, on_fault=self._on_fault)
        self.ingest_stats = IngestStats()
        graph.ingest_stats = self.ingest_stats
        self.ingest_stage = IngestStage(
            depth=ingest_depth, stats=self.ingest_stats, faults=faults,
            on_fault=self._on_fault)
        # last known-poison-free host copy of the chain state (only
        # while a state.poison fault is armed — quarantine source)
        self._last_good = None
        self.clock = clock

    def _on_fault(self, e: BaseException):
        # freeze the span ring: the post-mortem shows the cycles that
        # led into the isolated failure
        if self.tracer is not None:
            self.tracer.dump(f"onerror-isolation:{type(e).__name__}")
        if self.faults is not None:
            self.faults.notify(e)

    def _poison_guard(self) -> bool:
        """NaN/Inf quarantine over the WHOLE chain's state tuple, active
        only while a ``state.poison`` fault is armed (the
        DeviceQueryRuntime contract, applied chain-wide)."""
        fi = self.faults
        if fi is None or not fi.watches("state.poison"):
            return False
        from siddhi_tpu.util import faults as _faults

        if fi.poisoned("state.poison"):
            self.state = _faults.poison_state(self.state)
        if not _faults.state_has_poison(self.state):
            self._last_good = _faults.host_copy(self.state)
            return False
        fi.stats.poison_quarantines += 1
        jnp = self.graph.jnp
        if self._last_good is not None:
            log.error("fused chain state poisoned (NaN/Inf); quarantining "
                      "batch and re-materializing last clean state")
            self.state = tuple(
                {k: jnp.asarray(v) for k, v in st.items()}
                for st in self._last_good)
        else:
            log.error("fused chain state poisoned (NaN/Inf) with no clean "
                      "copy; quarantining batch and re-initializing")
            self.state = self.graph.init_state()
        return True

    # -- event path ----------------------------------------------------------

    def process_stream_batch(self, batch: EventBatch, keys=None):
        cur = batch.only(ev.CURRENT)
        n = len(cur)
        if n == 0:
            return
        # one sampled-or-None cycle token per junction batch: ingest
        # span starts here, at receive time
        tok = (self.tracer.begin_cycle(self.engine_kind, n)
               if self.tracer is not None else None)
        head = self.graph.stages[0]
        cols = {
            a: cur.columns[a]
            for a in head.all_attrs if a in cur.columns
        }
        ts = cur.timestamps
        self.state, pending = self.graph.process_batch_deferred(
            self.state, cols, ts)
        self.step_invocations += 1
        self.fused_hops += self.hops_per_dispatch
        if self._poison_guard():
            if tok is not None:
                tok.aborted("step")
            if self.tracer is not None:
                self.tracer.dump("poison-quarantine")
            return
        now = self.clock() if self.clock is not None else None

        def _finish(p=pending, t=now, tk=tok):
            c = 0 if p is None else p.resolve()
            if tk is not None:
                # count gate resolved: the fused step finished
                tk.step_done(c)
            if c == 0:
                self.emit_queue.skip()
                return
            self.emit_queue.push(PendingEmit(
                p.device_arrays(),
                lambda host, pp=p, tt=t: self._emit_deferred(pp, host, tt),
                trace=tk))

        self.ingest_stage.submit(
            pending.probe() if pending is not None else None, _finish,
            trace=tok)

    def drain(self):
        """Flush barrier (snapshot/restore, rate-limiter fires, pull
        queries, shutdown): staged batches enqueue first, then one
        coalesced drain emits everything in the synchronous order."""
        self.ingest_stage.flush()
        self.emit_queue.drain()

    def _emit_deferred(self, pending, host_arrays, now=None):
        out_cols, out_ts = pending.materialize(host_arrays)
        if len(out_ts) == 0:
            return
        mb = EventBatch(
            self.out_stream_id, self.graph.output_names, out_cols,
            out_ts, np.full(len(out_ts), ev.CURRENT, dtype=np.int8),
        )
        if now is not None:
            mb.aux["emit_now"] = now
        self.emit_cb(mb)

    def close(self):
        self.drain()

    # -- scheduler task contract (the fused kinds have no pane timers;
    # registration keeps the planner wiring uniform) -------------------------

    def next_wakeup(self) -> Optional[int]:
        return None

    def fire(self, now: int):
        self.drain()

    def on_start(self, now: int):
        pass

    def on_time(self, now: int):
        pass

    # -- stats ---------------------------------------------------------------

    def stats(self) -> Dict:
        return {
            "engine": "fused",
            "stages": len(self.graph.stages)
            + (1 if self.graph.dense is not None else 0),
            "step_invocations": self.step_invocations,
            "fused_hops": self.fused_hops,
        }

    # -- snapshot contract ---------------------------------------------------

    def snapshot(self) -> Dict:
        self.drain()
        from siddhi_tpu.util.faults import host_copy

        snap: Dict = {
            "chain": [host_copy(st) for st in self.state],
            "hosts": [eng.host_snapshot() for eng in self.graph.stages],
        }
        if self.graph.dense is not None:
            snap["dense_base_ts"] = self.graph.dense.base_ts
        return snap

    def restore(self, state: Dict):
        self.drain()
        self._last_good = None
        g = self.graph
        jnp = g.jnp
        chain = state["chain"]
        n_states = len(g.stages) + (1 if g.dense is not None else 0)
        if len(chain) != n_states:
            raise SiddhiAppRuntimeError(
                f"fused-chain snapshot has {len(chain)} stage states; "
                f"this chain has {n_states} — persist and restore must "
                "use the same app definition")
        restored: List = []
        for si, st in enumerate(chain):
            eng = g.stages[si] if si < len(g.stages) else g.dense
            expect = {k: v.shape for k, v in eng.init_state_host().items()}
            for k, v in st.items():
                if k in expect and v.shape != expect[k]:
                    raise SiddhiAppRuntimeError(
                        f"fused-chain snapshot stage {si} array '{k}' has "
                        f"shape {v.shape}; this chain expects {expect[k]}")
            restored.append({k: jnp.asarray(v) for k, v in st.items()})
        self.state = tuple(restored)
        for eng, h in zip(g.stages, state["hosts"]):
            eng.host_restore(h)
        if g.dense is not None:
            g.dense.base_ts = state.get("dense_base_ts")


class _FusedChainReceiver:
    """Head-junction subscriber feeding one fused chain."""

    def __init__(self, runtime: FusedChainRuntime):
        self.runtime = runtime

    def receive(self, batch: EventBatch):
        self.runtime.process_stream_batch(batch)
