"""Capability probe for the Pallas kernel layer.

``kernels_available()`` answers "can this process build Pallas kernels
at all" once per process: the ``jax.experimental.pallas`` import plus a
trivial kernel lowered end to end.  Per-engine eligibility and the
per-engine smoke lowering live in ``planner/kernels.py``; this module
only rules out environments where no kernel could ever build (no
Pallas in the jax install, broken lowering pipeline).

On anything that is not a TPU backend the kernels run under
``interpret=True`` — semantics-exact, speed-irrelevant — which is what
keeps the tier-1 differential tests meaningful on CPU.
"""

from __future__ import annotations

import logging
from typing import Optional, Tuple

log = logging.getLogger("siddhi_tpu")

_PROBE: Optional[Tuple[bool, str]] = None


def interpret_mode() -> bool:
    """True when kernels must run interpreted (any non-TPU backend)."""
    import jax

    return jax.default_backend() != "tpu"


def kernels_available() -> Tuple[bool, str]:
    """(ok, reason): can this process lower a Pallas kernel at all?

    Cached for the life of the process — the answer cannot change
    underneath us, and the trivial lowering is not free.
    """
    global _PROBE
    if _PROBE is not None:
        return _PROBE

    try:
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
    except Exception as e:  # pragma: no cover - depends on jax build
        log.warning("pallas kernels unavailable: import failed: %s", e)
        _PROBE = (False, f"pallas import failed: {e}")
        return _PROBE

    try:

        def _k(x_ref, o_ref):
            o_ref[...] = x_ref[...] + 1

        fn = pl.pallas_call(
            _k,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.int32),
            interpret=interpret_mode(),
        )
        x = jax.ShapeDtypeStruct((8, 128), jnp.int32)
        jax.jit(fn).lower(x)
    except Exception as e:  # pragma: no cover - depends on backend
        log.warning("pallas kernels unavailable: probe lowering failed: %s", e)
        _PROBE = (False, f"pallas probe lowering failed: {e}")
        return _PROBE

    _PROBE = (True, "")
    return _PROBE
