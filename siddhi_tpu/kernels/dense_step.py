"""Bit-packed Pallas step for the simple every-chain dense NFA class.

The eligible class (gated in ``planner/kernels.py``) is the capture-free
every-start chain: all nodes are plain stream states (``min==max==1``),
no sequences, no group-every, no absent deadlines, no register slots, no
mesh.  Inside that class the XLA step's carry shrinks to two arrays —
node activity and the within anchor — and node activity packs 32 batch
rows per int32 word: bit ``b`` of word ``w`` is batch row ``w*32 + b``
(the collision rounds upstream guarantee each partition appears once
per dispatch, so a batch row IS a partition for the step's purposes).
``counts``/``regs`` are provably constant in this class and pass
through the state dict untouched, so snapshot/restore, sharding, and
the multiplex seat tiling keep seeing the existing layout.

The kernel mirrors the XLA step operation for operation — within
expiry, the reversed node sweep, the rank-matched placement
(``_rank_place``) and the overflow count — on packed planes, so
detections, anchors, and overflow counters are bit-identical (pure
boolean/int32 arithmetic; there is no float in the whole step).
Candidate filters are lane-uniform in this class and are evaluated on
the XLA side into one packed eligibility word row per node; output
columns are pure per-event selects and are assembled outside the
kernel from the emit mask, exactly as ``_emit_rows`` writes them.
"""

from __future__ import annotations

from typing import Dict, Tuple

from siddhi_tpu.planner.expr import N_KEY, TS_KEY
from siddhi_tpu.query_api import AttrType

_INT_TYPES = (AttrType.INT, AttrType.LONG)

# single-block ceiling: batches up to this size run as one grid point;
# larger batches tile in 1024-row blocks (32 words) along the grid
MAX_SINGLE_BLOCK = 1024


def _batch_blocks(B: int) -> Tuple[int, int, int]:
    """(padded batch, total words, words per block) for a batch of B."""
    Bp = ((B + 31) // 32) * 32
    if Bp <= MAX_SINGLE_BLOCK:
        return Bp, Bp // 32, Bp // 32
    Bp = ((Bp + MAX_SINGLE_BLOCK - 1) // MAX_SINGLE_BLOCK) * MAX_SINGLE_BLOCK
    return Bp, Bp // 32, MAX_SINGLE_BLOCK // 32


def build_packed_nfa(engine, stream_key: str, jit: bool = True):
    """Kernel-backed replacement for ``DensePatternEngine.make_step``.

    Same signature and same returns as the XLA step; only callable for
    engines that passed ``check_dense_kernel_eligible``.
    """
    jax, jnp = engine.jax, engine.jnp
    from jax.experimental import pallas as pl

    from siddhi_tpu.kernels import probe
    from siddhi_tpu.kernels.plane_pack import pack_bits, unpack_bits

    S, I = engine.S, engine.I
    nodes = engine.nodes
    node_filters = engine.node_filters
    within = engine.within_ms
    out_spec = engine.out_spec
    out_int = engine.out_int
    O = max(len(out_spec), 1)
    n_iout = sum(out_int)
    scratch_row = engine.n_partitions
    interpret = probe.interpret_mode()
    on_stream = [n.specs[0].stream_key == stream_key for n in nodes]
    int_out_idx: Dict[int, int] = {}
    for _oi, _isint in enumerate(out_int):
        if _isint:
            int_out_idx[_oi] = len(int_out_idx)

    _calls: Dict[Tuple[int, int], object] = {}

    def _pallas_call(W: int, WB: int):
        call = _calls.get((W, WB))
        if call is not None:
            return call
        BB = WB * 32
        i32 = jnp.int32

        def kernel(ok_ref, a_ref, first_ref, ts_ref,
                   a_out, first_out, emit_out, anch_out, ovf_out):
            ok = ok_ref[...]          # [S, WB] packed (valid pre-ANDed)
            A = a_ref[...]            # [S*I, WB] packed activity
            FT = first_ref[...]       # [S*I, BB] anchors
            ts = ts_ref[...]          # [1, BB]
            a = {s: A[s * I:(s + 1) * I, :] for s in range(S)}
            first = {s: FT[s * I:(s + 1) * I, :] for s in range(S)}

            if within is not None:
                for s in range(S):
                    fs = first[s]
                    expired = (fs > 0) & ((ts - fs) > within)
                    a[s] = a[s] & ~pack_bits(jax, jnp, expired)
                    first[s] = jnp.where(expired, 0, fs)

            # the standing virgin: instance lane 0 of node 0, every row
            row_i = jax.lax.broadcasted_iota(i32, (I, WB), 0)
            lane0_pk = jnp.where(row_i == 0, i32(-1), i32(0))

            emit_pk = jnp.zeros((I, WB), i32)
            anch = jnp.zeros((I, BB), i32)
            ovf = jnp.zeros((1, BB), i32)
            for s in reversed(range(S)):
                if not on_stream[s]:
                    continue
                pend = a[s] | lane0_pk if s == 0 else a[s]
                fire_pk = pend & ok[s:s + 1, :]
                fire = unpack_bits(jax, jnp, fire_pk)  # [I, BB]
                if s == 0:
                    # fresh arming each event: anchor is THIS event
                    first[0] = jnp.where(fire, ts, first[0])
                else:
                    first[s] = jnp.where(fire & (first[s] == 0), ts,
                                         first[s])
                    a[s] = a[s] & ~fire_pk
                anchor = jnp.where(first[s] > 0, first[s], ts)  # [I, BB]
                if s == S - 1:
                    emit_pk = emit_pk | fire_pk
                    anch = jnp.where(fire, anchor, anch)
                    continue
                # rank-matched placement into node s+1 (_rank_place with
                # counts == 0: free lanes are just the inactive ones)
                free = unpack_bits(jax, jnp, ~a[s + 1])  # [I, BB]
                fire_i = fire.astype(i32)
                free_i = free.astype(i32)
                src_rank = jnp.cumsum(fire_i, axis=0) - 1
                free_rank = jnp.cumsum(free_i, axis=0) - 1
                n_free = jnp.sum(free_i, axis=0, keepdims=True)  # [1, BB]
                placed = fire & (src_rank < n_free)
                ovf = ovf + jnp.sum((fire & ~placed).astype(i32), axis=0,
                                    keepdims=True)
                assign = (placed[:, None, :] & free[None, :, :]
                          & (src_rank[:, None, :] == free_rank[None, :, :]))
                got = jnp.any(assign, axis=0)  # [I, BB] target lanes
                moved = jnp.sum(jnp.where(assign, anchor[:, None, :], 0),
                                axis=0)
                a[s + 1] = a[s + 1] | pack_bits(jax, jnp, got)
                first[s + 1] = jnp.where(got, moved, first[s + 1])

            a_out[...] = jnp.concatenate([a[s] for s in range(S)], axis=0)
            first_out[...] = jnp.concatenate(
                [first[s] for s in range(S)], axis=0)
            emit_out[...] = emit_pk
            anch_out[...] = anch
            ovf_out[...] = ovf

        Bp = W * 32
        call = pl.pallas_call(
            kernel,
            grid=(W // WB,),
            in_specs=[
                pl.BlockSpec((S, WB), lambda i: (0, i)),
                pl.BlockSpec((S * I, WB), lambda i: (0, i)),
                pl.BlockSpec((S * I, BB), lambda i: (0, i)),
                pl.BlockSpec((1, BB), lambda i: (0, i)),
            ],
            out_specs=[
                pl.BlockSpec((S * I, WB), lambda i: (0, i)),
                pl.BlockSpec((S * I, BB), lambda i: (0, i)),
                pl.BlockSpec((I, WB), lambda i: (0, i)),
                pl.BlockSpec((I, BB), lambda i: (0, i)),
                pl.BlockSpec((1, BB), lambda i: (0, i)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((S * I, W), jnp.int32),
                jax.ShapeDtypeStruct((S * I, Bp), jnp.int32),
                jax.ShapeDtypeStruct((I, W), jnp.int32),
                jax.ShapeDtypeStruct((I, Bp), jnp.int32),
                jax.ShapeDtypeStruct((1, Bp), jnp.int32),
            ],
            interpret=interpret,
        )
        _calls[(W, WB)] = call
        return call

    def env_for(s, cols, ts):
        env = {}
        spec = nodes[s].specs[0]
        for attr in spec.stream_def.attributes:
            if attr.type in _INT_TYPES:
                hk, lk = f"{attr.name}|hi", f"{attr.name}|lo"
                if hk in cols:
                    env[f"__cand.{attr.name}|hi"] = cols[hk][:, None]
                    env[f"__cand.{attr.name}|lo"] = cols[lk][:, None]
            elif attr.name in cols:
                env["__cand." + attr.name] = cols[attr.name][:, None]
        env[TS_KEY] = ts[:, None]
        env[N_KEY] = ts.shape[0]
        return env

    def step(state, part_idx, cols, ts, valid):
        B = part_idx.shape[0]
        Bp, W, WB = _batch_blocks(B)
        pad = Bp - B

        # lane-uniform candidate filters, evaluated XLA-side: one packed
        # eligibility row per node, pre-ANDed with the valid mask
        ok_rows = []
        for s in range(S):
            if not on_stream[s]:
                ok_rows.append(jnp.zeros((B,), dtype=bool))
                continue
            f = node_filters[s][0]
            if f is None:
                ok_rows.append(valid)
            else:
                okb = jnp.broadcast_to(
                    jnp.asarray(f.fn(env_for(s, cols, ts))).astype(bool),
                    (B, 1))[:, 0]
                ok_rows.append(okb & valid)
        ok_mat = jnp.stack(ok_rows, axis=0)  # [S, B]

        a = state["active"][part_idx]        # [B, S, I]
        first = state["first_ts"][part_idx]  # [B, S, I]
        if pad:
            a = jnp.pad(a, ((0, pad), (0, 0), (0, 0)))
            first = jnp.pad(first, ((0, pad), (0, 0), (0, 0)))
            ok_mat = jnp.pad(ok_mat, ((0, 0), (0, pad)))
            ts_p = jnp.pad(ts, (0, pad))
        else:
            ts_p = ts

        a_pk = pack_bits(jax, jnp,
                         a.transpose(1, 2, 0).reshape(S * I, Bp))
        first_t = first.transpose(1, 2, 0).reshape(S * I, Bp)
        ok_pk = pack_bits(jax, jnp, ok_mat)

        a_o, first_o, emit_o, anch_o, ovf_o = _pallas_call(W, WB)(
            ok_pk, a_pk, first_t, ts_p.reshape(1, Bp))

        a_new = unpack_bits(jax, jnp, a_o).reshape(S, I, Bp)
        a_new = a_new.transpose(2, 0, 1)[:B]
        first_new = first_o.reshape(S, I, Bp).transpose(2, 0, 1)[:B]
        emit_b0 = unpack_bits(jax, jnp, emit_o).transpose(1, 0)[:B]  # [B, I]
        anch_b0 = anch_o.transpose(1, 0)[:B]
        ovf_delta = ovf_o[0, :B]

        emit = jnp.concatenate(
            [emit_b0, jnp.zeros((B, I), dtype=bool)], axis=1)
        emit_anchor = jnp.concatenate(
            [anch_b0, jnp.zeros((B, I), dtype=jnp.int32)], axis=1)

        # output columns: pure candidate selects, assembled from the
        # emit mask exactly as the XLA _emit_rows writes them (bank 0
        # only — the eligible class has no via-path)
        out_vals = jnp.zeros((B, 2 * I, O), dtype=jnp.float32)
        out_ivals = jnp.zeros((B, 2 * I, 2 * n_iout), dtype=jnp.int32)
        sl = slice(0, I)
        for oi, (_name, src) in enumerate(out_spec):
            ii = int_out_idx.get(oi)
            if ii is not None:
                hk, lk = f"{src[1]}|hi", f"{src[1]}|lo"
                if hk not in cols:
                    continue
                out_ivals = out_ivals.at[:, sl, 2 * ii].set(
                    jnp.where(emit_b0, cols[hk][:, None],
                              out_ivals[:, sl, 2 * ii]))
                out_ivals = out_ivals.at[:, sl, 2 * ii + 1].set(
                    jnp.where(emit_b0, cols[lk][:, None],
                              out_ivals[:, sl, 2 * ii + 1]))
                continue
            val = cols.get(src[1])
            if val is None:
                continue
            out_vals = out_vals.at[:, sl, oi].set(
                jnp.where(emit_b0, val.astype(jnp.float32)[:, None],
                          out_vals[:, sl, oi]))

        new_ovf = state["overflow"][part_idx] + ovf_delta

        v1 = valid[:, None, None]
        new_state = {
            "active": state["active"].at[part_idx].set(
                jnp.where(v1, a_new, state["active"][part_idx])),
            "first_ts": state["first_ts"].at[part_idx].set(
                jnp.where(v1, first_new, state["first_ts"][part_idx])),
            # constant in the eligible class: pass through value-identical
            # (a same-value scatter keeps donation layouts unchanged)
            "counts": state["counts"].at[part_idx].set(
                state["counts"][part_idx]),
            "regs": state["regs"].at[part_idx].set(
                state["regs"][part_idx]),
            "overflow": state["overflow"].at[part_idx].set(
                jnp.where(valid, new_ovf, state["overflow"][part_idx])),
        }
        n_emit = jnp.sum((emit & valid[:, None]).astype(jnp.int32))
        return (new_state, emit, {"f": out_vals, "i": out_ivals},
                emit_anchor, n_emit)

    return jax.jit(step, donate_argnums=(0,)) if jit else step


def smoke_lower(engine):
    """Lower the kernel step for every source stream at a tiny batch;
    raise on any failure (Mosaic rejection, shape bug, ...).

    Goes through ``engine.make_step`` (the engine's ``use_kernel`` flag
    must already be set) so the traced function lands in the engine's
    step cache and is reused at runtime.
    """
    import numpy as np

    jax = engine.jax
    host = engine.init_state_host()
    state_shapes = {
        k: jax.ShapeDtypeStruct(v.shape, v.dtype) for k, v in host.items()
    }
    B = 32
    i32 = jax.ShapeDtypeStruct((B,), np.int32)
    b1 = jax.ShapeDtypeStruct((B,), np.bool_)
    for sk in engine.stream_keys:
        cols = {
            k: jax.ShapeDtypeStruct(
                (B,), np.int32 if "|" in k else np.float32)
            for k in engine.device_col_keys(sk)
        }
        step = engine.make_step(sk)
        step.lower(state_shapes, i32, cols, i32, b1)
