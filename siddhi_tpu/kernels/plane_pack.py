"""Bit-plane layout for the packed dense-NFA step.

One int32 word carries the boolean node activity of 32 batch rows:
row ``w*32 + b`` lives at bit ``b`` of word ``w``.  The ``[S, I]``
plane shape of the engine state is untouched — only the batch /
partition axis packs — so snapshot/restore, mesh sharding, and the
multiplex seat tiling keep seeing the existing dict layout, and the
host converters here round-trip ``DensePatternEngine`` state exactly.

Two flavours live side by side:

- ``pack_active_host``/``unpack_active_host`` — numpy, axis 0 packs
  (``[P, S, I] bool`` ↔ ``[ceil(P/32), S, I] int32``); used for
  snapshot compaction and the packed round-trip tests.
- ``pack_bits``/``unpack_bits`` — traced jax, last axis packs; used on
  both sides of the ``dense_step`` kernel boundary (they only use
  ``broadcasted_iota`` so they lower inside Mosaic too).

Both flavours use the same bit order, so a word is a word regardless
of which axis it was packed along.
"""

from __future__ import annotations

import numpy as np

PLANE_BITS = 32


def packed_words(n_rows: int) -> int:
    """Words needed to hold ``n_rows`` packed rows."""
    return (n_rows + PLANE_BITS - 1) // PLANE_BITS


def pack_active_host(active: np.ndarray) -> np.ndarray:
    """``[P, S, I] bool`` → ``[ceil(P/32), S, I] int32`` bit planes."""
    P, S, I = active.shape
    W = packed_words(P)
    padded = np.zeros((W * PLANE_BITS, S, I), dtype=np.uint32)
    padded[:P] = active.astype(np.uint32)
    planes = np.zeros((W, S, I), dtype=np.uint32)
    for b in range(PLANE_BITS):
        planes |= padded[b::PLANE_BITS] << np.uint32(b)
    return planes.view(np.int32)


def unpack_active_host(planes: np.ndarray, n_rows: int) -> np.ndarray:
    """``[W, S, I] int32`` bit planes → ``[n_rows, S, I] bool``."""
    planes = np.ascontiguousarray(planes, dtype=np.int32)
    W, S, I = planes.shape
    u = planes.view(np.uint32)
    out = np.zeros((W * PLANE_BITS, S, I), dtype=bool)
    for b in range(PLANE_BITS):
        out[b::PLANE_BITS] = ((u >> np.uint32(b)) & np.uint32(1)).astype(bool)
    return out[:n_rows]


def pack_state(state: dict) -> dict:
    """Engine state dict (host numpy) → packed snapshot dict.

    ``active`` is replaced by its bit planes plus the original row
    count; every other array passes through untouched.
    """
    out = {k: v for k, v in state.items() if k != "active"}
    out["active_planes"] = pack_active_host(state["active"])
    out["active_rows"] = int(state["active"].shape[0])
    return out


def unpack_state(packed: dict) -> dict:
    """Inverse of ``pack_state`` — restores the engine dict layout."""
    out = {
        k: v
        for k, v in packed.items()
        if k not in ("active_planes", "active_rows")
    }
    out["active"] = unpack_active_host(
        packed["active_planes"], packed["active_rows"]
    )
    return out


def pack_bits(jax, jnp, bits):
    """Traced: ``[..., 32*W] bool`` → ``[..., W] int32`` (last axis)."""
    shape = bits.shape
    W = shape[-1] // PLANE_BITS
    b = bits.reshape(shape[:-1] + (W, PLANE_BITS)).astype(jnp.uint32)
    shifts = jax.lax.broadcasted_iota(jnp.uint32, b.shape, b.ndim - 1)
    return jnp.sum(b << shifts, axis=-1).astype(jnp.int32)


def unpack_bits(jax, jnp, words):
    """Traced: ``[..., W] int32`` → ``[..., 32*W] bool`` (last axis)."""
    u = words.astype(jnp.uint32)[..., None]
    shifts = jax.lax.broadcasted_iota(
        jnp.uint32, u.shape[:-1] + (PLANE_BITS,), u.ndim - 1
    )
    bits = (u >> shifts) & jnp.uint32(1)
    flat = words.shape[:-1] + (words.shape[-1] * PLANE_BITS,)
    return bits.reshape(flat).astype(bool)
