"""One fused kernel for the hotkey scan's max-plus + counting chains.

The XLA path materializes per-event transition matrices ``M [H,n,S,S]``
(max-plus) and ``T [H,n,S,S]`` (counting) and runs two passes of
``jax.lax.associative_scan`` over the event axis.  This kernel walks
the events of each hot-key slot once, carrying the ``[1, S]`` value and
count vectors directly — no matrices, no second pass — with the filter
matrix streamed in slot-major so each event's row is one static-shape
dynamic-slice load.

Bit-identity contract vs the XLA path (pinned by the differential
tests):

- emissions (which events fire, and their counts) are bit-identical:
  counts are exact integer-valued f32 (< 2^24 by the engine's own
  bound) and liveness is a discrete fact both paths agree on;
- live lane values are bit-identical: a live chain's value is the
  armed timestamp plus exactly-representable ``+ 0.0`` hops in both
  formulations, and ``NEG + x == NEG`` exactly for every in-range
  timestamp (f32 absorption at 1e30);
- dead lanes (``<= NEG/2``) may differ bitwise between the tree and
  sequential evaluations — they are unobservable by the engine's own
  contract (every read is thresholded at ``NEG/2``), and the explicit
  ``NEG`` floor below keeps them inside the same dead band the XLA
  ``max`` (which always includes ``NEG + v[0] == NEG``) guarantees.
"""

from __future__ import annotations

from typing import Dict, Tuple

_cache: Dict[Tuple, object] = {}


def _build(H, n, S, neg, interpret):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    f32 = jnp.float32
    # python float, not np.float32: a strongly-typed scalar closed over
    # by the fori_loop body becomes a jaxpr *const* (Pallas rejects
    # captured constants); a weak python float stays a literal and
    # promotes to f32 against the f32 carries
    NEG = float(neg)

    def kernel(F_ref, ts_ref, v_ref, c_ref, vout_ref, cout_ref, emit_ref):
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, S), 1)
        lane0 = lane == 0
        lane1 = lane == 1

        def body(e, carry):
            v, c = carry  # [1, S] each
            frow = pl.load(F_ref, (pl.dslice(e, 1), slice(None)))  # [1, S+1]
            tse = pl.load(ts_ref, (slice(None), pl.dslice(e, 1)))  # [1, 1]
            f = frow > 0.5
            fi = f[:, 0:S]  # lane i: filter F_i   (lane 0 unused)
            fip1 = f[:, 1 : S + 1]  # lane i: filter F_{i+1}

            # emission is decided on the PRE-update vectors, exactly as
            # the XLA path reads before_v/before_c
            live_last = v[:, S - 1 : S] > NEG / 2
            em = jnp.where(
                f[:, S : S + 1] & live_last, c[:, S - 1 : S], 0.0
            )
            pl.store(emit_ref, (slice(0, 1), pl.dslice(e, 1)), em)

            zero1 = jnp.zeros((1, 1), f32)
            one1 = jnp.ones((1, 1), f32)
            v_sh = jnp.concatenate([zero1, v[:, : S - 1]], axis=1)
            c_sh = jnp.concatenate([one1, c[:, : S - 1]], axis=1)

            # lane i advance-in term: F_i ? (i==1 ? ts : v[i-1]) : NEG+v[i-1]
            t1_true = jnp.where(lane1, jnp.broadcast_to(tse, (1, S)), v_sh)
            term1 = jnp.where(fi, t1_true, NEG + v_sh)
            # lane i keep term: F_{i+1} ? NEG+v[i] : v[i]
            term2 = jnp.where(fip1, NEG + v, v)
            nv = jnp.maximum(jnp.maximum(term1, term2), NEG)
            nv = jnp.where(lane0, 0.0, nv)

            nc = jnp.where(fi, c_sh, 0.0) + jnp.where(fip1, 0.0, c)
            nc = jnp.where(lane0, 1.0, nc)
            return nv, nc

        v0 = v_ref[...]
        c0 = c_ref[...]
        v_fin, c_fin = jax.lax.fori_loop(0, n, body, (v0, c0))
        vout_ref[...] = v_fin
        cout_ref[...] = c_fin

    return pl.pallas_call(
        kernel,
        grid=(H,),
        in_specs=[
            pl.BlockSpec((n, S + 1), lambda h: (h, 0)),
            pl.BlockSpec((1, n), lambda h: (h, 0)),
            pl.BlockSpec((1, S), lambda h: (h, 0)),
            pl.BlockSpec((1, S), lambda h: (h, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, S), lambda h: (h, 0)),
            pl.BlockSpec((1, S), lambda h: (h, 0)),
            pl.BlockSpec((1, n), lambda h: (h, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((H, S), f32),
            jax.ShapeDtypeStruct((H, S), f32),
            jax.ShapeDtypeStruct((H, n), f32),
        ],
        interpret=interpret,
    )


def fused_scan(jax, jnp, F, ts_rel, v, c, neg):
    """Run the fused chain: ``F [H,n,S+1] f32``, ``ts_rel/v/c`` as the
    XLA path holds them → ``(v' [H,S], c' [H,S], emit [H,n])``."""
    from siddhi_tpu.kernels import probe

    H, n, Sp1 = F.shape
    S = Sp1 - 1
    key = (int(H), int(n), int(S), float(neg), probe.interpret_mode())
    call = _cache.get(key)
    if call is None:
        call = _build(*key)
        _cache[key] = call
    Ff = F.reshape(H * n, Sp1)
    return call(Ff, ts_rel, v, c)


def smoke_lower(S, H, neg):
    """Lower one tiny fused scan end to end; raise on failure."""
    import jax
    import numpy as np

    from siddhi_tpu.kernels import probe

    n = 16
    call = _build(int(H), n, int(S), float(neg), probe.interpret_mode())
    f32 = np.float32
    jax.jit(call).lower(
        jax.ShapeDtypeStruct((H * n, S + 1), f32),
        jax.ShapeDtypeStruct((H, n), f32),
        jax.ShapeDtypeStruct((H, S), f32),
        jax.ShapeDtypeStruct((H, S), f32),
    )
