"""Collision-free segmented reduce for the aggregation device bank.

The bank's XLA path scatters every event at its assigned row with
``.at[rows].add/min/max`` — on TPU, colliding indices (hot keys) are
applied as serialized collision rounds inside the scatter.  This
kernel computes the same per-row reduction as a dense one-hot
compare-and-reduce over an (events × rows) tile grid: every event
block contributes to every row block exactly once, so a million events
on one key cost the same as a million events on a million keys.

Contract vs the XLA scatter: int32 lanes and min/max lanes are
bit-identical (order-free).  f32 *sums* may associate differently than
the scatter's collision rounds; the bank only routes integer-valued
f32 lanes through exactness-sensitive tests, and ``COUNT_EXACT_MAX``
already bounds exact counting, so the documented contract is
unchanged.

Grid layout: ``(row_blocks, event_blocks)`` with the row axis
outermost, so each ``[1, RB]`` output block is initialized once (at
``eb == 0``) and then revisited by every event block in sequence.
Events ride the sublane axis as ``[n, 1]`` columns; the one-hot
compare broadcasts them against the row ids on the lane axis.
"""

from __future__ import annotations

from typing import Dict, Tuple

EVENT_BLOCK = 512
ROW_BLOCK = 256

_cache: Dict[Tuple, object] = {}


def pad_rows(r: int) -> int:
    """Round a row count up to a whole number of row blocks."""
    return max(ROW_BLOCK, ((r + ROW_BLOCK - 1) // ROW_BLOCK) * ROW_BLOCK)


def _build(n_pad, r_pad, dtype_name, op, identity, interpret):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    dtype = jnp.dtype(dtype_name)
    EB = min(n_pad, EVENT_BLOCK)
    RB = min(r_pad, ROW_BLOCK)
    grid = (r_pad // RB, n_pad // EB)

    def kernel(rows_ref, vals_ref, out_ref):
        rb = pl.program_id(0)
        eb = pl.program_id(1)

        @pl.when(eb == 0)
        def _init():
            out_ref[...] = jnp.full((1, RB), identity, dtype)

        r = rows_ref[...]  # [EB, 1] int32
        v = vals_ref[...]  # [EB, 1]
        row_ids = rb * RB + jax.lax.broadcasted_iota(jnp.int32, (EB, RB), 1)
        onehot = r == row_ids  # [EB, RB] via lane broadcast
        contrib = jnp.where(onehot, v, jnp.asarray(identity, dtype))
        if op in ("sum", "count"):
            out_ref[...] = out_ref[...] + jnp.sum(
                contrib, axis=0, keepdims=True
            )
        elif op == "min":
            out_ref[...] = jnp.minimum(
                out_ref[...], jnp.min(contrib, axis=0, keepdims=True)
            )
        else:
            out_ref[...] = jnp.maximum(
                out_ref[...], jnp.max(contrib, axis=0, keepdims=True)
            )

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((EB, 1), lambda rb, eb: (eb, 0)),
            pl.BlockSpec((EB, 1), lambda rb, eb: (eb, 0)),
        ],
        out_specs=pl.BlockSpec((1, RB), lambda rb, eb: (0, rb)),
        out_shape=jax.ShapeDtypeStruct((1, r_pad), dtype),
        interpret=interpret,
    )


def segmented_reduce(rows, vals, r_pad, op, identity, interpret):
    """Per-row reduction delta: (``rows [n]``, ``vals [n]``) → ``[r_pad]``.

    ``rows`` must already be padded to a whole number of event blocks
    with entries pointing at a dump row < ``r_pad`` and ``vals`` padded
    with ``identity``.  The result is the reduction of each row's
    events against ``identity`` — the caller combines it with the live
    accumulator (``+`` for sums, ``min``/``max`` for extrema).
    """
    key = (int(rows.shape[0]), int(r_pad), str(vals.dtype), op, interpret)
    call = _cache.get(key)
    if call is None:
        call = _build(*key[:2], key[2], op, identity, interpret)
        _cache[key] = call
    out = call(rows.reshape(-1, 1), vals.reshape(-1, 1))
    return out[0]


def smoke_lower():
    """Lower one tiny segmented reduce end to end; raise on failure."""
    import jax
    import numpy as np

    from siddhi_tpu.kernels import probe

    call = _build(256, 256, "int32", "sum", 0, probe.interpret_mode())
    rows = jax.ShapeDtypeStruct((256, 1), np.int32)
    vals = jax.ShapeDtypeStruct((256, 1), np.int32)
    jax.jit(call).lower(rows, vals)
