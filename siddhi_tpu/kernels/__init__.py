"""Hand-written Pallas kernels for the hot step (``@app:kernels``).

Three kernels replace the XLA-compiled hot loops, each pinned
bit-identical to the path it replaces and gated behind the planner the
same way the shard/multiplex/fuse/hotkey paths are:

- ``dense_step``  — bit-packed dense-NFA step: 32 batch rows' boolean
  node activity per int32 lane (``plane_pack`` holds the layout and
  the host converters that round-trip ``DensePatternEngine`` state).
- ``bank_scatter`` — collision-free segmented reduce for the
  aggregation device bank, replacing the serializing scatter-add.
- ``scan_chain``  — one fused kernel for the hotkey scan's max-plus
  matrix chain + counting chain, replacing the two-pass
  ``associative_scan``.

Kernels compile via ``jax.experimental.pallas`` on TPU and run under
``interpret=True`` everywhere else; ``probe.kernels_available()`` is
the capability gate and every unavailable/ineligible engine falls back
to the XLA path with a counted ``kernelFallbackReason``.
"""
