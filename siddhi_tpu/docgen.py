"""Extension documentation generator.

Re-design of the reference ``modules/siddhi-doc-gen`` (Maven mojo +
Freemarker templates rendering @Extension metadata to markdown/mkdocs):
here extension metadata is the registered class itself — kind, name,
namespace, constructor signature, and docstring — rendered to markdown.

CLI: ``python -m siddhi_tpu.docgen [output.md]``
"""

from __future__ import annotations

import inspect
import sys
from typing import Optional

from siddhi_tpu.extension.registry import KINDS, default_registry


_KIND_TITLES = {
    "window": "Windows (`#window.name(...)`)",
    "function": "Scalar functions",
    "aggregator": "Attribute aggregators",
    "stream_processor": "Stream processors",
    "stream_function": "Stream functions",
    "source": "Sources (`@source(type='...')`)",
    "sink": "Sinks (`@sink(type='...')`)",
    "source_mapper": "Source mappers (`@map(type='...')`)",
    "sink_mapper": "Sink mappers (`@map(type='...')`)",
    "table": "Tables",
    "store": "Stores (`@store(type='...')`)",
    "script": "Script languages (`define function f[lang]`)",
}


def _doc_of(factory) -> str:
    doc = inspect.getdoc(factory) or "(undocumented)"
    return doc.strip()


def generate_markdown(registry=None, title: str = "siddhi_tpu extensions") -> str:
    """Markdown API reference for every registered extension."""
    reg = registry if registry is not None else default_registry()
    lines = [f"# {title}", ""]
    lines.append(
        "Auto-generated from extension registrations (the reference "
        "generates the analogous pages from `@Extension` annotations via "
        "siddhi-doc-gen)."
    )
    lines.append("")
    for kind in KINDS:
        items = reg.items(kind)
        if not items:
            continue
        lines.append(f"## {_KIND_TITLES.get(kind, kind)}")
        lines.append("")
        for full_name, factory in sorted(items):
            lines.append(f"### `{full_name}`")
            lines.append("")
            lines.append(_doc_of(factory))
            lines.append("")
    return "\n".join(lines)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    md = generate_markdown()
    if argv:
        with open(argv[0], "w") as f:
            f.write(md)
    else:
        print(md)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
