"""Debugger tests (reference: debugger/SiddhiDebuggerTestCase.java —
breakpoints at query IN/OUT, next/play stepping, state inspection)."""

import threading

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.debugger import SiddhiDebugger


@pytest.fixture
def manager():
    m = SiddhiManager()
    yield m
    m.shutdown()


APP = (
    "define stream S (symbol string, v long); "
    "@info(name='q1') from S[v > 10] select symbol, v insert into Out; "
    "@info(name='q2') from Out select symbol insert into Out2;"
)


def test_breakpoint_at_in_and_out(manager):
    rt = manager.create_siddhi_app_runtime(APP)
    hits = []

    def on_debug(events, query, terminal, debugger):
        hits.append((query, terminal, [e.data for e in events]))
        debugger.play()  # resume from inside the callback

    dbg = rt.debug()
    dbg.set_debugger_callback(on_debug)
    dbg.acquire_break_point("q1", SiddhiDebugger.QueryTerminal.IN)
    dbg.acquire_break_point("q1", SiddhiDebugger.QueryTerminal.OUT)
    got = []
    rt.add_callback("Out", lambda evs: got.extend(evs))
    rt.get_input_handler("S").send(["IBM", 50])
    rt.shutdown()
    assert hits == [
        ("q1", "IN", [["IBM", 50]]),
        ("q1", "OUT", [["IBM", 50]]),
    ]
    assert [e.data for e in got] == [["IBM", 50]]


def test_next_steps_to_following_query(manager):
    rt = manager.create_siddhi_app_runtime(APP)
    hits = []

    def on_debug(events, query, terminal, debugger):
        hits.append((query, terminal))
        if len(hits) < 3:
            debugger.next()   # step: next checkpoint regardless of acquisition
        else:
            debugger.play()

    dbg = rt.debug()
    dbg.set_debugger_callback(on_debug)
    dbg.acquire_break_point("q1", "IN")
    rt.get_input_handler("S").send(["IBM", 50])
    rt.shutdown()
    # IN(q1) acquired; next() stops at OUT(q1); next() stops at IN(q2)
    assert hits == [("q1", "IN"), ("q1", "OUT"), ("q2", "IN")]


def test_release_breakpoint(manager):
    rt = manager.create_siddhi_app_runtime(APP)
    hits = []

    def on_debug(events, query, terminal, debugger):
        hits.append((query, terminal))
        debugger.play()

    dbg = rt.debug()
    dbg.set_debugger_callback(on_debug)
    dbg.acquire_break_point("q1", "IN")
    rt.get_input_handler("S").send(["A", 20])
    dbg.release_break_point("q1", "IN")
    rt.get_input_handler("S").send(["B", 30])
    rt.shutdown()
    assert hits == [("q1", "IN")]


def test_blocked_thread_resumed_externally(manager):
    rt = manager.create_siddhi_app_runtime(APP)
    dbg = rt.debug()
    dbg.acquire_break_point("q1", "IN")
    reached = threading.Event()
    hits = []

    def on_debug(events, query, terminal, debugger):
        hits.append(query)
        reached.set()  # no resume here: thread must block

    dbg.set_debugger_callback(on_debug)
    got = []
    rt.add_callback("Out", lambda evs: got.extend(evs))
    t = threading.Thread(target=lambda: rt.get_input_handler("S").send(["X", 99]))
    t.start()
    assert reached.wait(2)
    assert not got  # still paused before the filter ran downstream
    dbg.play()
    t.join(2)
    rt.shutdown()
    assert [e.data for e in got] == [["X", 99]]


def test_get_query_state(manager):
    rt = manager.create_siddhi_app_runtime(
        "define stream S (v long); "
        "@info(name='w') from S#window.length(3) select sum(v) as t insert into O;"
    )
    dbg = rt.debug()
    rt.get_input_handler("S").send([5])
    state = dbg.get_query_state("w")
    rt.shutdown()
    assert state is not None and "windows" in state
