"""Device-resident tables: differential + lifecycle suite.

``@app:devtables`` stores eligible tables as device-resident columnar
arrays (``siddhi_tpu/devtable/``): one ``[capacity]`` device column per
attribute plus a validity lane, mutations lowered to jitted one-hot
last-writer-wins scatters, and stream-table joins lowered to a ``[B, C]``
masked probe that keeps matched pairs device-resident from ingest to the
coalesced emit drain.  The contracts pinned here:

* **Differential exactness** — every mutation shape (insert, delete,
  update, update-or-insert, duplicate keys inside one batch, mutations
  straddling join batches) and the join output are bit-identical to the
  host ``InMemoryTable`` path, event for event.
* **Fault transparency** — transient ``ingest.put`` / ``emit.drain``
  faults retry without losing or duplicating rows; a simulated crash +
  journal replay reproduces the uninterrupted run.
* **MVCC pinning** — ``persist(mode='async')`` captures the revision
  pinned at the barrier even while later mutations land, and
  ``restore_last_revision`` + replay is bit-exact.
* **Graceful degradation** — capacity overflow first compacts
  tombstones in-barrier (counted), then demotes the table to the host
  path with a WARNING and a counted ``devtable_demotions`` stat;
  ineligible tables/queries never lower and are counted, never wrong.
* **TableCache honesty** — the host path the devtable differential
  compares against must itself be correct: a primary-key-rewriting
  update through the callbacks invalidates the DESTINATION key too
  (regression for a stale-cache read in ``table/record.py``).
"""

import contextlib

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.event import Event
from siddhi_tpu.core.exceptions import SimulatedCrashError
from siddhi_tpu.devtable import DeviceTable
from siddhi_tpu.durability import DurableFileSystemPersistenceStore

pytestmark = pytest.mark.faults


BODY = (
    "define stream S (k int, x float); "
    "define stream Ins (k int, v float, f bool); "
    "define stream Del (k int); "
    "define stream Upd (k int, v float); "
    "define stream Ups (k int, v float, f bool); "
    "@PrimaryKey('k') define table T (k int, v float, f bool); "
    "from Ins insert into T; "
    "from Del delete T on T.k == k; "
    "from Upd update T set T.v = v on T.k == k; "
    "from Ups update or insert into T set T.v = v, T.f = f "
    "on T.k == k; "
    "@info(name='j') from S join T as t on S.k == t.k "
    "select S.k as k, S.x as x, t.v as v, t.f as f insert into Out;"
)


def ops_series(n, seed, n_keys=6):
    """Random interleaved mutation + probe series (stream, row) pairs."""
    rng = np.random.default_rng(seed)
    ops = []
    for _ in range(n):
        k = int(rng.integers(0, n_keys))
        v = float(np.float32(rng.uniform(0, 100)))
        roll = rng.random()
        if roll < 0.25:
            ops.append(("Ins", [k, v, bool(rng.integers(0, 2))]))
        elif roll < 0.40:
            ops.append(("Del", [k]))
        elif roll < 0.55:
            ops.append(("Upd", [k, v]))
        elif roll < 0.75:
            ops.append(("Ups", [k, v, bool(rng.integers(0, 2))]))
        else:
            ops.append(("S", [k, v]))
    return ops


def run(ops, devtables=True, capacity=64, faults=None, header_extra="",
        transfer_guard=False, batches=None):
    """Playback run of the mixed series -> (emitted tuples, sorted table
    rows, lowering map, stats dict).  ``batches``: list of (stream,
    [rows]) groups sent as ONE junction batch each (dup-key coverage)."""
    header = "@app:name('dt') @app:playback @app:execution('tpu') "
    if devtables:
        header += f"@app:devtables(capacity='{capacity}') "
    if faults is not None:
        header += f"@app:faults({faults}) "
    header += header_extra
    guard = contextlib.nullcontext()
    if transfer_guard:
        import jax

        # no-op on the CPU backend (host<->cpu crossings are free), but
        # wires the zero-host-materialization contract for TPU CI — the
        # static twin is the host-sync-hazard rule over devtable/
        guard = jax.transfer_guard("disallow")
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(header + BODY)
        got = []
        rt.add_callback("Out", lambda evs: got.extend(tuple(e.data)
                                                      for e in evs))
        rt.start()
        handlers = {s: rt.get_input_handler(s)
                    for s in ("S", "Ins", "Del", "Upd", "Ups")}
        ts = 1000
        with guard:
            if batches is None:
                for stream, row in ops:
                    handlers[stream].send(list(row), timestamp=ts)
                    ts += 10
            else:
                for stream, rows in batches:
                    handlers[stream].send(
                        [Event(ts + i, list(r)) for i, r in enumerate(rows)])
                    ts += 10 * (len(rows) + 1)
            rt.drain_device_emits()
        t = rt.tables["T"]
        b = t.rows_batch()
        rows = sorted(tuple(b.columns[nm][i] for nm in b.attribute_names)
                      for i in range(len(b)))
        lowering = rt.lowering()
        stats = rt.statistics()
        rt.shutdown()
        return got, rows, lowering, stats
    finally:
        m.shutdown()


def host_reference(ops, batches=None):
    """The same series through the host table path (no @app:devtables)."""
    return run(ops, devtables=False, batches=batches)


class TestDevTableDifferential:
    def test_lowering_reports_devtable(self):
        ops = [("Ins", [1, 5.0, True]), ("S", [1, 0.5])]
        got, rows, lowering, stats = run(ops)
        assert lowering["j"] == "devtable"
        assert got == [(1, np.float32(0.5), np.float32(5.0), True)]
        key = [k for k in stats if k.endswith("devtableScatterSteps")]
        assert key and stats[key[0]] >= 1

    @pytest.mark.parametrize("seed", [3, 17, 41])
    def test_mixed_mutations_and_joins_bit_identical(self, seed):
        ops = ops_series(60, seed)
        ref_got, ref_rows, ref_low, _ = host_reference(ops)
        got, rows, lowering, _ = run(ops)
        assert lowering["j"] == "devtable"
        assert ref_low["j"] != "devtable"
        assert got == ref_got, f"seed {seed}: join outputs diverged"
        assert rows == ref_rows, f"seed {seed}: table contents diverged"
        assert any(s == "S" for s, _ in ops) and len(ref_got) > 0, (
            "series too tame; differential is vacuous")

    def test_duplicate_keys_in_one_batch_lww(self):
        # several writers hit the SAME slot inside one scatter: last
        # writer (by arrival order) must win, exactly like the host's
        # sequential application
        batches = [
            ("Ups", [[1, 10.0, True], [1, 11.0, False], [2, 20.0, True],
                     [1, 12.0, True], [2, 21.0, False]]),
            ("S", [[1, 0.5], [2, 0.25]]),
            ("Del", [[1], [1]]),          # double-delete of one key
            ("Ups", [[1, 13.0, False], [3, 30.0, True], [3, 31.0, False]]),
            ("S", [[1, 0.75], [3, 0.125]]),
        ]
        ref_got, ref_rows, _, _ = host_reference([], batches=batches)
        got, rows, lowering, _ = run([], batches=batches)
        assert lowering["j"] == "devtable"
        assert got == ref_got
        assert rows == ref_rows

    def test_batch_straddling_mutations(self):
        # probes interleaved between mutation batches must observe each
        # barrier-pinned revision in order: probe -> update -> probe ->
        # delete -> probe sees three different table states
        ops = [
            ("Ins", [7, 1.0, True]),
            ("S", [7, 0.1]),
            ("Upd", [7, 2.0]),
            ("S", [7, 0.2]),
            ("Del", [7]),
            ("S", [7, 0.3]),
            ("Ups", [7, 3.0, False]),
            ("S", [7, 0.4]),
        ]
        ref_got, ref_rows, _, _ = host_reference(ops)
        got, rows, _, _ = run(ops)
        assert got == ref_got
        assert rows == ref_rows
        assert [np.float32(g[2]) for g in got] == [
            np.float32(1.0), np.float32(2.0), np.float32(3.0)]

    def test_zero_host_materialization_under_transfer_guard(self):
        ops = ops_series(40, seed=23)
        ref_got, ref_rows, _, _ = host_reference(ops)
        got, rows, lowering, _ = run(ops, transfer_guard=True)
        assert lowering["j"] == "devtable"
        assert got == ref_got
        assert rows == ref_rows


class TestDevTableFaults:
    def test_transient_ingest_and_emit_faults_recovered(self):
        ops = ops_series(50, seed=29)
        ref_got, ref_rows, _, _ = host_reference(ops)
        got, rows, lowering, stats = run(
            ops, faults=("transfer.retry.scale='0.0001', "
                         "ingest.put='transient:count=2', "
                         "emit.drain='transient:count=2'"))
        assert lowering["j"] == "devtable"
        assert got == ref_got, "retried transfers must not lose/dup rows"
        assert rows == ref_rows

    def test_crash_and_journal_replay_bit_identical(self, tmp_path):
        ops = ops_series(40, seed=37)
        ref_got, ref_rows, _, _ = host_reference(ops)
        header = ("@app:name('dt') @app:playback @app:execution('tpu') "
                  "@app:devtables(capacity='64') "
                  "@app:faults(journal='256') ")
        m = SiddhiManager()
        try:
            m.set_persistence_store(
                DurableFileSystemPersistenceStore(str(tmp_path)))
            rt = m.create_siddhi_app_runtime(header + BODY)
            got = []
            rt.add_callback("Out", lambda evs: got.extend(tuple(e.data)
                                                          for e in evs))
            rt.start()
            hs = {s: rt.get_input_handler(s)
                  for s in ("S", "Ins", "Del", "Upd", "Ups")}
            ts = 1000
            for stream, row in ops[:12]:
                hs[stream].send(list(row), timestamp=ts)
                ts += 10
            rt.persist()
            for stream, row in ops[12:25]:
                hs[stream].send(list(row), timestamp=ts)
                ts += 10
            rt.app_context.fault_injector.configure("ingest", "crash",
                                                    count=1)
            with pytest.raises(SimulatedCrashError):
                hs[ops[25][0]].send(list(ops[25][1]), timestamp=ts)
            ts += 10
            rt.shutdown()  # the crashed runtime is gone

            rt2 = m.create_siddhi_app_runtime(header + BODY)
            rt2.add_callback("Out", lambda evs: got.extend(tuple(e.data)
                                                           for e in evs))
            rt2.start()
            assert rt2.restore_last_revision() is not None
            hs2 = {s: rt2.get_input_handler(s)
                   for s in ("S", "Ins", "Del", "Upd", "Ups")}
            # the crashed send was journaled before the crash fired, so
            # replay already delivered it — continue after it
            for stream, row in ops[26:]:
                hs2[stream].send(list(row), timestamp=ts)
                ts += 10
            rt2.drain_device_emits()
            t = rt2.tables["T"]
            b = t.rows_batch()
            rows = sorted(tuple(b.columns[nm][i]
                                for nm in b.attribute_names)
                          for i in range(len(b)))
            rt2.shutdown()
            assert got == ref_got, "crash+replay diverged"
            assert rows == ref_rows
        finally:
            m.shutdown()


class TestDevTableDurability:
    def test_async_persist_pins_barrier_revision_mid_mutation(
            self, tmp_path):
        """persist(mode='async') while mutations keep landing must
        capture the revision pinned AT the barrier — later scatters make
        new device arrays and cannot retroactively change the capture —
        and restore + journal replay is bit-exact."""
        ops = ops_series(40, seed=43)
        ref_got, ref_rows, _, _ = host_reference(ops)
        header = ("@app:name('dt') @app:playback @app:execution('tpu') "
                  "@app:devtables(capacity='64') "
                  "@app:faults(journal='256') ")
        m = SiddhiManager()
        try:
            m.set_persistence_store(
                DurableFileSystemPersistenceStore(str(tmp_path)))
            rt = m.create_siddhi_app_runtime(header + BODY)
            got = []
            rt.add_callback("Out", lambda evs: got.extend(tuple(e.data)
                                                          for e in evs))
            rt.start()
            hs = {s: rt.get_input_handler(s)
                  for s in ("S", "Ins", "Del", "Upd", "Ups")}
            ts = 1000
            for stream, row in ops[:15]:
                hs[stream].send(list(row), timestamp=ts)
                ts += 10
            rev = rt.persist(mode="async")
            # keep mutating BEFORE the async write commits: the writer
            # must still persist the barrier-pinned revision
            for stream, row in ops[15:]:
                hs[stream].send(list(row), timestamp=ts)
                ts += 10
            assert rt.wait_for_persist(rev, timeout=30) == "committed"
            rt.shutdown()

            rt2 = m.create_siddhi_app_runtime(header + BODY)
            rt2.add_callback("Out", lambda evs: got.extend(tuple(e.data)
                                                           for e in evs))
            rt2.start()
            assert rt2.restore_last_revision() == rev
            # journal replay re-delivers ops[15:]; any emissions it
            # produces re-enter `got` — the restored run must converge
            # to the same table state as the uninterrupted reference
            rt2.drain_device_emits()
            t = rt2.tables["T"]
            assert isinstance(t, DeviceTable) and not t.demoted
            b = t.rows_batch()
            rows = sorted(tuple(b.columns[nm][i]
                                for nm in b.attribute_names)
                          for i in range(len(b)))
            rt2.shutdown()
            assert rows == ref_rows, "restored+replayed table diverged"
        finally:
            m.shutdown()


class TestCapacityLifecycle:
    def test_overflow_compacts_then_demotes_counted(self, caplog):
        import logging

        # capacity 4: churn one key (tombstones) -> compaction keeps the
        # table device-resident; then 5 distinct live keys overflow ->
        # demotion with a WARNING + counted stat, results still exact
        ops = []
        for i in range(6):
            ops.append(("Ups", [1, float(i), True]))
            ops.append(("Del", [1]))
        for k in range(5):
            ops.append(("Ins", [k, float(k) * 10.0, False]))
        ops += [("S", [k, 0.5]) for k in range(5)]
        ref_got, ref_rows, _, _ = host_reference(ops)
        with caplog.at_level(logging.WARNING, logger="siddhi_tpu"):
            got, rows, lowering, stats = run(ops, capacity=4)
        assert got == ref_got
        assert rows == ref_rows

        def stat(suffix):
            keys = [k for k in stats if k.endswith(suffix)]
            return stats[keys[0]] if keys else None

        assert stat("devtableCompactions") >= 1
        assert stat("devtableDemotions") == 1
        assert stat("devtableDemoted") is True
        assert any("demot" in r.message.lower() for r in caplog.records), (
            "demotion must be surfaced with a WARNING")

    def test_ineligible_table_stays_host_counted(self):
        # STRING attribute -> no device lane -> the table never lowers;
        # the reason is counted and everything still runs on host
        body = (
            "define stream S (sym string, x float); "
            "define stream Ins (sym string, v float); "
            "@PrimaryKey('sym') define table T (sym string, v float); "
            "from Ins insert into T; "
            "@info(name='j') from S join T as t on S.sym == t.sym "
            "select S.sym as sym, t.v as v insert into Out;")
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:name('dt2') @app:playback @app:execution('tpu') "
                "@app:devtables(capacity='8') " + body)
            got = []
            rt.add_callback("Out", lambda evs: got.extend(tuple(e.data)
                                                          for e in evs))
            rt.start()
            assert not isinstance(rt.tables["T"], DeviceTable)
            assert rt.lowering()["j"] != "devtable"
            sm = rt.app_context.statistics_manager
            assert sm.devtable_fallback_reasons, (
                "ineligibility must be counted, not silent")
            rt.get_input_handler("Ins").send(["IBM", 9.0], timestamp=1000)
            rt.get_input_handler("S").send(["IBM", 0.5], timestamp=1010)
            rt.shutdown()
            assert got == [("IBM", np.float32(9.0))]
        finally:
            m.shutdown()

    def test_bad_annotation_rejected(self):
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        m = SiddhiManager()
        try:
            with pytest.raises(SiddhiAppCreationError):
                m.create_siddhi_app_runtime(
                    "@app:devtables define stream S (k int); "
                    "from S insert into Out;")  # needs @app:execution('tpu')
            with pytest.raises(SiddhiAppCreationError):
                m.create_siddhi_app_runtime(
                    "@app:execution('tpu') @app:devtables(capacity='0') "
                    "define stream S (k int); from S insert into Out;")
        finally:
            m.shutdown()


class TestTableCacheInvalidation:
    """Regression: a primary-key-rewriting update through the callbacks
    must invalidate the DESTINATION key's cache entry too — a stale
    single-row entry under the new key otherwise keeps answering pk
    probes after the store already holds two rows for that key."""

    APP = (
        "define stream Ins (symbol string, price float); "
        "define stream Ren (old string, new string); "
        "define stream Chk (symbol string); "
        "@store(type='memory', @cache(size='10', cache.policy='LRU')) "
        "@PrimaryKey('symbol') "
        "define table T (symbol string, price float); "
        "from Ins insert into T; "
        "from Ren update T set T.symbol = new on T.symbol == old; "
        "@info(name='chk') from Chk join T as t on Chk.symbol == t.symbol "
        "select t.symbol as symbol, t.price as price insert into Out;")

    def test_pk_rewrite_invalidates_destination_key(self):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:name('cache') @app:playback " + self.APP)
            rt.start()
            rt.get_input_handler("Ins").send(["B", 9.0], timestamp=1000)
            # prime the cache under key 'B'
            assert [e.data for e in rt.query(
                "from T on symbol == 'B' select price")] == [[9.0]]
            rt.get_input_handler("Ins").send(["A", 1.0], timestamp=1010)
            # rewrite A's primary key to 'B': the store now holds two
            # 'B' rows; the cached single-row entry for 'B' is stale
            rt.get_input_handler("Ren").send(["A", "B"], timestamp=1020)
            events = rt.query("from T on symbol == 'B' select price")
            assert sorted(e.data[0] for e in events) == [1.0, 9.0], (
                "stale TableCache entry under the rewritten key")
            rt.shutdown()
        finally:
            m.shutdown()

    def test_update_or_insert_then_probe_sees_fresh_row(self):
        app = (
            "define stream Ups (symbol string, price float); "
            "@store(type='memory', @cache(size='10', cache.policy='LRU')) "
            "@PrimaryKey('symbol') "
            "define table T (symbol string, price float); "
            "from Ups update or insert into T set T.price = price "
            "on T.symbol == symbol;")
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:name('cache2') @app:playback " + app)
            rt.start()
            h = rt.get_input_handler("Ups")
            h.send(["IBM", 1.0], timestamp=1000)
            assert [e.data for e in rt.query(
                "from T on symbol == 'IBM' select price")] == [[1.0]]
            h.send(["IBM", 2.0], timestamp=1010)  # update branch
            assert [e.data for e in rt.query(
                "from T on symbol == 'IBM' select price")] == [[2.0]]
            rt.shutdown()
        finally:
            m.shutdown()
