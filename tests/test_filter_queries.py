"""End-to-end filter/projection query conformance tests.

Style mirrors the reference TestNG suite (SiddhiQL in, events in,
asserted events out — e.g. query/FilterTestCase1.java): no mocks, the
whole engine runs in-process.
"""

import pytest

from siddhi_tpu import SiddhiManager


@pytest.fixture
def manager():
    m = SiddhiManager()
    yield m
    m.shutdown()


def run_app(manager, app, stream, rows, out_stream="OutputStream"):
    rt = manager.create_siddhi_app_runtime(app)
    got = []
    rt.add_callback(out_stream, lambda events: got.extend(events))
    rt.start()
    h = rt.get_input_handler(stream)
    for r in rows:
        h.send(r)
    rt.shutdown()
    return got


class TestFilter:
    APP = (
        "define stream cseEventStream (symbol string, price float, volume long); "
        "@info(name = 'query1') "
        "from cseEventStream[volume < 150] "
        "select symbol, price insert into OutputStream;"
    )

    def test_basic_filter(self, manager):
        got = run_app(
            manager,
            self.APP,
            "cseEventStream",
            [["IBM", 700.0, 100], ["WSO2", 60.5, 200], ["GOOG", 50.0, 30]],
        )
        assert [e.data for e in got] == [["IBM", 700.0], ["GOOG", 50.0]]

    def test_compound_condition(self, manager):
        app = (
            "define stream S (symbol string, price float, volume long); "
            "from S[volume < 150 and price > 55.0] select symbol insert into OutputStream;"
        )
        got = run_app(manager, app, "S", [["A", 60.0, 100], ["B", 50.0, 100], ["C", 60.0, 200]])
        assert [e.data for e in got] == [["A"]]

    def test_string_equality(self, manager):
        app = (
            "define stream S (symbol string, price float); "
            "from S[symbol == 'IBM'] select price insert into OutputStream;"
        )
        got = run_app(manager, app, "S", [["IBM", 10.0], ["X", 20.0], ["IBM", 30.0]])
        assert [e.data for e in got] == [[10.0], [30.0]]

    def test_not_and_or(self, manager):
        app = (
            "define stream S (a int, b int); "
            "from S[not (a > 5) or b == 0] select a, b insert into OutputStream;"
        )
        got = run_app(manager, app, "S", [[1, 1], [9, 1], [9, 0]])
        assert [e.data for e in got] == [[1, 1], [9, 0]]

    def test_math_projection(self, manager):
        app = (
            "define stream S (a int, b int); "
            "from S select a + b * 2 as x, a - b as y, a / b as d, a % b as m "
            "insert into OutputStream;"
        )
        got = run_app(manager, app, "S", [[7, 2]])
        assert got[0].data == [11, 5, 3, 1]

    def test_java_int_division_semantics(self, manager):
        app = (
            "define stream S (a int, b int); "
            "from S select a / b as d, a % b as m insert into OutputStream;"
        )
        got = run_app(manager, app, "S", [[-7, 2], [7, -2], [-7, -2]])
        # Java: -7/2 == -3 (trunc toward zero), -7%2 == -1 (sign of dividend)
        assert [e.data for e in got] == [[-3, -1], [-3, 1], [3, -1]]

    def test_float_promotion(self, manager):
        app = (
            "define stream S (a int, f float); "
            "from S select a + f as x insert into OutputStream;"
        )
        got = run_app(manager, app, "S", [[1, 0.5]])
        assert got[0].data[0] == pytest.approx(1.5)

    def test_select_star(self, manager):
        app = "define stream S (a int, b string); from S select * insert into OutputStream;"
        got = run_app(manager, app, "S", [[5, "x"]])
        assert got[0].data == [5, "x"]

    def test_chained_queries(self, manager):
        app = (
            "define stream S (a int); "
            "from S[a > 0] select a * 10 as b insert into Mid; "
            "from Mid[b > 50] select b insert into OutputStream;"
        )
        got = run_app(manager, app, "S", [[1], [6], [-3], [9]])
        assert [e.data for e in got] == [[60], [90]]

    def test_multiple_queries_same_stream(self, manager):
        app = (
            "define stream S (a int); "
            "from S[a > 5] select a insert into OutputStream; "
            "from S[a < 3] select a insert into OutputStream;"
        )
        got = run_app(manager, app, "S", [[1], [6], [4]])
        assert sorted(e.data[0] for e in got) == [1, 6]

    def test_if_then_else_and_cast(self, manager):
        app = (
            "define stream S (a int); "
            "from S select ifThenElse(a > 5, 'big', 'small') as size, "
            "cast(a, 'double') as d insert into OutputStream;"
        )
        got = run_app(manager, app, "S", [[10], [2]])
        assert got[0].data == ["big", 10.0]
        assert got[1].data == ["small", 2.0]

    def test_query_callback(self, manager):
        rt = manager.create_siddhi_app_runtime(self.APP)
        received = []
        rt.add_callback("query1", lambda ts, ins, outs: received.append((ins, outs)))
        rt.start()
        h = rt.get_input_handler("cseEventStream")
        h.send(["IBM", 700.0, 100])
        h.send(["WSO2", 60.5, 200])
        rt.shutdown()
        assert len(received) == 1
        ins, outs = received[0]
        assert outs is None
        assert [e.data for e in ins] == [["IBM", 700.0]]

    def test_event_timestamp_fn(self, manager):
        app = (
            "define stream S (a int); "
            "from S select eventTimestamp() as ts, a insert into OutputStream;"
        )
        rt = manager.create_siddhi_app_runtime(app)
        got = []
        rt.add_callback("OutputStream", lambda evs: got.extend(evs))
        rt.start()
        rt.get_input_handler("S").send([1], timestamp=12345)
        rt.shutdown()
        assert got[0].data == [12345, 1]

    def test_undefined_stream_error(self, manager):
        from siddhi_tpu.core.exceptions import DefinitionNotExistError

        with pytest.raises(DefinitionNotExistError):
            manager.create_siddhi_app_runtime(
                "define stream S (a int); from Missing select a insert into O;"
            )

    def test_unknown_function_error(self, manager):
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        with pytest.raises(SiddhiAppCreationError):
            manager.create_siddhi_app_runtime(
                "define stream S (a int); from S select nosuchfn(a) as x insert into O;"
            )


class TestAggregationsNoWindow:
    def test_running_sum_count(self, manager):
        app = (
            "define stream S (symbol string, price double); "
            "from S select symbol, sum(price) as total, count() as n "
            "insert into OutputStream;"
        )
        got = run_app(manager, app, "S", [["A", 1.0], ["A", 2.0], ["A", 3.0]])
        assert [e.data for e in got] == [["A", 1.0, 1], ["A", 3.0, 2], ["A", 6.0, 3]]

    def test_group_by_running_sum(self, manager):
        app = (
            "define stream S (symbol string, v long); "
            "from S select symbol, sum(v) as total group by symbol "
            "insert into OutputStream;"
        )
        got = run_app(
            manager, app, "S", [["A", 10], ["B", 1], ["A", 5], ["B", 2]]
        )
        assert [e.data for e in got] == [["A", 10], ["B", 1], ["A", 15], ["B", 3]]

    def test_avg_min_max(self, manager):
        app = (
            "define stream S (v double); "
            "from S select avg(v) as a, min(v) as mn, max(v) as mx "
            "insert into OutputStream;"
        )
        got = run_app(manager, app, "S", [[4.0], [2.0], [6.0]])
        assert got[-1].data == [4.0, 2.0, 6.0]

    def test_having(self, manager):
        app = (
            "define stream S (symbol string, v long); "
            "from S select symbol, sum(v) as total group by symbol "
            "having total > 10 insert into OutputStream;"
        )
        got = run_app(manager, app, "S", [["A", 5], ["A", 7], ["B", 3]])
        assert [e.data for e in got] == [["A", 12]]

    def test_agg_in_expression(self, manager):
        app = (
            "define stream S (v long); "
            "from S select sum(v) * 2 as double_total insert into OutputStream;"
        )
        got = run_app(manager, app, "S", [[1], [2]])
        assert [e.data for e in got] == [[2], [6]]


class TestLengthWindows:
    def test_length_window_expiry(self, manager):
        app = (
            "define stream S (symbol string, price float); "
            "from S#window.length(2) select symbol, price insert all events into OutputStream;"
        )
        got = run_app(manager, app, "S", [["A", 1.0], ["B", 2.0], ["C", 3.0]])
        # third arrival expires A first (eviction precedes arrival)
        assert [e.data for e in got] == [["A", 1.0], ["B", 2.0], ["A", 1.0], ["C", 3.0]]

    def test_length_window_sum(self, manager):
        app = (
            "define stream S (v long); "
            "from S#window.length(2) select sum(v) as total insert into OutputStream;"
        )
        got = run_app(manager, app, "S", [[1], [2], [3], [4]])
        # windowed running sum over last 2 (evictions subtract first)
        assert [e.data[0] for e in got] == [1, 3, 5, 7]

    def test_length_batch(self, manager):
        app = (
            "define stream S (v long); "
            "from S#window.lengthBatch(2) select sum(v) as total insert into OutputStream;"
        )
        got = run_app(manager, app, "S", [[1], [2], [3], [4]])
        # batch mode: one aggregate per flush (reference batched selector)
        assert [e.data[0] for e in got] == [3, 7]

    def test_query_callback_remove_events(self, manager):
        app = (
            "define stream S (v long); "
            "@info(name='q') from S#window.length(1) select v insert all events into OutputStream;"
        )
        rt = manager.create_siddhi_app_runtime(app)
        pairs = []
        rt.add_callback("q", lambda ts, ins, outs: pairs.append((ins, outs)))
        rt.start()
        h = rt.get_input_handler("S")
        h.send([1])
        h.send([2])
        rt.shutdown()
        assert [e.data for e in pairs[0][0]] == [[1]]
        assert pairs[0][1] is None
        assert [e.data for e in pairs[1][0]] == [[2]]
        assert [e.data for e in pairs[1][1]] == [[1]]


class TestManagerApis:
    def test_validate_ok(self, manager):
        manager.validate_siddhi_app(
            "define stream S (v long); from S[v > 1] select v insert into O;"
        )
        # validation does not leave a runtime registered
        assert manager.get_siddhi_app_runtimes() == {}

    def test_validate_bad_raises(self, manager):
        import pytest as _pytest
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        with _pytest.raises(Exception):
            manager.validate_siddhi_app(
                "define stream S (v long); from S[nope > 1] select v insert into O;"
            )

    def test_sandbox_strips_transports(self, manager):
        rt = manager.create_sandbox_siddhi_app_runtime(
            "@source(type='doesNotExist', topic='x', @map(type='passThrough')) "
            "define stream S (v long); "
            "@store(type='alsoMissing') define table T (v long); "
            "from S select v insert into T;"
        )
        rt.start()
        rt.get_input_handler("S").send([7])
        events = rt.query("from T select v")
        rt.shutdown()
        assert [e.data[0] for e in events] == [7]

    def test_set_attribute(self, manager):
        manager.set_attribute("shared", {"x": 1})
        assert manager.get_attributes()["shared"] == {"x": 1}
