"""The driver's multi-chip dryrun, exercised in CI.

This is exactly what the driver runs with N virtual CPU devices — it
failed unnoticed in rounds 1 and 2 because nothing in `pytest tests/`
covered it.  The conftest already forces an 8-device CPU mesh, so the
entry point must work in-process here.
"""

import numpy as np
import pytest


def test_dryrun_multichip_8():
    from __graft_entry__ import dryrun_multichip

    dryrun_multichip(8)


def test_entry_compiles_and_steps():
    import jax

    from __graft_entry__ import entry

    fn, args = entry()
    out_state, emit, out_vals, emit_anchor, n_emit = jax.jit(fn)(*args)
    assert set(out_state) == {"active", "first_ts", "counts", "regs", "overflow"}
    assert np.asarray(emit).dtype == bool
    # async emit pipeline: the step returns a scalar match count so the
    # host can skip all column transfers on zero-match batches
    assert np.asarray(n_emit).shape == ()
    assert np.asarray(n_emit).dtype == np.int32


def test_sharded_engine_init_is_host_only(monkeypatch):
    """init_state of the sharded wrapper must not allocate via the
    engine's device init (the round-2 crash path)."""
    from siddhi_tpu.ops.dense_nfa import compile_pattern
    from siddhi_tpu.parallel import ShardedPatternEngine, make_mesh

    from __graft_entry__ import FRAUD_APP

    eng = compile_pattern(FRAUD_APP, "fraud", n_partitions=64 * 8)

    def boom():
        raise AssertionError("device init_state called during sharded init")

    monkeypatch.setattr(eng, "init_state", boom)
    mesh = make_mesh(8)
    sharded = ShardedPatternEngine(eng, mesh)
    state = sharded.init_state()
    assert state["active"].shape[0] == 8 * (64 + 1)
