"""Overload protection and self-healing (robustness/) suite.

The contract under test, end to end:

- ``@app:limits(rate=...)`` admission control sheds EXACTLY what the
  token-bucket arithmetic says it must (a reference bucket is
  reimplemented here as an independent oracle), per stream, with the
  admitted events' outputs bit-identical to an unthrottled run fed
  only the admitted set — including under a Zipf-skewed multi-tenant
  chaos soak with transient ingest/emit faults.
- The watchdog detects a wedged async batch cycle and self-heals by
  forcing a replan: engines rebuilt, journal history replayed through
  the suppressing output ledger, outputs bit-identical to an
  uninterrupted run.  Without a journal the heal is REFUSED and
  counted, never attempted.
- Circuit breakers on sinks spool output while open (bounded) and
  flush exactly once on close — no duplicates, order preserved.
- The degradation ladder demotes lowerings in the documented order
  under sustained pressure and re-promotes under hysteresis, each rung
  a counted bit-exact replan.
- ``GET /siddhi-health/<app>`` reports the same counters the
  statistics feed carries; overloaded apps answer 503 with a JSON body
  instead of blocking on the app lock.
- Zero behavior change without the annotation.
"""

import time
import types
import urllib.error
import urllib.request

import json

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.exceptions import (
    InjectedFaultError,
    SiddhiAppCreationError,
    SimulatedCrashError,
)
from siddhi_tpu.robustness import (
    DEMOTE_ORDER,
    DegradationLadder,
    RobustnessStats,
    TokenBucket,
    apply_degradation,
)


def _collector(res):
    return lambda events: res.extend(
        (e.timestamp, tuple(e.data)) for e in events)


def _norm(rows):
    """DOUBLE attrs ride float32 device lanes (documented precision
    subset): one-decimal inputs are exact at 4dp."""
    return [(ts, tuple(round(v, 4) if isinstance(v, float) else v
                       for v in r)) for ts, r in rows]


class RefBucket:
    """Independent oracle: the token-bucket arithmetic reimplemented
    from the paper's spec (NOT imported from robustness/) — the exact
    float ops the controller must match, event time in seconds."""

    def __init__(self, rate, burst, now):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.last = now

    def take(self, n, now):
        if now > self.last:
            self.tokens = min(self.burst,
                              self.tokens + (now - self.last) * self.rate)
            self.last = now
        k = int(min(n, self.tokens))
        self.tokens -= k
        return k


class TestTokenBucket:
    def test_burst_then_refill(self):
        b = TokenBucket(rate=10.0, burst=5.0, now=0.0)
        assert b.take(8, 0.0) == 5          # burst drained
        assert b.take(3, 0.0) == 0
        assert b.take(3, 0.2) == 2          # 0.2 s * 10/s = 2 tokens
        assert b.take(100, 10.0) == 5       # refill caps at burst

    def test_refill_never_rewinds(self):
        b = TokenBucket(rate=10.0, burst=5.0, now=1.0)
        b.take(5, 1.0)
        b.refill(0.5)                       # stale clock: no-op
        assert b.tokens == 0.0

    def test_eta_to_next_token(self):
        b = TokenBucket(rate=4.0, burst=1.0, now=0.0)
        assert b.eta_s(0.0) == 0.0
        b.take(1, 0.0)
        assert b.eta_s(0.0) == pytest.approx(0.25)


class TestLimitsAnnotation:
    @pytest.mark.parametrize("ann, msg", [
        ("@app:limits()", "at least one"),
        ("@app:limits(burst='5')", "burst needs rate"),
        ("@app:limits(rate='0/s')", "positive"),
        ("@app:limits(rate='5/s', shed='weird')", "drop, oldest, block"),
        ("@app:limits(ladder='true')", "needs watchdog"),
        ("@app:limits(rate='5/s', burst='0')", "burst"),
        ("@app:limits(breaker='0')", "breaker"),
    ])
    def test_invalid_annotations_refused(self, ann, msg):
        m = SiddhiManager()
        try:
            with pytest.raises(SiddhiAppCreationError, match=msg):
                m.create_siddhi_app_runtime(
                    ann + " define stream S (k long);")
        finally:
            m.shutdown()

    def test_no_annotation_means_zero_machinery(self):
        """Zero behavior change without @app:limits: no controller, no
        stats object, no watchdog, no breaker, no Robustness metrics."""
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime("""
@app:name('plain0') @app:playback
define stream S (k long, v double);
@info(name='q') from S[v > 0.0] select k, v insert into OutS;
""")
            got = []
            rt.add_callback("OutS", _collector(got))
            rt.start()
            ctx = rt.app_context
            assert ctx.admission is None
            assert ctx.robustness is None
            assert getattr(rt, "_watchdog", None) is None
            assert rt.sinks == [] or all(
                s._breaker is None for s in rt.sinks)
            h = rt.get_input_handler("S")
            for i in range(50):
                h.send([i, 1.0], timestamp=1000 + i)
            assert len(got) == 50
            assert not any("Robustness" in k for k in rt.statistics())
            hd = rt.health()
            assert hd["healthy"] and hd["admission"] is None
            rt.shutdown()
        finally:
            m.shutdown()


SHED_APP = """
@app:name('sh{tag}') @app:playback
@app:limits(rate='{rate}/s', burst='{burst}', shed='{shed}')
define stream S (k long, v double);
@info(name='q') from S[v >= 0.0] select k, v insert into OutS;
"""


class TestShedPolicies:
    def _run(self, tag, shed, sends, rate=5, burst=5):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(SHED_APP.format(
                tag=tag, rate=rate, burst=burst, shed=shed))
            got = []
            rt.add_callback("OutS", _collector(got))
            rt.start()
            h = rt.get_input_handler("S")
            for row, ts in sends:
                h.send(list(row), timestamp=ts)
            rb = rt.app_context.robustness
            snap = rt.app_context.admission.snapshot()
            rt.shutdown()
            return got, rb, snap
        finally:
            m.shutdown()

    def test_drop_keeps_arrival_order_prefix(self):
        # 12 events inside one event-time second, budget = burst 5
        sends = [([i, float(i)], 1_000_000 + i) for i in range(12)]
        got, rb, snap = self._run("d0", "drop", sends)
        assert [r[1] for ts, r in got] == [0.0, 1.0, 2.0, 3.0, 4.0]
        assert (rb.events_admitted, rb.events_shed) == (5, 7)
        assert rb.shed_drop == 7 and rb.shed_oldest == 0
        assert snap["streams"]["S"] == {
            "admitted": 5, "shed": 7,
            "tokens": snap["streams"]["S"]["tokens"]}

    def test_oldest_keeps_the_freshest_rows(self):
        # one BATCH of 12: 'oldest' sheds the head, the newest 5 survive
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(SHED_APP.format(
                tag="o0", rate=5, burst=5, shed="oldest"))
            got = []
            rt.add_callback("OutS", _collector(got))
            rt.start()
            from siddhi_tpu.core.event import Event

            h = rt.get_input_handler("S")
            h.send([Event(1_000_000 + i, [i, float(i)])
                    for i in range(12)])
            rb = rt.app_context.robustness
            assert [r[1] for ts, r in got] == [7.0, 8.0, 9.0, 10.0, 11.0]
            assert rb.shed_oldest == 7 and rb.events_admitted == 5
            rt.shutdown()
        finally:
            m.shutdown()

    def test_block_in_playback_is_an_immediate_counted_timeout(self):
        # event time cannot advance while the sender parks: block
        # degrades to a deterministic timeout shed
        sends = [([i, float(i)], 1_000_000 + i) for i in range(12)]
        got, rb, _ = self._run("b0", "block", sends)
        assert len(got) == 5
        assert rb.shed_block_timeout == 7
        assert rb.block_waits == 0

    def test_block_backpressures_the_sender_wall_clock(self):
        # live clock: rate 200/s refills fast enough that every send
        # eventually admits — the sender just waits for its budget
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime("""
@app:name('blk1')
@app:limits(rate='200/s', burst='1', shed='block', block.max='2 sec')
define stream S (k long, v double);
@info(name='q') from S[v >= 0.0] select k, v insert into OutS;
""")
            got = []
            rt.add_callback("OutS", _collector(got))
            rt.start()
            h = rt.get_input_handler("S")
            for i in range(6):
                h.send([i, float(i)], timestamp=1000 + i)
            rb = rt.app_context.robustness
            assert len(got) == 6                  # nothing shed
            assert rb.events_shed == 0
            assert rb.block_waits >= 1            # backpressure happened
            rt.shutdown()
        finally:
            m.shutdown()

    def test_block_max_expiry_sheds_and_counts(self):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime("""
@app:name('blk2')
@app:limits(rate='5/s', burst='1', shed='block', block.max='40 ms')
define stream S (k long, v double);
@info(name='q') from S[v >= 0.0] select k, v insert into OutS;
""")
            got = []
            rt.add_callback("OutS", _collector(got))
            rt.start()
            h = rt.get_input_handler("S")
            for i in range(5):
                h.send([i, float(i)], timestamp=1000 + i)
            rb = rt.app_context.robustness
            assert rb.shed_block_timeout >= 1
            assert rb.events_admitted + rb.events_shed == 5
            assert len(got) == rb.events_admitted
            rt.shutdown()
        finally:
            m.shutdown()

    def test_admission_shed_fault_site_fires_on_the_drop(self):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:faults " + SHED_APP.format(
                    tag="f0", rate=5, burst=5, shed="drop")[1:])
            got = []
            rt.add_callback("OutS", _collector(got))
            rt.start()
            rt.app_context.fault_injector.configure(
                "admission.shed", "error", count=1)
            h = rt.get_input_handler("S")
            for i in range(5):
                h.send([i, float(i)], timestamp=1_000_000 + i)
            with pytest.raises(InjectedFaultError):
                h.send([5, 5.0], timestamp=1_000_000 + 5)   # first shed
            h.send([6, 6.0], timestamp=1_000_000 + 6)       # next is fine
            rb = rt.app_context.robustness
            assert rb.events_shed == 2      # both sheds counted
            assert len(got) == 5
            rt.shutdown()
        finally:
            m.shutdown()


SOAK_LIMITS = "@app:limits(rate='100/s', burst='20', shed='drop')"

SOAK_APP = """
@app:name('soak{tag}') @app:playback @app:execution('tpu') {faults} {limits}
define stream T0 (sym int, price float, vol int);
define stream T1 (sym int, price float, vol int);
define stream T2 (sym int, price float, vol int);
@info(name='q0') from T0[price > 5.0]
select sym, price, vol insert into OutA;
@info(name='q1') from T1[price > 5.0]
select sym, price, vol insert into OutA;
@info(name='q2') from T2[vol > 20] select sym, price insert into OutB;
"""


def _soak_traffic(n=900, seed=101):
    """Zipf-skewed multi-tenant traffic: tenant T0 takes ~60% of a
    ~300 ev/s aggregate (≈1.8x its 100/s budget), T1 ~27%, T2 ~13%
    (comfortably under budget).  Strictly increasing event time."""
    rng = np.random.default_rng(seed)
    weights = np.array([1.0, 1 / 2.2, 1 / 4.5])
    weights /= weights.sum()
    sends, ts = [], 1_000_000
    for _ in range(n):
        ts += int(rng.integers(2, 5))  # ~3.3 ms mean -> ~300 ev/s
        tenant = int(rng.choice(3, p=weights))
        row = [int(rng.integers(0, 50)),
               float(np.float32(rng.uniform(0, 30))),
               int(rng.integers(1, 100))]
        sends.append((f"T{tenant}", row, ts))
    return sends


def _expected_admission(sends, rate=100.0, burst=20.0):
    """Run the oracle buckets over the traffic: the exact admitted
    subset and per-stream shed counts the engine must reproduce."""
    buckets, admitted, shed = {}, [], {}
    for sid, row, ts in sends:
        now = ts / 1000.0
        b = buckets.get(sid)
        if b is None:
            b = buckets[sid] = RefBucket(rate, burst, now)
        if b.take(1, now):
            admitted.append((sid, row, ts))
        else:
            shed[sid] = shed.get(sid, 0) + 1
    return admitted, shed


class TestChaosSoak:
    pytestmark = pytest.mark.faults

    def test_zipf_multitenant_shed_is_exact_and_bit_identical(self):
        sends = _soak_traffic()
        admitted, shed = _expected_admission(sends)
        # the skew actually exercises both regimes
        assert shed.get("T0", 0) > 100      # heavy tenant sheds hard
        assert "T2" not in shed             # light tenant untouched

        def run(tag, faults, limits, traffic):
            m = SiddhiManager()
            try:
                rt = m.create_siddhi_app_runtime(SOAK_APP.format(
                    tag=tag, faults=faults, limits=limits))
                a, b = [], []
                rt.add_callback("OutA", _collector(a))
                rt.add_callback("OutB", _collector(b))
                rt.start()
                for sid, row, ts in traffic:
                    rt.get_input_handler(sid).send(list(row), timestamp=ts)
                rb = rt.app_context.robustness
                snap = (rt.app_context.admission.snapshot()
                        if rt.app_context.admission else None)
                rt.shutdown()
                return a, b, rb, snap
            finally:
                m.shutdown()

        # unthrottled reference fed ONLY the oracle-admitted subset
        ref_a, ref_b, _, _ = run("r", "", "", admitted)
        # throttled chaos run fed EVERYTHING, with transient faults on
        # the ingest and emit paths
        faults = ("@app:faults(journal='16384', "
                  "transfer.retry.scale='0.001', "
                  "ingest.put='transient:count=3', "
                  "emit.drain='transient:count=2')")
        got_a, got_b, rb, snap = run("c", faults, SOAK_LIMITS, sends)

        # exact shed accounting, per tenant, against the oracle
        assert rb.events_shed == sum(shed.values())
        assert rb.events_admitted == len(admitted)
        for sid in ("T0", "T1", "T2"):
            assert snap["streams"].get(sid, {}).get("shed", 0) == \
                shed.get(sid, 0)
        # admitted outputs bit-identical to the unthrottled reference
        assert len(ref_a) > 100 and len(ref_b) > 20
        assert _norm(got_a) == _norm(ref_a)
        assert _norm(got_b) == _norm(ref_b)


WD_APP = """
@app:name('wd{tag}') {faults}
@app:limits(watchdog='200 ms')
@async(buffer.size='64', batch.size.max='16')
define stream S (k long, v double);
@info(name='q') from S[v > 0.0] select k, v insert into OutS;
"""


class _Wedge:
    """Junction receiver whose BaseException kills the async worker
    mid-dispatch — batches journal and queue but never deliver, the
    exact wedge the watchdog exists to heal."""

    def receive(self, batch):
        raise SimulatedCrashError("wedged worker")


class TestWatchdog:
    pytestmark = pytest.mark.faults

    def test_wedge_heals_and_journal_tail_replays_bit_exactly(self):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(WD_APP.format(
                tag="h0", faults="@app:faults(journal='8192')"))
            got = []
            rt.add_callback("OutS", _collector(got))
            rt.start()
            rt.junctions["S"].subscribe(_Wedge())
            h = rt.get_input_handler("S")
            for i in range(1, 6):
                h.send([i, float(i)], timestamp=1000 + i)
            time.sleep(0.05)   # worker is dead by now
            for i in range(6, 11):
                h.send([i, float(i)], timestamp=1000 + i)
            rb = rt.app_context.robustness
            deadline = time.time() + 15
            while rb.watchdog_recoveries == 0 \
                    and rb.watchdog_recovery_failures == 0 \
                    and time.time() < deadline:
                time.sleep(0.05)
            time.sleep(0.3)    # let the post-heal dispatches settle
            assert rb.watchdog_trips >= 1
            assert rb.watchdog_recoveries == 1
            assert rb.watchdog_recovery_failures == 0
            # the tail keeps flowing through the rebuilt engines (the
            # cached InputHandler was re-pointed in place)
            for i in range(11, 16):
                h.send([i, float(i)], timestamp=1000 + i)
            time.sleep(0.3)
            expect = sorted((1000 + i, (i, float(i)))
                            for i in range(1, 16))
            assert sorted(got) == expect    # bit-identical, no dupes
            hd = rt.health()
            assert not hd["wedged"]
            assert hd["watchdog"]["recoveries"] == 1
            # the heal left a latency span on the live tracer
            tr = rt.app_context.tracer
            assert tr is not None
            assert tr.stage_stats().get("watchdog.heal", {}).get(
                "spans", 0) >= 1
            rt.shutdown()
        finally:
            m.shutdown()

    def test_heal_without_journal_is_refused_and_counted(self):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(WD_APP.format(
                tag="r0", faults=""))
            got = []
            rt.add_callback("OutS", _collector(got))
            rt.start()
            rt.junctions["S"].subscribe(_Wedge())
            h = rt.get_input_handler("S")
            for i in range(1, 6):
                h.send([i, float(i)], timestamp=1000 + i)
            time.sleep(0.05)   # worker is dead by now
            # a second wave piles up behind the dead worker: the queue
            # stays pending, which is what makes the stall visible
            for i in range(6, 11):
                h.send([i, float(i)], timestamp=1000 + i)
            rb = rt.app_context.robustness
            deadline = time.time() + 15
            while rb.watchdog_recovery_failures == 0 \
                    and time.time() < deadline:
                time.sleep(0.05)
            assert rb.watchdog_recovery_failures >= 1
            assert rb.watchdog_recoveries == 0
            hd = rt.health()
            assert hd["wedged"] and not hd["healthy"]
            rt.shutdown()
        finally:
            m.shutdown()


class TestCircuitBreaker:
    def setup_method(self):
        from siddhi_tpu.transport.broker import InMemoryBroker

        InMemoryBroker.clear()

    def test_state_machine_counts_every_transition(self):
        from siddhi_tpu.robustness import CircuitBreaker

        clock = [0.0]
        rb = RobustnessStats()
        b = CircuitBreaker("t", threshold=2, cooldown_ms=100, stats=rb,
                           clock=lambda: clock[0])
        assert b.allow() and b.state == "closed"
        b.record_failure()
        assert b.state == "closed"          # below threshold
        b.record_failure()
        assert b.state == "open" and rb.breaker_opens == 1
        assert not b.allow()                # short-circuited
        assert rb.breaker_short_circuits == 1
        clock[0] = 0.2                      # past cooldown
        assert b.allow()                    # half-open probe
        assert b.state == "half-open" and rb.breaker_half_opens == 1
        assert not b.allow()                # only ONE probe in flight
        b.record_failure()                  # probe failed -> reopen
        assert b.state == "open" and rb.breaker_opens == 2
        clock[0] = 0.4
        assert b.allow()
        assert b.record_success() is True   # this close flushes spools
        assert b.state == "closed" and rb.breaker_closes == 1
        assert b.record_success() is False  # already closed

    def test_open_breaker_spools_and_flushes_exactly_once(self):
        from siddhi_tpu.transport.broker import (
            FunctionSubscriber,
            InMemoryBroker,
        )

        m = SiddhiManager()
        sub = None
        try:
            rt = m.create_siddhi_app_runtime("""
@app:name('cb1')
@app:faults(sink.connect='conn:count=4')
@app:limits(breaker='2', breaker.cooldown='60 ms')
@sink(type='inMemory', topic='tcb1', retry.scale='0.004')
define stream S (k long, v double);
""")
            published = []
            sub = FunctionSubscriber("tcb1", published.append)
            InMemoryBroker.subscribe(sub)
            rt.start()
            sink = rt.sinks[0]
            rb = rt.app_context.robustness
            assert sink._breaker is not None
            # wait for the failed connects to OPEN the breaker
            deadline = time.time() + 10
            while rb.breaker_opens == 0 and time.time() < deadline:
                time.sleep(0.01)
            assert sink._breaker.state == "open"
            # everything sent while open spools — no publish attempts
            h = rt.get_input_handler("S")
            for i in range(4):
                h.send([i, float(i)], timestamp=1000 + i)
            assert rb.breaker_spooled_batches == 4
            assert len(published) == 0
            # cooldown elapses, the retry chain's probe connects, the
            # breaker closes and the spool flushes IN ORDER, exactly once
            deadline = time.time() + 10
            while (not sink.connected or sink._spool) \
                    and time.time() < deadline:
                time.sleep(0.01)
            time.sleep(0.05)
            assert sink.connected and sink._breaker.state == "closed"
            assert rb.breaker_closes >= 1
            assert rb.breaker_flushed_batches == 4
            assert rb.breaker_spool_dropped == 0
            assert [e.data[0] for e in published] == [0, 1, 2, 3]
            assert rb.breaker_short_circuits >= 1
            hd = rt.health()
            assert hd["breakers"] and \
                hd["breakers"][0]["state"] == "closed"
            rt.shutdown()
        finally:
            m.shutdown()
            if sub is not None:
                InMemoryBroker.unsubscribe(sub)

    def test_spool_overflow_evicts_oldest_and_counts(self):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime("""
@app:name('cb2')
@app:faults(sink.connect='conn:count=999')
@app:limits(breaker='1', breaker.cooldown='60 sec')
@sink(type='inMemory', topic='tcb2', retry.scale='0.0001')
define stream S (k long, v double);
""")
            rt.start()
            sink = rt.sinks[0]
            sink.attach_breaker(sink._breaker, spool_cap=2)  # tiny spool
            rb = rt.app_context.robustness
            deadline = time.time() + 10
            while rb.breaker_opens == 0 and time.time() < deadline:
                time.sleep(0.01)
            h = rt.get_input_handler("S")
            for i in range(5):
                h.send([i, float(i)], timestamp=1000 + i)
            assert len(sink._spool) == 2
            assert rb.breaker_spool_dropped == 3   # oldest 3 evicted
            rt.shutdown()
        finally:
            m.shutdown()

    def test_shutdown_flushes_deliverable_spool(self):
        """Regression (found by the barrier-flush-completeness rule):
        ``Sink.shutdown`` used to warn-and-drop batches still spooled
        behind the breaker even when the transport was up and the
        cooldown had elapsed — the shutdown barrier never reached a
        flush of the ``_spool`` queue it owns.  It now attempts one
        final breaker-gated flush before declaring the loss."""
        from siddhi_tpu.transport.broker import (
            FunctionSubscriber,
            InMemoryBroker,
        )

        m = SiddhiManager()
        sub = None
        try:
            rt = m.create_siddhi_app_runtime("""
@app:name('cb3')
@app:limits(breaker='2', breaker.cooldown='40 ms')
@sink(type='inMemory', topic='tcb3')
define stream S (k long, v double);
""")
            published = []
            sub = FunctionSubscriber("tcb3", published.append)
            InMemoryBroker.subscribe(sub)
            rt.start()
            sink = rt.sinks[0]
            assert sink.connected and sink._breaker is not None
            # trip the breaker while connected (publish-side failures)
            sink._breaker.record_failure()
            sink._breaker.record_failure()
            assert sink._breaker.is_open()
            h = rt.get_input_handler("S")
            for i in range(3):
                h.send([i, float(i)], timestamp=1000 + i)
            assert len(sink._spool) == 3 and published == []
            time.sleep(0.08)  # cooldown elapses; no further traffic
            rt.shutdown()
            # the final barrier flush delivered everything, in order
            assert [e.data[0] for e in published] == [0, 1, 2]
            assert not sink._spool
            rb = rt.app_context.robustness
            assert rb.breaker_flushed_batches == 3
        finally:
            m.shutdown()
            if sub is not None:
                InMemoryBroker.unsubscribe(sub)

    def test_half_open_flush_does_not_self_deadlock(self):
        """Regression (found by the lock-order-deadlock rule's
        reentrancy audit): flushing through a HALF-OPEN breaker closes
        it on the first successful publish, and
        ``publish_with_reconnect`` then re-enters ``_flush_spool`` on
        the same thread — with a non-reentrant ``_spool_lock`` that
        path self-deadlocked.  The lock is an RLock now; the nested
        flush drains the remainder and the outer loop exits empty."""
        import threading

        from siddhi_tpu.transport.broker import (
            FunctionSubscriber,
            InMemoryBroker,
        )

        m = SiddhiManager()
        sub = None
        try:
            rt = m.create_siddhi_app_runtime("""
@app:name('cb4')
@app:limits(breaker='2', breaker.cooldown='40 ms')
@sink(type='inMemory', topic='tcb4')
define stream S (k long, v double);
""")
            published = []
            sub = FunctionSubscriber("tcb4", published.append)
            InMemoryBroker.subscribe(sub)
            rt.start()
            sink = rt.sinks[0]
            sink._breaker.record_failure()
            sink._breaker.record_failure()
            assert sink._breaker.is_open()
            h = rt.get_input_handler("S")
            h.send([0, 0.0], timestamp=1000)
            h.send([1, 1.0], timestamp=1001)
            assert len(sink._spool) == 2
            time.sleep(0.08)  # past cooldown: next send probes half-open
            t = threading.Thread(
                target=lambda: h.send([2, 2.0], timestamp=1002),
                daemon=True)
            t.start()
            t.join(timeout=5)
            assert not t.is_alive(), (
                "send through a half-open breaker with a non-empty "
                "spool deadlocked in the nested flush")
            assert [e.data[0] for e in published] == [0, 1, 2]
            assert sink._breaker.state == "closed"
            rt.shutdown()
        finally:
            m.shutdown()
            if sub is not None:
                InMemoryBroker.unsubscribe(sub)


class TestRetryShutdownRace:
    def test_arm_after_shutdown_is_a_gated_noop(self):
        """Regression: a connect failure racing ``shutdown()`` used to
        arm a fresh backoff Timer AFTER ``_shutdown_retry()`` had
        cancelled the old one — a zombie firing into a dead (or worse,
        restarted) transport.  The arm is now gated on ``_shutdown``
        under ``_retry_lock``."""
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@sink(type='inMemory', topic='trace1') "
                "define stream S (k long, v double);")
            rt.start()
            sink = rt.sinks[0]
            sink._shutdown_retry()
            # the racing failure path tries to arm the next interval
            with sink._retry_lock:
                sink._retrying = True
            sink._arm_retry_timer(60_000)
            assert sink._retry_timer is None      # no zombie armed
            assert sink._retrying is False        # chain marked dead
            # and the mixin stays restartable
            sink.start()
            assert sink.connected
            rt.shutdown()
        finally:
            m.shutdown()


class TestDegradationLadder:
    def _fake_runtime(self, **flags):
        attrs = dict(name="fake", degrade_level=0, plan_pins={},
                     statistics_manager=None, kernels=False,
                     devtables=False, fuse=False)
        attrs.update(flags)
        ctx = types.SimpleNamespace(**attrs)
        rt = types.SimpleNamespace(app_context=ctx, replans=[])
        rt.replan = lambda pins, forced=True, reason="": \
            rt.replans.append((dict(pins), reason))
        return rt

    def test_apply_degradation_demotes_in_documented_order(self):
        ctx = types.SimpleNamespace(kernels=True, devtables=True,
                                    fuse=True)
        assert apply_degradation(ctx, 2) == ["kernels", "devtables"]
        assert (ctx.kernels, ctx.devtables, ctx.fuse) == \
            (False, False, True)
        # only ENABLED features count as rungs
        ctx2 = types.SimpleNamespace(kernels=False, devtables=False,
                                     fuse=True)
        assert apply_degradation(ctx2, 1) == ["fuse"]
        assert DEMOTE_ORDER == ("kernels", "devtables", "fuse")

    def test_hysteresis_demote_then_promote(self):
        rt = self._fake_runtime(fuse=True)
        ladder = DegradationLadder(rt, RobustnessStats(), dwell=3)
        assert ladder.features == ["fuse"]
        assert not ladder.observe(1.0) and not ladder.observe(1.0)
        assert ladder.observe(1.0)            # 3rd hot tick: demote
        assert ladder.level == 1
        assert ladder.stats.ladder_demotions == 1
        # mid-band pressure resets BOTH streaks (no flip-flop)
        ladder.observe(0.5)
        for _ in range(5):
            assert not ladder.observe(0.0)
        assert ladder.observe(0.0)            # 6th cool tick: promote
        assert ladder.level == 0
        assert ladder.stats.ladder_promotions == 1
        assert len(rt.replans) == 2

    def test_rungs_survive_a_degraded_rebuild(self):
        """A context rebuilt at level 1 reads ``fuse=False`` — the
        ``degraded_features`` record is what keeps the consumed rung on
        the rebuilt ladder's list so it can still re-promote."""
        rt = self._fake_runtime(fuse=False, degrade_level=1,
                                degraded_features=("fuse",))
        ladder = DegradationLadder(rt, RobustnessStats(), dwell=1)
        assert ladder.features == ["fuse"] and ladder.level == 1
        assert not ladder.observe(0.0)
        assert ladder.observe(0.0)            # 2*dwell cool: promote
        assert rt.replans and rt.app_context.degrade_level == 0

    def test_zero_rung_ladder_is_inert(self):
        rt = self._fake_runtime()
        ladder = DegradationLadder(rt, RobustnessStats())
        for _ in range(20):
            assert not ladder.observe(1.0)
        assert rt.replans == []

    def test_real_demote_and_promote_stay_bit_identical(self):
        """Integration: the ladder's forced replans ride the same
        restore-and-replay protocol — fused → device → fused mid-stream
        with outputs identical to an uninterrupted run."""
        app = """
@app:name('ld{tag}') @app:playback @app:execution('tpu') @app:fuse
@app:faults(journal='8192')
{limits}
define stream SIn (sym int, price float, vol int);
@info(name='q1') from SIn[price > 10.0]
select sym, price, vol insert into Mid;
@info(name='q2') from Mid[vol > 50] select sym, price insert into Out;
"""
        rng = np.random.default_rng(7)
        sends = [([int(rng.integers(0, 5)),
                   float(np.float32(rng.uniform(0, 30))),
                   int(rng.integers(1, 100))], 1000 + 3 * i)
                 for i in range(300)]

        def run(tag, limits, steps=None):
            m = SiddhiManager()
            try:
                rt = m.create_siddhi_app_runtime(
                    app.format(tag=tag, limits=limits))
                got = []
                rt.add_callback("Out", _collector(got))
                rt.start()
                h = rt.get_input_handler("SIn")
                lows = []
                for i, (row, ts) in enumerate(sends):
                    if steps and i in steps:
                        ladder = rt._ladder
                        assert ladder is not None
                        pressure, ticks = steps[i]
                        for _ in range(ticks):
                            ladder.observe(pressure)
                        lows.append(dict(rt.lowering()))
                        h = rt.get_input_handler("SIn")
                    h.send(list(row), timestamp=ts)
                rb = rt.app_context.robustness
                rt.shutdown()
                return got, lows, rb
            finally:
                m.shutdown()

        ref, _, _ = run("r", "")
        # watchdog interval 15s: its own ticks never interfere here
        limits = "@app:limits(watchdog='60 sec', ladder='true')"
        got, lows, rb = run("s", limits, steps={
            100: (1.0, 3),   # 3 hot ticks -> demote fuse
            200: (0.0, 6),   # 6 cool ticks -> promote back
        })
        assert lows == [{"q1": "device", "q2": "device"},
                        {"q1": "fused", "q2": "fused"}]
        assert rb.ladder_demotions == 1 and rb.ladder_promotions == 1
        assert len(ref) > 0
        assert got == ref


class TestHealthEndpoint:
    def test_health_rest_matches_statistics_feed(self):
        from siddhi_tpu.service import SiddhiService

        svc = SiddhiService()
        svc.start()
        base = f"http://127.0.0.1:{svc.port}"
        try:
            # the per-request socket timeout satellite is on the handler
            assert svc._server.RequestHandlerClass.timeout == 10
            app = """
@app:name('hrest') @app:playback
@app:limits(rate='5/s', burst='5', shed='drop')
define stream S (k long, v double);
@info(name='q') from S[v > 0.0] select k, v insert into OutS;
"""
            req = urllib.request.Request(
                f"{base}/siddhi-artifact-deploy", data=app.encode(),
                method="POST")
            with urllib.request.urlopen(req) as r:
                assert r.status == 200
            with urllib.request.urlopen(
                    f"{base}/siddhi-health/hrest") as r:
                doc = json.loads(r.read())
            assert r.status == 200 and doc["status"] == "OK"
            assert doc["healthy"] and not doc["shedding"]

            # push past the budget: shedding -> 503 with a JSON body
            rt = svc.manager.get_siddhi_app_runtime("hrest")
            h = rt.get_input_handler("S")
            for i in range(12):
                h.send([i, 1.0], timestamp=1_000_000 + i)
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(f"{base}/siddhi-health/hrest")
            assert e.value.code == 503
            doc = json.loads(e.value.read())
            assert doc["status"] == "UNHEALTHY" and doc["shedding"]
            assert doc["counters"]["events_shed"] == 7
            # the REST counters ARE the statistics feed's counters
            st = rt.statistics()
            key = ("io.siddhi.SiddhiApps.hrest.Siddhi."
                   "Robustness.overload.events_shed")
            assert st[key] == doc["counters"]["events_shed"]
            # lock-taking ops answer 503-overloaded instead of queueing
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(
                    f"{base}/siddhi-pattern-state/hrest")
            assert e.value.code == 503
            assert json.loads(e.value.read())["status"] == "ERROR"

            # unknown app -> 404
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(f"{base}/siddhi-health/ghost")
            assert e.value.code == 404

            # window passes -> healthy again, ops unblocked
            time.sleep(1.1)
            with urllib.request.urlopen(
                    f"{base}/siddhi-health/hrest") as r:
                assert json.loads(r.read())["healthy"]
            with urllib.request.urlopen(
                    f"{base}/siddhi-pattern-state/hrest") as r:
                assert r.status == 200
        finally:
            svc.stop()

    def test_manager_wide_rollup(self):
        m = SiddhiManager()
        try:
            m.create_siddhi_app_runtime(
                "@app:name('ra') define stream S (k long);")
            m.create_siddhi_app_runtime(
                "@app:name('rb') @app:limits(rate='5/s') "
                "define stream S (k long);")
            hd = m.health()
            assert set(hd) == {"ra", "rb"}
            assert hd["ra"]["admission"] is None
            assert hd["rb"]["admission"]["rate_per_s"] == 5.0
        finally:
            m.shutdown()
