"""Failure-routing conformance: @OnError LOG/STREAM fault streams,
exception listeners, and error isolation between receivers — the
behavioral contract of the reference's StreamJunction.handleError
(stream/StreamJunction.java:368-430) and fault-stream definitions
(`!streamName` consuming queries, SiddhiAppParser.java:364-368).
"""

import pytest

from siddhi_tpu import SiddhiManager


def boom(v):
    raise RuntimeError("boom")


class TestOnErrorStream:
    APP = (
        "@OnError(action='STREAM') "
        "define stream S (k string, v double); "
        "@info(name='q') from S select k, custom:boom(v) as x "
        "insert into O; "
        "@info(name='qf') from !S select k, v insert into FaultOut; "
    )

    def _manager(self):
        m = SiddhiManager()
        m.set_extension("custom:boom", boom, kind="function")
        return m

    def test_failing_event_routes_to_fault_stream(self):
        m = self._manager()
        try:
            rt = m.create_siddhi_app_runtime(self.APP)
            ok, fault = [], []
            rt.add_callback("O", lambda evs: ok.extend(list(e.data) for e in evs))
            rt.add_callback("FaultOut",
                            lambda evs: fault.extend(list(e.data) for e in evs))
            rt.start()
            rt.get_input_handler("S").send(["a", 1.0])
            rt.shutdown()
            assert ok == []
            assert fault == [["a", 1.0]]  # original payload preserved
        finally:
            m.shutdown()

    def test_fault_stream_exposes_error_column(self):
        app = self.APP.replace(
            "from !S select k, v insert into FaultOut;",
            "from !S select k, _error insert into FaultOut;")
        m = self._manager()
        try:
            rt = m.create_siddhi_app_runtime(app)
            fault = []
            rt.add_callback("FaultOut",
                            lambda evs: fault.extend(list(e.data) for e in evs))
            rt.start()
            rt.get_input_handler("S").send(["a", 1.0])
            rt.shutdown()
            assert len(fault) == 1
            k, err = fault[0]
            assert k == "a" and isinstance(err, RuntimeError)
        finally:
            m.shutdown()

    def test_healthy_queries_unaffected_by_failing_sibling(self):
        app = self.APP + "@info(name='q2') from S select v insert into OK2; "
        m = self._manager()
        try:
            rt = m.create_siddhi_app_runtime(app)
            ok2 = []
            rt.add_callback("OK2", lambda evs: ok2.extend(list(e.data) for e in evs))
            rt.start()
            rt.get_input_handler("S").send(["a", 7.0])
            rt.shutdown()
            assert ok2 == [[7.0]]  # the sibling query still ran
        finally:
            m.shutdown()


class TestOnErrorLog:
    def test_log_mode_notifies_exception_listeners(self):
        app = (
            "define stream S (k string, v double); "
            "@info(name='q') from S select custom:boom(v) as x "
            "insert into O; ")
        m = SiddhiManager()
        m.set_extension("custom:boom", boom, kind="function")
        try:
            rt = m.create_siddhi_app_runtime(app)
            seen = []
            rt.add_exception_listener(seen.append)
            rt.start()
            rt.get_input_handler("S").send(["a", 1.0])
            rt.shutdown()
            assert len(seen) == 1 and isinstance(seen[0], RuntimeError)
        finally:
            m.shutdown()

    def test_processing_continues_after_logged_error(self):
        app = (
            "define stream S (k string, v double); "
            "@info(name='q') from S[v > 0.0] "
            "select custom:boom(v) as x insert into O; "
            "@info(name='q2') from S select v insert into OK2; ")
        m = SiddhiManager()
        m.set_extension("custom:boom", boom, kind="function")
        try:
            rt = m.create_siddhi_app_runtime(app)
            ok2 = []
            rt.add_callback("OK2", lambda evs: ok2.extend(list(e.data) for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            h.send(["a", 1.0])
            h.send(["b", 2.0])
            rt.shutdown()
            assert ok2 == [[1.0], [2.0]]
        finally:
            m.shutdown()


class TestSinkPublishFaults:
    """Publish failures follow the sink stream's @OnError contract
    (reference: Sink.onError:354 + FaultStreamTestCase.java:604-943,
    the sink-failure variants)."""

    def _failing_sink(self, manager):
        from siddhi_tpu.core.exceptions import ConnectionUnavailableError
        from siddhi_tpu.transport.sink import Sink

        class FailSink(Sink):
            def publish(self, payload):
                raise ConnectionUnavailableError("transport down")

        manager.set_extension("alwaysFail", FailSink, kind="sink")

    def test_stream_action_routes_failed_event(self):
        m = SiddhiManager()
        try:
            self._failing_sink(m)
            rt = m.create_siddhi_app_runtime(
                "define stream S (v long); "
                "@OnError(action='STREAM') "
                "@sink(type='alwaysFail', topic='t', "
                "retry.scale='100000', @map(type='passThrough')) "
                "define stream Out (v long); "
                "from S select v insert into Out; "
                "from !Out select v, _error insert into FaultOut;")
            got = []
            rt.add_callback("FaultOut", lambda evs: got.extend(
                e.data for e in evs))
            rt.start()
            rt.get_input_handler("S").send([7])
            rt.get_input_handler("S").send([8])
            rt.shutdown()
            assert [g[0] for g in got] == [7, 8]
            assert "transport down" in str(got[0][1])
        finally:
            m.shutdown()

    def test_log_action_drops_and_keeps_flowing(self, caplog):
        import logging

        m = SiddhiManager()
        try:
            self._failing_sink(m)
            rt = m.create_siddhi_app_runtime(
                "define stream S (v long); "
                "@sink(type='alwaysFail', topic='t', "
                "retry.scale='100000', @map(type='passThrough')) "
                "define stream Out (v long); "
                "from S select v insert into Out;")
            got = []
            rt.add_callback("Out", lambda evs: got.extend(
                e.data for e in evs))
            rt.start()
            with caplog.at_level(logging.ERROR):
                rt.get_input_handler("S").send([1])
                rt.get_input_handler("S").send([2])
            rt.shutdown()
            # in-process callbacks still see the events; only the
            # transport drop is logged
            assert got == [[1], [2]]
            assert any("failed to publish" in r.getMessage()
                       for r in caplog.records)
        finally:
            m.shutdown()
