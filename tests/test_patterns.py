"""Pattern/sequence conformance tests.

Event sequences and expected match counts/values transcribed from the
reference TestNG corpus: query/pattern/EveryPatternTestCase.java,
CountPatternTestCase.java, LogicalPatternTestCase.java,
query/sequence/SequenceTestCase.java — same behavioral contracts, run
against the TPU engine.
"""

import pytest

from siddhi_tpu import SiddhiManager

S12 = (
    "define stream Stream1 (symbol string, price float, volume int); "
    "define stream Stream2 (symbol string, price float, volume int); "
)


@pytest.fixture
def manager():
    m = SiddhiManager()
    yield m
    m.shutdown()


def run(manager, app, sends, out="OutputStream"):
    """sends: list of (stream, row). Returns collected output events."""
    rt = manager.create_siddhi_app_runtime(app)
    got = []
    rt.add_callback(out, lambda evs: got.extend(evs))
    rt.start()
    for stream, row in sends:
        rt.get_input_handler(stream).send(row)
    rt.shutdown()
    return got


class TestPatterns:
    def test_simple_pattern(self, manager):
        # EveryPatternTestCase.testQuery1
        app = S12 + (
            "@info(name='query1') "
            "from e1=Stream1[price>20] -> e2=Stream2[price>e1.price] "
            "select e1.symbol as symbol1, e2.symbol as symbol2 insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["WSO2", 55.6, 100]),
            ("Stream2", ["IBM", 55.7, 100]),
        ])
        assert [e.data for e in got] == [["WSO2", "IBM"]]

    def test_non_every_ignores_middle_event(self, manager):
        # EveryPatternTestCase.testQuery2: extra non-continuing event ignored
        app = S12 + (
            "from e1=Stream1[price>20] -> e2=Stream2[price>e1.price] "
            "select e1.symbol as symbol1, e2.symbol as symbol2 insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["WSO2", 55.6, 100]),
            ("Stream1", ["GOOG", 55.6, 100]),
            ("Stream2", ["IBM", 55.7, 100]),
        ])
        assert [e.data for e in got] == [["WSO2", "IBM"]]

    def test_non_every_single_match(self, manager):
        # after a match, non-every patterns stop
        app = S12 + (
            "from e1=Stream1[price>20] -> e2=Stream2[price>e1.price] "
            "select e1.symbol as s1, e2.symbol as s2 insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["A", 55.6, 100]),
            ("Stream2", ["B", 57.7, 100]),
            ("Stream1", ["C", 55.6, 100]),
            ("Stream2", ["D", 57.7, 100]),
        ])
        assert [e.data for e in got] == [["A", "B"]]

    def test_every_overlapping(self, manager):
        # EveryPatternTestCase.testQuery3: overlapping instances both match
        app = S12 + (
            "from every e1=Stream1[price>20] -> e2=Stream2[price>e1.price] "
            "select e1.symbol as s1, e2.symbol as s2 insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["WSO2", 55.6, 100]),
            ("Stream1", ["GOOG", 55.6, 100]),
            ("Stream2", ["IBM", 55.7, 100]),
        ])
        assert sorted(e.data[0] for e in got) == ["GOOG", "WSO2"]
        assert len(got) == 2

    def test_every_group_non_overlapping(self, manager):
        # EveryPatternTestCase.testQuery4: every (e1->e3) -> e2
        app = S12 + (
            "from every (e1=Stream1[price>20] -> e3=Stream1[price>20]) -> e2=Stream2[price>e1.price] "
            "select e1.symbol as s1, e3.symbol as s3, e2.symbol as s2 insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["WSO2", 55.6, 100]),
            ("Stream1", ["GOOG", 54.0, 100]),
            ("Stream2", ["IBM", 57.7, 100]),
        ])
        assert [e.data for e in got] == [["WSO2", "GOOG", "IBM"]]

    def test_every_group_two_pairs(self, manager):
        # EveryPatternTestCase.testQuery5
        app = S12 + (
            "from every (e1=Stream1[price>20] -> e3=Stream1[price>20]) -> e2=Stream2[price>e1.price] "
            "select e1.symbol as s1, e3.symbol as s3, e2.symbol as s2 insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["WSO2", 55.6, 100]),
            ("Stream1", ["GOOG", 54.0, 100]),
            ("Stream1", ["WSO2", 53.6, 100]),
            ("Stream1", ["GOOG", 53.0, 100]),
            ("Stream2", ["IBM", 57.7, 100]),
        ])
        assert len(got) == 2

    def test_whole_pattern_every_group(self, manager):
        # EveryPatternTestCase.testQuery7: every (e1 -> e3), no suffix
        app = S12 + (
            "from every (e1=Stream1[price>20] -> e3=Stream1[price>20]) "
            "select e1.symbol as s1, e3.symbol as s3 insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["MSFT", 55.6, 100]),
            ("Stream1", ["WSO2", 57.6, 100]),
            ("Stream1", ["GOOG", 54.0, 100]),
            ("Stream1", ["WSO2", 53.6, 100]),
        ])
        assert [e.data for e in got] == [["MSFT", "WSO2"], ["GOOG", "WSO2"]]

    def test_every_single_state(self, manager):
        # EveryPatternTestCase.testQuery8
        app = S12 + (
            "from every e1=Stream1[price>20] "
            "select e1.symbol as s1 insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["MSFT", 55.6, 100]),
            ("Stream1", ["WSO2", 57.6, 100]),
        ])
        assert [e.data for e in got] == [["MSFT"], ["WSO2"]]

    def test_prefix_then_every_group(self, manager):
        # EveryPatternTestCase.testQuery6: e4 -> every (e1->e3) -> e2
        app = S12 + (
            "from e4=Stream1[symbol=='MSFT'] -> every (e1=Stream1[price>20] -> e3=Stream1[price>20]) "
            "-> e2=Stream2[price>e1.price] "
            "select e4.symbol as s4, e1.symbol as s1, e3.symbol as s3, e2.symbol as s2 "
            "insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["MSFT", 55.6, 100]),
            ("Stream1", ["WSO2", 55.7, 100]),
            ("Stream1", ["GOOG", 54.0, 100]),
            ("Stream1", ["WSO2", 53.6, 100]),
            ("Stream1", ["GOOG", 53.0, 100]),
            ("Stream2", ["IBM", 57.7, 100]),
        ])
        assert len(got) == 2
        assert all(e.data[0] == "MSFT" for e in got)


class TestCountPatterns:
    APP = S12.replace("symbol string, price float, volume int", "price float, volume int", 1)

    def test_count_greedy(self, manager):
        # CountPatternTestCase.testQuery1: <2:5>, failing event ignored,
        # greedy capture, single match with all captures
        app = (
            "define stream Stream1 (symbol string, price float, volume int); "
            "define stream Stream2 (symbol string, price float, volume int); "
            "from e1=Stream1[price>20]<2:5> -> e2=Stream2[price>20] "
            "select e1[0].price as p0, e1[1].price as p1, e1[2].price as p2, "
            "e1[3].price as p3, e2.price as p4 insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["WSO2", 25.6, 100]),
            ("Stream1", ["GOOG", 47.6, 100]),
            ("Stream1", ["GOOG", 13.7, 100]),
            ("Stream1", ["GOOG", 47.8, 100]),
            ("Stream2", ["IBM", 45.7, 100]),
            ("Stream2", ["IBM", 55.7, 100]),
        ])
        assert len(got) == 1
        d = got[0].data
        assert d[0] == pytest.approx(25.6, abs=1e-4)
        assert d[1] == pytest.approx(47.6, abs=1e-4)
        assert d[2] == pytest.approx(47.8, abs=1e-4)
        assert d[3] is None
        assert d[4] == pytest.approx(45.7, abs=1e-4)

    def test_count_min_not_reached(self, manager):
        # CountPatternTestCase.testQuery3-style: e2 event before min ignored
        app = S12 + (
            "from e1=Stream1[price>20]<2:5> -> e2=Stream2[price>20] "
            "select e1[0].price as p0, e1[1].price as p1, e2.price as p2 "
            "insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["WSO2", 25.6, 100]),
            ("Stream2", ["IBM", 45.7, 100]),
            ("Stream1", ["GOOG", 47.8, 100]),
            ("Stream2", ["IBM", 55.7, 100]),
        ])
        assert len(got) == 1
        assert got[0].data[0] == pytest.approx(25.6, abs=1e-4)
        assert got[0].data[1] == pytest.approx(47.8, abs=1e-4)
        assert got[0].data[2] == pytest.approx(55.7, abs=1e-4)

    def test_count_none_when_min_unmet(self, manager):
        # CountPatternTestCase.testQuery4: 0 matches
        app = S12 + (
            "from e1=Stream1[price>20]<2:5> -> e2=Stream2[price>20] "
            "select e1[0].price as p0, e2.price as p2 insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["WSO2", 25.6, 100]),
            ("Stream2", ["IBM", 45.7, 100]),
        ])
        assert got == []

    def test_count_max_cap(self, manager):
        # CountPatternTestCase.testQuery5: capture capped at 5
        app = S12 + (
            "from e1=Stream1[price>20]<2:5> -> e2=Stream2[price>20] "
            "select e1[0].price as p0, e1[4].price as p4, e2.price as pe "
            "insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["WSO2", 25.6, 100]),
            ("Stream1", ["GOOG", 47.6, 100]),
            ("Stream1", ["GOOG", 23.7, 100]),
            ("Stream1", ["GOOG", 24.7, 100]),
            ("Stream1", ["GOOG", 25.7, 100]),
            ("Stream1", ["WSO2", 27.6, 100]),
            ("Stream2", ["IBM", 45.7, 100]),
            ("Stream1", ["GOOG", 47.8, 100]),
            ("Stream2", ["IBM", 55.7, 100]),
        ])
        assert len(got) == 1
        assert got[0].data[0] == pytest.approx(25.6, abs=1e-4)
        assert got[0].data[1] == pytest.approx(25.7, abs=1e-4)

    def test_count_cross_state_index_filter(self, manager):
        # CountPatternTestCase.testQuery6: e2 filter references e1[1]
        app = S12 + (
            "from e1=Stream1[price>20]<2:5> -> e2=Stream2[price>e1[1].price] "
            "select e1[0].price as p0, e1[1].price as p1, e2.price as p2 "
            "insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["WSO2", 25.6, 100]),
            ("Stream1", ["GOOG", 47.6, 100]),
            ("Stream2", ["IBM", 45.7, 100]),
            ("Stream2", ["IBM", 55.7, 100]),
        ])
        assert len(got) == 1
        assert got[0].data[2] == pytest.approx(55.7, abs=1e-4)

    def test_trailing_optional_count_via_next(self, manager):
        # CountPatternTestCase.testQuery2-style: zero-count middle state
        app = (
            "define stream EventStream (symbol string, price float, volume int); "
            "from e1=EventStream[price >= 50 and volume > 100] -> "
            "e2=EventStream[price <= 40]<0:5> -> e3=EventStream[volume <= 70] "
            "select e1.symbol as s1, e2[0].symbol as s2, e3.symbol as s3 "
            "insert into OutputStream;"
        )
        got = run(manager, app, [
            ("EventStream", ["IBM", 75.6, 105]),
            ("EventStream", ["GOOG", 21.0, 61]),
        ])
        assert len(got) == 1
        assert got[0].data == ["IBM", None, "GOOG"]


class TestLogicalPatterns:
    def test_and_pattern(self, manager):
        app = S12 + (
            "from e1=Stream1[price>20] and e2=Stream2[price>20] "
            "select e1.symbol as s1, e2.symbol as s2 insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream2", ["IBM", 45.7, 100]),
            ("Stream1", ["WSO2", 55.6, 100]),
        ])
        assert [e.data for e in got] == [["WSO2", "IBM"]]

    def test_or_pattern(self, manager):
        app = S12 + (
            "from e1=Stream1[price>20] or e2=Stream2[price>20] "
            "select e1.symbol as s1, e2.symbol as s2 insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream2", ["IBM", 45.7, 100]),
        ])
        assert len(got) == 1
        assert got[0].data == [None, "IBM"]

    def test_and_then_next(self, manager):
        app = S12 + (
            "from e1=Stream1[price>20] and e2=Stream2[price>20] -> e3=Stream1[price>e1.price] "
            "select e1.symbol as s1, e2.symbol as s2, e3.symbol as s3 insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["A", 50.0, 100]),
            ("Stream2", ["B", 45.7, 100]),
            ("Stream1", ["C", 55.6, 100]),
        ])
        assert [e.data for e in got] == [["A", "B", "C"]]


class TestWithin:
    def test_within_expires(self, manager):
        app = S12 + (
            "from every e1=Stream1[price>20] -> e2=Stream2[price>e1.price] within 1 sec "
            "select e1.symbol as s1, e2.symbol as s2 insert into OutputStream;"
        )
        rt = manager.create_siddhi_app_runtime(app)
        got = []
        rt.add_callback("OutputStream", lambda evs: got.extend(evs))
        rt.start()
        h1, h2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
        h1.send(["WSO2", 55.6, 100], timestamp=1000)
        h2.send(["IBM", 55.7, 100], timestamp=2500)  # too late
        h1.send(["GOOG", 55.6, 100], timestamp=3000)
        h2.send(["IBM2", 55.7, 100], timestamp=3500)  # in time
        rt.shutdown()
        assert [e.data for e in got] == [["GOOG", "IBM2"]]


class TestSequences:
    def test_simple_sequence(self, manager):
        # SequenceTestCase.testQuery1
        app = S12 + (
            "from e1=Stream1[price>20], e2=Stream2[price>e1.price] "
            "select e1.symbol as s1, e2.symbol as s2 insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["WSO2", 55.6, 100]),
            ("Stream2", ["IBM", 55.7, 100]),
        ])
        assert [e.data for e in got] == [["WSO2", "IBM"]]

    def test_strict_continuity_restart(self, manager):
        # SequenceTestCase.testQuery2: interrupting event kills + restarts
        app = S12 + (
            "from every e1=Stream1[price>20], e2=Stream2[price>e1.price] "
            "select e1.symbol as s1, e2.symbol as s2 insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["WSO2", 55.6, 100]),
            ("Stream1", ["GOOG", 57.6, 100]),
            ("Stream2", ["IBM", 65.7, 100]),
        ])
        assert [e.data for e in got] == [["GOOG", "IBM"]]

    def test_trailing_star_immediate(self, manager):
        # SequenceTestCase.testQuery3: trailing * emits immediately
        app = S12 + (
            "from every e1=Stream1[price>20], e2=Stream2[price>e1.price]* "
            "select e1.symbol as s1, e2[0].symbol as s2, e2[1].symbol as s3 "
            "insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["WSO2", 55.6, 100]),
            ("Stream1", ["IBM", 55.7, 100]),
        ])
        assert len(got) == 2
        assert got[0].data == ["WSO2", None, None]
        assert got[1].data == ["IBM", None, None]

    def test_star_collects(self, manager):
        # SequenceTestCase.testQuery4
        app = S12 + (
            "from every e1=Stream2[price>20]*, e2=Stream1[price>e1[0].price] "
            "select e1[0].price as p1, e1[1].price as p2, e2.price as p3 "
            "insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["WSO2", 59.6, 100]),
            ("Stream2", ["WSO2", 55.6, 100]),
            ("Stream2", ["IBM", 55.7, 100]),
            ("Stream1", ["WSO2", 57.6, 100]),
        ])
        assert len(got) == 1
        assert got[0].data[0] == pytest.approx(55.6, abs=1e-4)
        assert got[0].data[1] == pytest.approx(55.7, abs=1e-4)
        assert got[0].data[2] == pytest.approx(57.6, abs=1e-4)

    def test_optional_one(self, manager):
        # SequenceTestCase.testQuery6: `?` keeps at most one
        app = S12 + (
            "from every e1=Stream2[price>20]?, e2=Stream1[price>e1[0].price] "
            "select e1[0].price as p1, e2.price as p3 insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["WSO2", 59.6, 100]),
            ("Stream2", ["WSO2", 55.6, 100]),
            ("Stream2", ["IBM", 55.7, 100]),
            ("Stream1", ["WSO2", 57.6, 100]),
        ])
        assert len(got) == 1
        assert got[0].data[0] == pytest.approx(55.7, abs=1e-4)

    def test_or_sequence(self, manager):
        # SequenceTestCase.testQuery7
        app = S12 + (
            "from every e1=Stream2[price>20], e2=Stream2[price>e1.price] or e3=Stream2[symbol=='IBM'] "
            "select e1.price as p1, e2.price as p2, e3.price as p3 insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream2", ["WSO2", 59.6, 100]),
            ("Stream2", ["WSO2", 55.6, 100]),
            ("Stream2", ["IBM", 55.7, 100]),
            ("Stream2", ["WSO2", 57.6, 100]),
        ])
        assert len(got) == 2

    def test_or_sequence_absent_branch(self, manager):
        # SequenceTestCase.testQuery8: e3 branch matches on symbol
        app = S12 + (
            "from every e1=Stream2[price>20], e2=Stream2[price>e1.price] or e3=Stream2[symbol=='IBM'] "
            "select e1.price as p1, e2.price as p2, e3.price as p3 insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream2", ["WSO2", 59.6, 100]),
            ("Stream2", ["WSO2", 55.6, 100]),
            ("Stream2", ["IBM", 55.0, 100]),
            ("Stream2", ["WSO2", 57.6, 100]),
        ])
        assert len(got) == 2

    def test_plus_sequence(self, manager):
        # SequenceTestCase.testQuery10
        app = S12 + (
            "from every e1=Stream2[price>20]+, e2=Stream1[price>e1[0].price] "
            "select e1[0].price as p1, e1[1].price as p2, e2.price as p3 "
            "insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["WSO2", 59.6, 100]),
            ("Stream2", ["WSO2", 55.6, 100]),
            ("Stream1", ["WSO2", 57.6, 100]),
        ])
        assert len(got) == 1
        assert got[0].data[0] == pytest.approx(55.6, abs=1e-4)
        assert got[0].data[1] is None
        assert got[0].data[2] == pytest.approx(57.6, abs=1e-4)

    def test_peak_detection(self, manager):
        # SequenceTestCase.testQuery11: classic peak via e2[last] filter
        app = (
            "define stream Stream1 (symbol string, price float, volume int); "
            "from every e1=Stream1[price>20], "
            "e2=Stream1[(e2[last].price is null and price>=e1.price) or "
            "((not (e2[last].price is null)) and price>=e2[last].price)]+, "
            "e3=Stream1[price<e2[last].price] "
            "select e1.price as p1, e2[0].price as p2, e2[1].price as p3, e3.price as p4 "
            "insert into OutputStream;"
        )
        got = run(manager, app, [
            ("Stream1", ["WSO2", 29.6, 100]),
            ("Stream1", ["WSO2", 35.6, 100]),
            ("Stream1", ["WSO2", 57.6, 100]),
            ("Stream1", ["IBM", 47.6, 100]),
        ])
        assert len(got) == 1
        d = got[0].data
        assert d[0] == pytest.approx(29.6, abs=1e-4)
        assert d[1] == pytest.approx(35.6, abs=1e-4)
        assert d[2] == pytest.approx(57.6, abs=1e-4)
        assert d[3] == pytest.approx(47.6, abs=1e-4)


class TestAbsentPatterns:
    """Expectations from query/pattern/absent/AbsentWithEveryPatternTestCase."""

    def test_absent_fires_after_timeout(self, manager):
        import time

        app = S12 + (
            "from every e1=Stream1[price>20] -> not Stream2[price>e1.price] for 100 millisec "
            "select e1.symbol as s1 insert into OutputStream;"
        )
        rt = manager.create_siddhi_app_runtime(app)
        got = []
        rt.add_callback("OutputStream", lambda evs: got.extend(evs))
        rt.start()
        h1 = rt.get_input_handler("Stream1")
        h1.send(["WSO2", 55.6, 100])
        time.sleep(0.02)
        h1.send(["GOOG", 55.6, 100])
        time.sleep(0.4)
        rt.shutdown()
        assert sorted(e.data[0] for e in got) == ["GOOG", "WSO2"]

    def test_absent_suppressed_by_event(self, manager):
        import time

        app = S12 + (
            "from every e1=Stream1[price>20] -> not Stream2[price>e1.price] for 100 millisec "
            "select e1.symbol as s1 insert into OutputStream;"
        )
        rt = manager.create_siddhi_app_runtime(app)
        got = []
        rt.add_callback("OutputStream", lambda evs: got.extend(evs))
        rt.start()
        rt.get_input_handler("Stream1").send(["WSO2", 55.6, 100])
        rt.get_input_handler("Stream1").send(["GOOG", 55.6, 100])
        rt.get_input_handler("Stream2").send(["IBM", 55.7, 100])  # kills both
        time.sleep(0.4)
        rt.shutdown()
        assert got == []

    def test_absent_then_more_states(self, manager):
        import time

        app = S12 + (
            "define stream Stream3 (symbol string, price float, volume int); "
            "from every e1=Stream1[price>20] -> not Stream2[price>e1.price] for 100 millisec "
            "-> e3=Stream3[price>20] "
            "select e1.symbol as s1, e3.symbol as s3 insert into OutputStream;"
        )
        rt = manager.create_siddhi_app_runtime(app)
        got = []
        rt.add_callback("OutputStream", lambda evs: got.extend(evs))
        rt.start()
        rt.get_input_handler("Stream1").send(["WSO2", 55.6, 100])
        rt.get_input_handler("Stream1").send(["GOOG", 55.6, 100])
        time.sleep(0.4)
        rt.get_input_handler("Stream3").send(["IBM", 55.7, 100])
        rt.shutdown()
        assert sorted(e.data for e in got) == [["GOOG", "IBM"], ["WSO2", "IBM"]]

    def test_leading_absent(self, manager):
        import time

        app = S12 + (
            "from not Stream1[price>10] for 100 millisec -> every e2=Stream2[price>20] "
            "select e2.symbol as s2 insert into OutputStream;"
        )
        rt = manager.create_siddhi_app_runtime(app)
        got = []
        rt.add_callback("OutputStream", lambda evs: got.extend(evs))
        rt.start()
        time.sleep(0.4)
        rt.get_input_handler("Stream2").send(["WSO2", 55.6, 100])
        rt.get_input_handler("Stream2").send(["GOOG", 55.6, 100])
        rt.shutdown()
        assert [e.data[0] for e in got] == ["WSO2", "GOOG"]

    def test_leading_absent_violated(self, manager):
        import time

        app = S12 + (
            "from not Stream1[price>10] for 100 millisec -> every e2=Stream2[price>20] "
            "select e2.symbol as s2 insert into OutputStream;"
        )
        rt = manager.create_siddhi_app_runtime(app)
        got = []
        rt.add_callback("OutputStream", lambda evs: got.extend(evs))
        rt.start()
        rt.get_input_handler("Stream1").send(["KILL", 55.6, 100])
        time.sleep(0.4)
        rt.get_input_handler("Stream2").send(["WSO2", 55.6, 100])
        rt.shutdown()
        assert got == []

    def test_logical_and_not(self, manager):
        # LogicalAbsentPatternTestCase-style: A and not B
        import time

        app = S12 + (
            "from e1=Stream1[price>20] and not Stream2[price>20] for 100 millisec "
            "select e1.symbol as s1 insert into OutputStream;"
        )
        rt = manager.create_siddhi_app_runtime(app)
        got = []
        rt.add_callback("OutputStream", lambda evs: got.extend(evs))
        rt.start()
        rt.get_input_handler("Stream1").send(["WSO2", 55.6, 100])
        time.sleep(0.4)
        rt.shutdown()
        assert [e.data[0] for e in got] == ["WSO2"]
