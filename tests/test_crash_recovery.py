"""Crash/restore differential: checkpoint + journal replay is bit-exact.

``@app:faults(journal='N')`` keeps a bounded input journal (keyed to the
app name on the MANAGER context, so it survives the death of a runtime)
pinned to ``persist()`` revisions.  After a simulated crash
(``SimulatedCrashError`` — deliberately a ``BaseException`` so it tears
through every ``except Exception`` hardening layer, like a real SIGKILL
would), a replacement runtime restores the last revision and replays the
post-checkpoint journal with output dedup: the callback/sink sequence
observed across crash + recovery must be identical to a run that never
crashed.

The differential runs across all three device engines (device-single,
dense NFA, sharded) plus a sink endpoint, and covers the degraded paths:
journal overflow (spilled to the persistence store and replayed when the
store supports segments — durability/spill.py — refused with a surfaced
warning when it does not), restore before start, and raw-bytes restore
invalidating the ledger.  The async persist pipeline gets the same
differential in tests/test_durability.py; here one case pins that
``persist(mode='async')`` recovery is bit-identical to the sync path.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.exceptions import SimulatedCrashError
from siddhi_tpu.util.persistence import InMemoryPersistenceStore

pytestmark = pytest.mark.faults

DEFINE = "define stream S (k long, v double); "

AGG_BODY = DEFINE + ("@info(name='q') from S#window.length(4) "
                     "select k, sum(v) as s group by k "
                     "insert into OutputStream;")
PATTERN_BODY = DEFINE + (
    "@info(name='q') from every e1=S[v > 50.0] -> e2=S[v > e1.v] "
    "within 10 sec select e1.v as a, e2.v as b insert into OutputStream;")

ENGINES = {
    "device_single": ("@app:execution('tpu') ", AGG_BODY),
    "dense_nfa": ("@app:execution('tpu', instances='32') ", PATTERN_BODY),
    "sharded": ("@app:execution('tpu', partitions='16', devices='8') ",
                AGG_BODY),
}


def series(n, seed=11, n_keys=3):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, n_keys, size=n)
    vals = rng.integers(1, 100, size=n).astype(float)
    ts = 1000 + np.arange(n) * 250
    return [([int(k), float(v)], int(t)) for k, v, t in zip(keys, vals, ts)]


def _header(engine, faults=True):
    exec_opts, body = ENGINES[engine]
    h = "@app:name('crashdiff') @app:playback "
    if faults:
        h += "@app:faults(journal='256') "
    return h + exec_opts + body


def reference_run(engine, sends):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(_header(engine, faults=False))
        got = []
        rt.add_callback("OutputStream",
                        lambda evs: got.extend(tuple(e.data) for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        for row, ts in sends:
            h.send(list(row), timestamp=ts)
        rt.shutdown()
        return got
    finally:
        m.shutdown()


def crash_and_recover_run(engine, sends, persist_at, crash_at):
    """Send ``sends[:crash_at]`` with a persist() at ``persist_at``,
    crash on the ingest of ``sends[crash_at]``, then recover in a FRESH
    runtime (same manager: the journal lives on the manager context) and
    finish the stream.  Returns (outputs, recovery_runtime)."""
    assert persist_at <= crash_at
    m = SiddhiManager()
    try:
        m.set_persistence_store(InMemoryPersistenceStore())
        rt = m.create_siddhi_app_runtime(_header(engine))
        got = []
        rt.add_callback("OutputStream",
                        lambda evs: got.extend(tuple(e.data) for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        for row, ts in sends[:persist_at]:
            h.send(list(row), timestamp=ts)
        rt.persist()
        for row, ts in sends[persist_at:crash_at]:
            h.send(list(row), timestamp=ts)
        rt.app_context.fault_injector.configure("ingest", "crash", count=1)
        with pytest.raises(SimulatedCrashError):
            h.send(list(sends[crash_at][0]), timestamp=sends[crash_at][1])
        rt.shutdown()  # the crashed runtime is gone

        rt2 = m.create_siddhi_app_runtime(_header(engine))
        rt2.add_callback("OutputStream",
                         lambda evs: got.extend(tuple(e.data) for e in evs))
        rt2.start()
        assert rt2.restore_last_revision() is not None
        h2 = rt2.get_input_handler("S")
        # the crashed send WAS journaled (crash fires after the record),
        # so replay already delivered it — continue after it
        for row, ts in sends[crash_at + 1:]:
            h2.send(list(row), timestamp=ts)
        rt2.shutdown()
        return got, rt2
    finally:
        m.shutdown()


class TestCrashRecoveryDifferential:
    @pytest.mark.parametrize("engine", sorted(ENGINES))
    def test_recovered_sequence_bit_identical(self, engine):
        sends = series(30)
        ref = reference_run(engine, sends)
        assert len(ref) > 4, "series too tame; differential is vacuous"
        got, rt2 = crash_and_recover_run(engine, sends,
                                         persist_at=10, crash_at=20)
        assert got == ref, (
            f"{engine}: crash+recover diverged from the uninterrupted run")
        jr = rt2.app_context.input_journal
        # sends 10..19 plus the crashed (journaled-but-undelivered) one
        assert jr.stats.replayed_batches == 11
        assert jr.stats.suppressed_events > 0

    @pytest.mark.parametrize("engine", sorted(ENGINES))
    def test_crash_immediately_after_persist(self, engine):
        # only the crashed (journaled-but-undelivered) send to replay;
        # nothing pre-crash needs suppression
        sends = series(24)
        ref = reference_run(engine, sends)
        got, rt2 = crash_and_recover_run(engine, sends,
                                         persist_at=12, crash_at=12)
        assert got == ref
        jr = rt2.app_context.input_journal
        assert jr.stats.replayed_batches == 1
        assert jr.stats.suppressed_events == 0


class TestSinkExactlyOnce:
    def test_sink_publishes_are_deduped_across_recovery(self):
        from siddhi_tpu.transport.broker import (
            FunctionSubscriber,
            InMemoryBroker,
        )

        InMemoryBroker.clear()
        app = ("@app:name('sinkdiff') @app:playback "
               "@app:faults(journal='256') @app:execution('tpu') "
               + DEFINE +
               "@info(name='q') from S[v > 0.0] select k, v "
               "insert into OutputStream; ")
        app += ("@sink(type='inMemory', topic='xo') "
                "define stream OutputStream (k long, v double);")
        published = []
        sub = FunctionSubscriber("xo", lambda e: published.append(
            tuple(e.data)))
        InMemoryBroker.subscribe(sub)
        sends = series(12)
        m = SiddhiManager()
        try:
            m.set_persistence_store(InMemoryPersistenceStore())
            rt = m.create_siddhi_app_runtime(app)
            rt.start()
            h = rt.get_input_handler("S")
            for row, ts in sends[:4]:
                h.send(list(row), timestamp=ts)
            rt.persist()
            for row, ts in sends[4:8]:
                h.send(list(row), timestamp=ts)
            rt.app_context.fault_injector.configure("ingest", "crash",
                                                    count=1)
            with pytest.raises(SimulatedCrashError):
                h.send(list(sends[8][0]), timestamp=sends[8][1])
            rt.shutdown()

            rt2 = m.create_siddhi_app_runtime(app)
            rt2.start()
            rt2.restore_last_revision()
            h2 = rt2.get_input_handler("S")
            for row, ts in sends[9:]:
                h2.send(list(row), timestamp=ts)
            rt2.shutdown()
        finally:
            InMemoryBroker.unsubscribe(sub)
            m.shutdown()
        assert published == [(int(k), float(v)) for (k, v), _ts in sends], (
            "sink published a duplicate or lost an event across recovery")


class TestAsyncPersistRecovery:
    def test_async_persist_recovers_bit_identical(self):
        # same differential as the sync matrix, through persist('async'):
        # the capture + background commit must recover exactly like the
        # blocking write (the full crash-site matrix lives in
        # tests/test_durability.py)
        sends = series(30)
        ref = reference_run("device_single", sends)
        m = SiddhiManager()
        try:
            m.set_persistence_store(InMemoryPersistenceStore())
            rt = m.create_siddhi_app_runtime(_header("device_single"))
            got = []
            rt.add_callback("OutputStream",
                            lambda evs: got.extend(tuple(e.data)
                                                   for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            for row, ts in sends[:10]:
                h.send(list(row), timestamp=ts)
            rev = rt.persist(mode="async")
            assert rt.wait_for_persist(rev, timeout=30) == "committed"
            for row, ts in sends[10:20]:
                h.send(list(row), timestamp=ts)
            rt.app_context.fault_injector.configure("ingest", "crash",
                                                    count=1)
            with pytest.raises(SimulatedCrashError):
                h.send(list(sends[20][0]), timestamp=sends[20][1])
            rt.shutdown()

            rt2 = m.create_siddhi_app_runtime(_header("device_single"))
            rt2.add_callback("OutputStream",
                             lambda evs: got.extend(tuple(e.data)
                                                    for e in evs))
            rt2.start()
            assert rt2.restore_last_revision() == rev
            h2 = rt2.get_input_handler("S")
            for row, ts in sends[21:]:
                h2.send(list(row), timestamp=ts)
            rt2.shutdown()
            assert got == ref
        finally:
            m.shutdown()


class TestDegradedPaths:
    def test_journal_overflow_spills_and_replays(self):
        # a depth-4 journal overflows before the crash: the cold half
        # spills to the persistence store (InMemory stores support
        # journal segments) and recovery stitches spilled + in-memory
        # entries back into a gapless bit-exact replay
        sends = series(20)
        ref = reference_run("device_single", sends)
        m = SiddhiManager()
        try:
            m.set_persistence_store(InMemoryPersistenceStore())
            app = ("@app:name('crashdiff') @app:playback "
                   "@app:faults(journal='4') @app:execution('tpu') "
                   + AGG_BODY)
            rt = m.create_siddhi_app_runtime(app)
            got = []
            rt.add_callback("OutputStream",
                            lambda evs: got.extend(tuple(e.data)
                                                   for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            for row, ts in sends[:4]:
                h.send(list(row), timestamp=ts)
            rt.persist()
            for row, ts in sends[4:16]:  # 12 > depth 4 -> spill
                h.send(list(row), timestamp=ts)
            jr = rt.app_context.input_journal
            assert jr.stats.journal_spills > 0
            assert jr.stats.journal_dropped == 0
            rt.shutdown()

            rt2 = m.create_siddhi_app_runtime(app)
            rt2.add_callback("OutputStream",
                             lambda evs: got.extend(tuple(e.data)
                                                    for e in evs))
            rt2.start()
            assert rt2.restore_last_revision() is not None
            jr2 = rt2.app_context.input_journal
            assert jr2.stats.replayed_spilled_batches > 0
            h2 = rt2.get_input_handler("S")
            for row, ts in sends[16:]:
                h2.send(list(row), timestamp=ts)
            rt2.shutdown()
            assert got == ref, "spilled replay diverged"
        finally:
            m.shutdown()

    def test_journal_overflow_without_segments_refuses_replay(self, caplog):
        # with a store that cannot hold journal segments, overflow still
        # degrades the old way: replay would be gapped, so restore must
        # refuse it (checkpoint-only recovery) and say so — silent
        # divergence is the one forbidden outcome
        import logging

        from siddhi_tpu.util.persistence import PersistenceStore

        class NoSegmentStore(PersistenceStore):
            def __init__(self):
                self._revs = {}

            def save(self, app_name, revision, data):
                self._revs.setdefault(app_name, {})[revision] = data

            def load(self, app_name, revision):
                return self._revs.get(app_name, {}).get(revision)

            def get_last_revision(self, app_name):
                revs = sorted(self._revs.get(app_name, {}))
                return revs[-1] if revs else None

            def revisions(self, app_name):
                return sorted(self._revs.get(app_name, {}))

            def clear_all_revisions(self, app_name):
                self._revs.pop(app_name, None)

        sends = series(20)
        m = SiddhiManager()
        try:
            m.set_persistence_store(NoSegmentStore())
            app = ("@app:name('ovf') @app:playback "
                   "@app:faults(journal='4') @app:execution('tpu') "
                   + AGG_BODY)
            rt = m.create_siddhi_app_runtime(app)
            rt.add_callback("OutputStream", lambda evs: None)
            rt.start()
            h = rt.get_input_handler("S")
            for row, ts in sends[:4]:
                h.send(list(row), timestamp=ts)
            rt.persist()
            for row, ts in sends[4:16]:  # 12 > depth 4 -> gap
                h.send(list(row), timestamp=ts)
            rt.shutdown()

            rt2 = m.create_siddhi_app_runtime(app)
            rt2.add_callback("OutputStream", lambda evs: None)
            rt2.start()
            with caplog.at_level(logging.WARNING, logger="siddhi_tpu"):
                assert rt2.restore_last_revision() is not None
            assert rt2.app_context.input_journal.stats.journal_dropped > 0
            assert any("journal" in r.message for r in caplog.records), (
                "lost-replay condition must be surfaced in the log")
            rt2.shutdown()
        finally:
            m.shutdown()

    def test_raw_bytes_restore_resets_ledger(self):
        # restore(bytes) is positionless — the ledger must not suppress
        # anything afterwards
        m = SiddhiManager()
        try:
            app = ("@app:name('raw') @app:playback "
                   "@app:faults(journal='64') @app:execution('tpu') "
                   + AGG_BODY)
            rt = m.create_siddhi_app_runtime(app)
            got = []
            rt.add_callback("OutputStream",
                            lambda evs: got.extend(tuple(e.data)
                                                   for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            h.send([0, 5.0], timestamp=1000)
            blob = rt.snapshot()
            rt.restore(blob)
            jr = rt.app_context.input_journal
            assert jr._counts == {}  # ledger forgotten
            h.send([0, 7.0], timestamp=2000)
            rt.shutdown()
            assert got == [(0, 5.0), (0, 12.0)]
        finally:
            m.shutdown()
